"""Faster/Mask R-CNN + RetinaNet + FPN detection ops (reference
/root/reference/paddle/fluid/operators/detection/: generate_proposals_op.cc,
rpn_target_assign_op.cc, generate_proposal_labels_op.cc,
generate_mask_labels_op.cc, distribute_fpn_proposals_op.h,
collect_fpn_proposals_op.h, box_decoder_and_assign_op.h,
retinanet_detection_output_op.cc).

TPU design notes: the reference's kernels are CPU loops emitting
dynamically-sized LoD tensors. Here every op is dense/static-shape:
variable-length results come back PADDED with an explicit count (the
multiclass_nms / sequence-op scheme), selection loops become sort-keys +
masks, and greedy NMS is the same fixed-trip fori pattern
detection_ops.py uses. Sampling ops implement the reference's
use_random=False path (first-k in index order) so results are
deterministic and testable; the random path falls back to it
(documented divergence — stateless per-step sampling would need the op
key plumbed per image).
"""
import math

import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import x_of
from .detection_ops import _iou_matrix

_BBOX_CLIP = float(math.log(1000.0 / 16.0))


def _iou_plus1(a, b):
    """Pixel-coordinate IoU with the +1 width convention the R-CNN family
    uses (reference bbox_util.h BboxOverlaps, normalized=false)."""
    area_a = (a[:, 2] - a[:, 0] + 1) * (a[:, 3] - a[:, 1] + 1)
    area_b = (b[:, 2] - b[:, 0] + 1) * (b[:, 3] - b[:, 1] + 1)
    lt = jnp.maximum(a[:, None, :2], b[None, :, :2])
    rb = jnp.minimum(a[:, None, 2:], b[None, :, 2:])
    wh = jnp.maximum(rb - lt + 1, 0)
    inter = wh[..., 0] * wh[..., 1]
    return inter / jnp.maximum(area_a[:, None] + area_b[None, :] - inter,
                               1e-10)


def _box_to_delta(ex, gt, weights=None):
    """reference bbox_util.h BoxToDelta (normalized=false: +1 widths)."""
    ex_w = ex[:, 2] - ex[:, 0] + 1
    ex_h = ex[:, 3] - ex[:, 1] + 1
    ex_cx = ex[:, 0] + 0.5 * ex_w
    ex_cy = ex[:, 1] + 0.5 * ex_h
    gt_w = gt[:, 2] - gt[:, 0] + 1
    gt_h = gt[:, 3] - gt[:, 1] + 1
    gt_cx = gt[:, 0] + 0.5 * gt_w
    gt_cy = gt[:, 1] + 0.5 * gt_h
    d = jnp.stack([(gt_cx - ex_cx) / ex_w, (gt_cy - ex_cy) / ex_h,
                   jnp.log(jnp.maximum(gt_w / ex_w, 1e-10)),
                   jnp.log(jnp.maximum(gt_h / ex_h, 1e-10))], axis=-1)
    if weights is not None:
        d = d / jnp.asarray(weights, d.dtype)
    return d


def _decode_boxes(anchors, deltas, variances=None):
    """reference generate_proposals_op.cc BoxCoder: anchors/deltas [M, 4]
    -> proposals [M, 4] (pixel convention, dw/dh clipped)."""
    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    acx = anchors[:, 0] + 0.5 * aw
    acy = anchors[:, 1] + 0.5 * ah
    if variances is not None:
        dx = variances[:, 0] * deltas[:, 0]
        dy = variances[:, 1] * deltas[:, 1]
        dw = variances[:, 2] * deltas[:, 2]
        dh = variances[:, 3] * deltas[:, 3]
    else:
        dx, dy, dw, dh = (deltas[:, i] for i in range(4))
    cx = dx * aw + acx
    cy = dy * ah + acy
    w = jnp.exp(jnp.minimum(dw, _BBOX_CLIP)) * aw
    h = jnp.exp(jnp.minimum(dh, _BBOX_CLIP)) * ah
    return jnp.stack([cx - w / 2, cy - h / 2,
                      cx + w / 2 - 1, cy + h / 2 - 1], axis=-1)


def _greedy_nms_mask(boxes, order_scores, nms_thresh, eta=1.0,
                     iou_fn=_iou_plus1):
    """Greedy suppression over boxes already sorted by descending score;
    returns the alive mask. eta < 1 shrinks the threshold after each kept
    box while it stays > 0.5 (reference NMS `adaptive_threshold *= eta`)."""
    n = boxes.shape[0]
    iou = iou_fn(boxes, boxes)
    alive = order_scores > -jnp.inf

    def body(i, carry):
        alive, thresh = carry
        sup = jnp.logical_and(alive[i], iou[i] > thresh)
        sup = sup.at[i].set(False)
        later = jnp.arange(n) > i
        alive = jnp.where(jnp.logical_and(sup, later), False, alive)
        thresh = jnp.where(jnp.logical_and(alive[i], thresh > 0.5),
                           thresh * eta, thresh)
        return alive, thresh

    alive, _ = jax.lax.fori_loop(
        0, n, body, (alive, jnp.asarray(nms_thresh, boxes.dtype)))
    return alive


def _first_k_mask(mask, k):
    """Keep the first k True positions (the use_random=False reservoir)."""
    rank = jnp.cumsum(mask.astype(jnp.int32)) - 1
    return mask & (rank < k)


def _compact(values, keep, fill):
    """Stable-compact rows where keep is True to the front; pad with fill.
    Returns (compacted values, count)."""
    n = keep.shape[0]
    order = jnp.argsort(jnp.where(keep, jnp.arange(n), n + jnp.arange(n)))
    taken = jnp.take(values, order, axis=0)
    count = jnp.sum(keep.astype(jnp.int32))
    idx = jnp.arange(n)
    shape = (n,) + (1,) * (taken.ndim - 1)
    return jnp.where(idx.reshape(shape) < count, taken, fill), count


@register_op("generate_proposals", grad=False, infer_shape=False)
def generate_proposals(ctx, ins, attrs):
    """RPN proposal generation (reference generate_proposals_op.cc):
    Scores [N, A, H, W], BboxDeltas [N, 4A, H, W], ImInfo [N, 3],
    Anchors [H, W, A, 4], Variances same. Padded outputs: RpnRois
    [N, post_nms_topN, 4], RpnRoiProbs [N, post_nms_topN, 1], RpnRoisLod
    [N] valid counts (the reference's dispensable lod output)."""
    scores = x_of(ins, "Scores")
    deltas = x_of(ins, "BboxDeltas")
    im_info = x_of(ins, "ImInfo")
    anchors = x_of(ins, "Anchors").reshape(-1, 4)
    variances = x_of(ins, "Variances")
    variances = (variances.reshape(-1, 4)
                 if variances is not None else None)
    pre_n = int(attrs.get("pre_nms_topN", 6000))
    post_n = int(attrs.get("post_nms_topN", 1000))
    nms_thresh = float(attrs.get("nms_thresh", 0.5))
    min_size = max(float(attrs.get("min_size", 0.1)), 1.0)
    eta = float(attrs.get("eta", 1.0))
    N, A, H, W = scores.shape
    M = A * H * W
    pre_n = min(pre_n if pre_n > 0 else M, M)

    def one_image(sc, dl, info):
        # layout: [A, H, W] -> [H, W, A] flattened (kernel's Transpose)
        s = jnp.transpose(sc, (1, 2, 0)).reshape(-1)          # [M]
        d = jnp.transpose(dl.reshape(A, 4, H, W),
                          (2, 3, 0, 1)).reshape(-1, 4)        # [M, 4]
        top_s, top_i = jax.lax.top_k(s, pre_n)
        props = _decode_boxes(anchors[top_i], d[top_i],
                              None if variances is None
                              else variances[top_i])
        # clip to image
        h_im, w_im, scale = info[0], info[1], info[2]
        props = jnp.stack([
            jnp.clip(props[:, 0], 0, w_im - 1),
            jnp.clip(props[:, 1], 0, h_im - 1),
            jnp.clip(props[:, 2], 0, w_im - 1),
            jnp.clip(props[:, 3], 0, h_im - 1)], axis=-1)
        ws = (props[:, 2] - props[:, 0]) / scale + 1
        hs = (props[:, 3] - props[:, 1]) / scale + 1
        cx = props[:, 0] + (props[:, 2] - props[:, 0] + 1) / 2
        cy = props[:, 1] + (props[:, 3] - props[:, 1] + 1) / 2
        keep = ((ws >= min_size) & (hs >= min_size)
                & (cx <= w_im) & (cy <= h_im))
        s_kept = jnp.where(keep, top_s, -jnp.inf)
        # keep-order compaction so NMS sees score-descending valid boxes
        order = jnp.argsort(-s_kept)
        props = props[order]
        s_kept = s_kept[order]
        alive = _greedy_nms_mask(props, s_kept, nms_thresh, eta)
        alive = _first_k_mask(alive, post_n)
        rois, cnt = _compact(props, alive, 0.0)
        probs, _ = _compact(s_kept, alive, 0.0)
        return (rois[:post_n], probs[:post_n, None],
                jnp.minimum(cnt, post_n))

    rois, probs, counts = jax.vmap(one_image)(scores, deltas, im_info)
    return {"RpnRois": rois, "RpnRoiProbs": probs,
            "RpnRoisLod": counts.astype(jnp.int32)}


def _gt_valid_mask(ins, B, G):
    """Valid (non-pad, non-crowd) gt mask from optional GtCount/IsCrowd."""
    valid = jnp.ones((B, G), bool)
    cnt = ins.get("GtCount")
    if cnt:
        counts = jnp.reshape(cnt[0], (-1,)).astype(jnp.int32)
        valid = valid & (jnp.arange(G)[None, :] < counts[:, None])
    crowd = ins.get("IsCrowd")
    if crowd:
        valid = valid & (jnp.reshape(crowd[0], (B, G)) == 0)
    return valid


@register_op("rpn_target_assign", grad=False, infer_shape=False)
def rpn_target_assign(ctx, ins, attrs):
    """RPN anchor labeling (reference rpn_target_assign_op.cc). Inputs:
    Anchor [A, 4]; GtBoxes [B, G, 4] padded (+ optional GtCount [B],
    IsCrowd [B, G]); ImInfo [B, 3]. S = rpn_batch_size_per_im. Padded
    outputs per image: LocationIndex [B, S] (-1 pad) + LocCount [B],
    ScoreIndex [B, S] + ScoreCount [B], TargetLabel [B, S, 1] aligned
    with ScoreIndex, TargetBBox [B, S, 4] + BBoxInsideWeight [B, S, 4]
    aligned with LocationIndex. use_random=False semantics (first-k)."""
    anchors = x_of(ins, "Anchor")
    gt = x_of(ins, "GtBoxes")
    im_info = x_of(ins, "ImInfo")
    S = int(attrs.get("rpn_batch_size_per_im", 256))
    straddle = float(attrs.get("rpn_straddle_thresh", 0.0))
    pos_ov = float(attrs.get("rpn_positive_overlap", 0.7))
    neg_ov = float(attrs.get("rpn_negative_overlap", 0.3))
    fg_frac = float(attrs.get("rpn_fg_fraction", 0.5))
    A = anchors.shape[0]
    B, G = gt.shape[0], gt.shape[1]
    gt_valid = _gt_valid_mask(ins, B, G)
    fg_cap = int(fg_frac * S) if fg_frac > 0 and S > 0 else A

    def one_image(gt_b, valid_b, info):
        if straddle >= 0:
            inside = ((anchors[:, 0] >= -straddle)
                      & (anchors[:, 1] >= -straddle)
                      & (anchors[:, 2] < info[1] + straddle)
                      & (anchors[:, 3] < info[0] + straddle))
        else:
            inside = jnp.ones((A,), bool)
        iou = _iou_plus1(anchors, gt_b)                      # [A, G]
        iou = jnp.where(valid_b[None, :], iou, -1.0)
        iou = jnp.where(inside[:, None], iou, -1.0)
        a2g_max = jnp.max(iou, axis=1)
        a2g_arg = jnp.argmax(iou, axis=1)
        g2a_max = jnp.max(iou, axis=0)                       # [G]
        is_best = jnp.any(
            (jnp.abs(iou - g2a_max[None, :]) < 1e-5) & valid_b[None, :]
            & (iou >= 0), axis=1)
        elig_fg = inside & (is_best | (a2g_max >= pos_ov))
        fg_sel = _first_k_mask(elig_fg, fg_cap)
        n_fg_sel = jnp.sum(fg_sel.astype(jnp.int32))
        elig_bg = inside & (a2g_max < neg_ov)
        bg_sel = _first_k_mask(elig_bg, S - n_fg_sel)
        fake = bg_sel & fg_sel          # demoted to bg, fake loc entry
        real_fg = fg_sel & ~bg_sel
        first_fg = jnp.argmax(fg_sel)   # fg_inds_fake[0]

        # loc entries: fakes (index = first fg) first, then real fgs —
        # the reference's emplace order
        loc_idx_fake = jnp.where(fake, first_fg, -1)
        fake_rows, n_fake = _compact(loc_idx_fake, fake, -1)
        real_rows, n_real = _compact(jnp.arange(A), real_fg, -1)
        loc_idx = jnp.where(
            jnp.arange(A) < n_fake, fake_rows,
            jnp.take(real_rows,
                     jnp.maximum(jnp.arange(A) - n_fake, 0), axis=0))
        n_loc = n_fake + n_real
        loc_idx = jnp.where(jnp.arange(A) < n_loc, loc_idx, -1)[:S]
        safe_loc = jnp.maximum(loc_idx, 0)
        tgt_gt = jnp.take(a2g_arg, safe_loc)
        tgt_bbox = _box_to_delta(anchors[safe_loc],
                                 gt_b[jnp.maximum(tgt_gt, 0)])
        live = (jnp.arange(S) < n_loc)
        tgt_bbox = jnp.where(live[:, None], tgt_bbox, 0.0)
        inw = jnp.where(
            (jnp.arange(S) < n_fake)[:, None], 0.0,
            jnp.where(live[:, None], 1.0, 0.0))

        # score entries: real fgs then bgs
        fg_rows, n_f = _compact(jnp.arange(A), real_fg, -1)
        bg_rows, n_b = _compact(jnp.arange(A), bg_sel, -1)
        sc_idx = jnp.where(
            jnp.arange(A) < n_f, fg_rows,
            jnp.take(bg_rows, jnp.maximum(jnp.arange(A) - n_f, 0),
                     axis=0))
        n_sc = n_f + n_b
        sc_idx = jnp.where(jnp.arange(A) < n_sc, sc_idx, -1)[:S]
        lbl = jnp.where(jnp.arange(S) < n_f, 1,
                        jnp.where(jnp.arange(S) < n_sc, 0, -1))
        return (loc_idx.astype(jnp.int32), jnp.minimum(n_loc, S),
                sc_idx.astype(jnp.int32), jnp.minimum(n_sc, S),
                lbl.astype(jnp.int32)[:, None], tgt_bbox, inw)

    (loc, locn, sci, scn, lbl, tb, inw) = jax.vmap(one_image)(
        gt, gt_valid, im_info)
    return {"LocationIndex": loc, "LocCount": locn,
            "ScoreIndex": sci, "ScoreCount": scn,
            "TargetLabel": lbl, "TargetBBox": tb,
            "BBoxInsideWeight": inw}


@register_op("retinanet_target_assign", grad=False, infer_shape=False)
def retinanet_target_assign(ctx, ins, attrs):
    """RetinaNet anchor labeling (reference rpn_target_assign_op.cc
    RetinanetTargetAssignOp): like rpn_target_assign but no subsampling,
    fg label comes from GtLabels, and ForegroundNumber is emitted.
    Outputs padded to A anchors per image."""
    anchors = x_of(ins, "Anchor")
    gt = x_of(ins, "GtBoxes")
    gt_labels = x_of(ins, "GtLabels")
    im_info = x_of(ins, "ImInfo")
    pos_ov = float(attrs.get("positive_overlap", 0.5))
    neg_ov = float(attrs.get("negative_overlap", 0.4))
    A = anchors.shape[0]
    B, G = gt.shape[0], gt.shape[1]
    gt_valid = _gt_valid_mask(ins, B, G)
    gt_labels = gt_labels.reshape(B, G)

    def one_image(gt_b, glbl, valid_b, info):
        iou = _iou_plus1(anchors, gt_b)
        iou = jnp.where(valid_b[None, :], iou, -1.0)
        a2g_max = jnp.max(iou, axis=1)
        a2g_arg = jnp.argmax(iou, axis=1)
        g2a_max = jnp.max(iou, axis=0)
        is_best = jnp.any(
            (jnp.abs(iou - g2a_max[None, :]) < 1e-5) & valid_b[None, :]
            & (iou >= 0), axis=1)
        fg = is_best | (a2g_max >= pos_ov)
        bg = ~fg & (a2g_max < neg_ov) & (a2g_max >= 0)
        loc_idx, n_loc = _compact(jnp.arange(A), fg, -1)
        sel = fg | bg
        sc_idx, n_sc = _compact(jnp.arange(A), sel, -1)
        lbl_all = jnp.where(fg, jnp.take(glbl, a2g_arg), 0)
        lbl, _ = _compact(lbl_all, sel, -1)
        tgt = _box_to_delta(anchors[jnp.maximum(loc_idx, 0)],
                            gt_b[a2g_arg[jnp.maximum(loc_idx, 0)]])
        live = (jnp.arange(A) < n_loc)[:, None]
        return (loc_idx.astype(jnp.int32), n_loc,
                sc_idx.astype(jnp.int32), n_sc,
                lbl.astype(jnp.int32)[:, None],
                jnp.where(live, tgt, 0.0),
                jnp.where(live, 1.0, 0.0) * jnp.ones((A, 4)),
                n_loc.astype(jnp.int32).reshape(1))

    (loc, locn, sci, scn, lbl, tb, inw, fgn) = jax.vmap(one_image)(
        gt, gt_labels, gt_valid, im_info)
    return {"LocationIndex": loc, "LocCount": locn,
            "ScoreIndex": sci, "ScoreCount": scn,
            "TargetLabel": lbl, "TargetBBox": tb,
            "BBoxInsideWeight": inw, "ForegroundNumber": fgn}


@register_op("generate_proposal_labels", grad=False, infer_shape=False)
def generate_proposal_labels(ctx, ins, attrs):
    """Sample RoIs for the bbox head (reference
    generate_proposal_labels_op.cc SampleRoisForOneImage,
    use_random=False). Inputs: RpnRois [B, R, 4] (+ RpnRoisLod [B]),
    GtClasses [B, G], IsCrowd [B, G], GtBoxes [B, G, 4], ImInfo [B, 3]
    (+ GtCount [B]). S = batch_size_per_im. Outputs padded per image:
    Rois [B, S, 4], LabelsInt32 [B, S, 1], BboxTargets [B, S, 4C],
    BboxInsideWeights / BboxOutsideWeights [B, S, 4C], RoisNum [B]."""
    rois_in = x_of(ins, "RpnRois")
    gt_classes = x_of(ins, "GtClasses")
    gt_boxes = x_of(ins, "GtBoxes")
    im_info = x_of(ins, "ImInfo")
    S = int(attrs.get("batch_size_per_im", 256))
    fg_frac = float(attrs.get("fg_fraction", 0.25))
    fg_thresh = float(attrs.get("fg_thresh", 0.5))
    bg_hi = float(attrs.get("bg_thresh_hi", 0.5))
    bg_lo = float(attrs.get("bg_thresh_lo", 0.0))
    weights = [float(w) for w in attrs.get("bbox_reg_weights",
                                           [0.1, 0.1, 0.2, 0.2])]
    C = int(attrs["class_nums"])
    B, R = rois_in.shape[0], rois_in.shape[1]
    G = gt_boxes.shape[1]
    gt_valid = _gt_valid_mask(ins, B, G)
    gt_classes = gt_classes.reshape(B, G)
    roi_cnt = ins.get("RpnRoisLod")
    roi_valid = jnp.ones((B, R), bool)
    if roi_cnt:
        counts = jnp.reshape(roi_cnt[0], (-1,)).astype(jnp.int32)
        roi_valid = jnp.arange(R)[None, :] < counts[:, None]
    fg_cap = int(round(fg_frac * S))

    def one_image(rois_b, rvalid, gt_b, gcls, gvalid, info):
        # reference SampleRoisForOneImage: rois arrive in scaled-image
        # coords; divide by im_scale so they match the gt boxes before
        # appending the gts themselves as candidates
        scale = info[2]
        rois_b = rois_b / scale
        cand = jnp.concatenate([rois_b, gt_b], axis=0)       # [R+G, 4]
        cvalid = jnp.concatenate([rvalid, gvalid], axis=0)
        iou = _iou_plus1(cand, gt_b)
        iou = jnp.where(gvalid[None, :], iou, -1.0)
        iou = jnp.where(cvalid[:, None], iou, -1.0)
        max_ov = jnp.max(iou, axis=1)
        argmax_ov = jnp.argmax(iou, axis=1)
        fg = cvalid & (max_ov >= fg_thresh)
        fg_sel = _first_k_mask(fg, fg_cap)
        n_fg = jnp.sum(fg_sel.astype(jnp.int32))
        bg = cvalid & (max_ov < bg_hi) & (max_ov >= bg_lo)
        bg_sel = _first_k_mask(bg, S - n_fg)
        n_bg = jnp.sum(bg_sel.astype(jnp.int32))

        n = cand.shape[0]
        fg_rows, _ = _compact(jnp.arange(n), fg_sel, 0)
        bg_rows, _ = _compact(jnp.arange(n), bg_sel, 0)
        pick = jnp.where(jnp.arange(n) < n_fg, fg_rows,
                         jnp.take(bg_rows,
                                  jnp.maximum(jnp.arange(n) - n_fg, 0)))
        pick = pick[:S]
        n_tot = jnp.minimum(n_fg + n_bg, S)
        live = jnp.arange(S) < n_tot
        is_fg = jnp.arange(S) < n_fg
        sel_rois = jnp.where(live[:, None], cand[pick], 0.0)
        sel_gt = argmax_ov[pick]
        labels = jnp.where(is_fg, jnp.take(gcls, sel_gt), 0)
        labels = jnp.where(live, labels, -1)
        deltas = _box_to_delta(sel_rois, gt_b[sel_gt], weights)
        # per-class expansion: write deltas into the label's 4-col slot
        cls = jnp.maximum(labels, 0)
        onehot = jax.nn.one_hot(cls, C, dtype=deltas.dtype)  # [S, C]
        wmask = onehot[:, :, None] * is_fg[:, None, None]    # [S, C, 1]
        tgt = (wmask * deltas[:, None, :]).reshape(S, 4 * C)
        inw = jnp.broadcast_to(wmask, (S, C, 4)).reshape(S, 4 * C)
        return (sel_rois, labels.astype(jnp.int32)[:, None],
                tgt, inw, inw, n_tot.astype(jnp.int32))

    (rois, lbl, tgt, inw, outw, num) = jax.vmap(one_image)(
        rois_in, roi_valid, gt_boxes, gt_classes, gt_valid, im_info)
    return {"Rois": rois, "LabelsInt32": lbl, "BboxTargets": tgt,
            "BboxInsideWeights": inw, "BboxOutsideWeights": outw,
            "RoisNum": num}


@register_op("generate_mask_labels", grad=False, infer_shape=False)
def generate_mask_labels(ctx, ins, attrs):
    """Mask head targets (reference generate_mask_labels_op.cc). Inputs:
    Rois [B, S, 4] + LabelsInt32 [B, S, 1] (the generate_proposal_labels
    outputs), GtClasses [B, G], GtSegms [B, G, P, 2] polygon vertices
    (+ GtSegmLens [B, G] valid vertex counts, GtCount [B]), ImInfo.
    M = resolution. Outputs: MaskRois [B, S, 4], RoiHasMaskInt32
    [B, S, 1], MaskInt32 [B, S, C*M*M] (-1 outside the roi's class
    slot, Detectron convention), MaskNum [B].

    Divergence (documented): the reference rasterizes COCO polygons via
    its own polygon utils on the host; here each gt carries ONE polygon
    rasterized on-device by an even-odd point-in-polygon test over the
    M x M grid of roi-local pixel centers."""
    rois = x_of(ins, "Rois")
    labels = x_of(ins, "LabelsInt32")
    gt_segms = x_of(ins, "GtSegms")
    M = int(attrs["resolution"])
    C = int(attrs["num_classes"])
    B, S = rois.shape[0], rois.shape[1]
    G, P = gt_segms.shape[1], gt_segms.shape[2]
    labels = labels.reshape(B, S)
    seg_lens = ins.get("GtSegmLens")
    if seg_lens:
        seg_len = jnp.reshape(seg_lens[0], (B, G)).astype(jnp.int32)
    else:
        seg_len = jnp.full((B, G), P, jnp.int32)
    gt_classes = x_of(ins, "GtClasses").reshape(B, G)
    gt_valid = _gt_valid_mask(ins, B, G)

    def poly_bbox(poly, n_pts):
        big = 1e30
        msk = jnp.arange(P) < n_pts
        xs = jnp.where(msk, poly[:, 0], big)
        ys = jnp.where(msk, poly[:, 1], big)
        x0, y0 = jnp.min(xs), jnp.min(ys)
        xs = jnp.where(msk, poly[:, 0], -big)
        ys = jnp.where(msk, poly[:, 1], -big)
        return jnp.stack([x0, y0, jnp.max(xs), jnp.max(ys)])

    def rasterize(poly, n_pts, roi):
        # pixel centers of the M x M grid inside the roi
        x0, y0, x1, y1 = roi[0], roi[1], roi[2], roi[3]
        w = jnp.maximum(x1 - x0, 1e-3)
        h = jnp.maximum(y1 - y0, 1e-3)
        gx = x0 + (jnp.arange(M) + 0.5) / M * w
        gy = y0 + (jnp.arange(M) + 0.5) / M * h
        px, py = jnp.meshgrid(gx, gy)                       # [M, M]
        # even-odd rule over the polygon's valid edges
        idx = jnp.arange(P)
        nxt = jnp.where(idx + 1 < n_pts, idx + 1, 0)
        xi, yi = poly[:, 0], poly[:, 1]
        xj, yj = poly[nxt, 0], poly[nxt, 1]
        valid_e = idx < n_pts
        yi_ = yi[:, None, None]
        yj_ = yj[:, None, None]
        xi_ = xi[:, None, None]
        xj_ = xj[:, None, None]
        cond = (yi_ > py[None]) != (yj_ > py[None])
        xcross = xi_ + (py[None] - yi_) / jnp.where(
            jnp.abs(yj_ - yi_) < 1e-12, 1e-12, yj_ - yi_) * (xj_ - xi_)
        hit = cond & (px[None] < xcross) & valid_e[:, None, None]
        return (jnp.sum(hit.astype(jnp.int32), axis=0) % 2) == 1

    def one_image(rois_b, lbl_b, segs, slens, gcls, gvalid):
        gt_bb = jax.vmap(poly_bbox)(segs, slens)            # [G, 4]
        has = lbl_b > 0

        def one_roi(roi, lab):
            iou = _iou_plus1(roi[None, :], gt_bb)[0]
            iou = jnp.where(gvalid, iou, -1.0)
            g = jnp.argmax(iou)
            mask = rasterize(segs[g], slens[g], roi)        # [M, M]
            cls_slot = jax.nn.one_hot(jnp.maximum(lab, 0), C,
                                      dtype=jnp.int32)
            flat = mask.astype(jnp.int32).reshape(-1)       # [M*M]
            out = jnp.where(cls_slot[:, None] > 0, flat[None, :], -1)
            return out.reshape(-1)                          # [C*M*M]

        masks = jax.vmap(one_roi)(rois_b, lbl_b)
        masks = jnp.where(has[:, None], masks, -1)
        return (rois_b, has.astype(jnp.int32)[:, None], masks,
                jnp.sum(has.astype(jnp.int32)))

    mr, hm, mi, num = jax.vmap(one_image)(
        rois, labels, gt_segms, seg_len, gt_classes, gt_valid)
    return {"MaskRois": mr, "RoiHasMaskInt32": hm, "MaskInt32": mi,
            "MaskNum": num}


@register_op("distribute_fpn_proposals", grad=False, infer_shape=False)
def distribute_fpn_proposals(ctx, ins, attrs):
    """Route RoIs to FPN levels (reference
    distribute_fpn_proposals_op.h): level = floor(log2(sqrt(area) /
    refer_scale + 1e-6)) + refer_level, clipped. FpnRois [B, R, 4]
    (+ RoisNum [B]) -> per level: MultiFpnRois[l] [B, R, 4] padded +
    MultiLevelRoisNum[l] [B]; RestoreIndex [B, R, 1] maps each
    original roi to its (level-major) position."""
    rois = x_of(ins, "FpnRois")
    min_l = int(attrs["min_level"])
    max_l = int(attrs["max_level"])
    refer_l = int(attrs["refer_level"])
    refer_s = int(attrs["refer_scale"])
    n_level = max_l - min_l + 1
    B, R = rois.shape[0], rois.shape[1]
    cnt = ins.get("RoisNum")
    valid = jnp.ones((B, R), bool)
    if cnt:
        counts = jnp.reshape(cnt[0], (-1,)).astype(jnp.int32)
        valid = jnp.arange(R)[None, :] < counts[:, None]

    def one_image(rois_b, valid_b):
        w = rois_b[:, 2] - rois_b[:, 0]
        h = rois_b[:, 3] - rois_b[:, 1]
        bad = (w < 0) | (h < 0)
        area = jnp.where(bad, 0.0, (w + 1) * (h + 1))
        scale = jnp.sqrt(area)
        lvl = jnp.floor(jnp.log2(scale / refer_s + 1e-6)) + refer_l
        lvl = jnp.clip(lvl, min_l, max_l).astype(jnp.int32)
        lvl = jnp.where(valid_b, lvl, max_l + 1)            # pad -> none
        outs, counts, pos_in_level = [], [], []
        base = jnp.zeros((), jnp.int32)
        for li, level in enumerate(range(min_l, max_l + 1)):
            m = lvl == level
            o, c = _compact(rois_b, m, 0.0)
            outs.append(o)
            counts.append(c)
            rank = jnp.cumsum(m.astype(jnp.int32)) - 1
            pos_in_level.append(jnp.where(m, base + rank, -1))
            base = base + c
        # RestoreIndex[orig] = the roi's position in the level-major
        # concatenation (reference: restore_index_data[orig] = concat pos)
        pos = jnp.stack(pos_in_level).max(axis=0)           # [R]
        return outs, counts, pos.astype(jnp.int32)[:, None]

    outs, counts, restore = jax.vmap(one_image)(rois, valid)
    return {"RestoreIndex": restore,
            "MultiFpnRois": list(outs),
            "MultiLevelRoisNum": [c.astype(jnp.int32) for c in counts]}


@register_op("collect_fpn_proposals", grad=False, infer_shape=False)
def collect_fpn_proposals(ctx, ins, attrs):
    """Merge per-level RoIs back, keeping the top post_nms_topN by score
    (reference collect_fpn_proposals_op.h). Inputs MultiLevelRois
    (multi-slot) [B, Rl, 4] and MultiLevelScores [B, Rl] (+ optional
    per-level counts MultiLevelRoisNum). Output FpnRois [B, topN, 4] +
    RoisNum [B]. Divergence: the reference applies one global topN over
    the whole batch; the padded form keeps topN PER IMAGE."""
    rois_list = [jnp.asarray(v) for v in ins["MultiLevelRois"]]
    score_list = [jnp.asarray(v) for v in ins["MultiLevelScores"]]
    topn = int(attrs.get("post_nms_topN", 100))
    B = rois_list[0].shape[0]
    cnts = ins.get("MultiLevelRoisNum")
    valids = []
    for li, r in enumerate(rois_list):
        R = r.shape[1]
        if cnts:
            c = jnp.reshape(cnts[li], (-1,)).astype(jnp.int32)
            valids.append(jnp.arange(R)[None, :] < c[:, None])
        else:
            valids.append(jnp.ones((B, R), bool))
    rois = jnp.concatenate(rois_list, axis=1)
    scores = jnp.concatenate(
        [s.reshape(B, -1) for s in score_list], axis=1)
    valid = jnp.concatenate(valids, axis=1)
    scores = jnp.where(valid, scores, -jnp.inf)
    k = min(topn, scores.shape[1])
    top_s, top_i = jax.lax.top_k(scores, k)
    sel = jnp.take_along_axis(rois, top_i[:, :, None], axis=1)
    n_valid = jnp.sum((top_s > -jnp.inf).astype(jnp.int32), axis=1)
    live = jnp.arange(k)[None, :] < n_valid[:, None]
    return {"FpnRois": jnp.where(live[:, :, None], sel, 0.0),
            "RoisNum": n_valid}


@register_op("box_decoder_and_assign", grad=False, infer_shape=False)
def box_decoder_and_assign(ctx, ins, attrs):
    """reference box_decoder_and_assign_op.h: decode per-class deltas
    against prior boxes, then pick each roi's best non-background class
    box. PriorBox [M, 4], PriorBoxVar [4], TargetBox [M, 4C],
    BoxScore [M, C] -> DecodeBox [M, 4C], OutputAssignBox [M, 4]."""
    prior = x_of(ins, "PriorBox")
    pvar = jnp.reshape(x_of(ins, "PriorBoxVar"), (-1,))[:4]
    tbox = x_of(ins, "TargetBox")
    score = x_of(ins, "BoxScore")
    clip = float(attrs.get("box_clip", _BBOX_CLIP))
    M, C = score.shape
    pw = prior[:, 2] - prior[:, 0] + 1
    ph = prior[:, 3] - prior[:, 1] + 1
    pcx = prior[:, 0] + pw / 2
    pcy = prior[:, 1] + ph / 2
    d = tbox.reshape(M, C, 4)
    dw = jnp.minimum(pvar[2] * d[:, :, 2], clip)
    dh = jnp.minimum(pvar[3] * d[:, :, 3], clip)
    cx = pvar[0] * d[:, :, 0] * pw[:, None] + pcx[:, None]
    cy = pvar[1] * d[:, :, 1] * ph[:, None] + pcy[:, None]
    w = jnp.exp(dw) * pw[:, None]
    h = jnp.exp(dh) * ph[:, None]
    decoded = jnp.stack([cx - w / 2, cy - h / 2,
                         cx + w / 2 - 1, cy + h / 2 - 1], axis=-1)
    # best non-background class (j > 0)
    sc = score.at[:, 0].set(-jnp.inf) if C > 0 else score
    best = jnp.argmax(sc, axis=1)
    has_fg = jnp.max(sc, axis=1) > -jnp.inf
    assign = jnp.take_along_axis(
        decoded, best[:, None, None].repeat(4, 2), axis=1)[:, 0]
    assign = jnp.where(has_fg[:, None] & (best > 0)[:, None],
                       assign, prior[:, :4])
    return {"DecodeBox": decoded.reshape(M, C * 4),
            "OutputAssignBox": assign}


@register_op("retinanet_detection_output", grad=False, infer_shape=False)
def retinanet_detection_output(ctx, ins, attrs):
    """reference retinanet_detection_output_op.cc: per FPN level decode +
    threshold + top-k, merge levels, per-class NMS. Multi-slot inputs:
    BBoxes[l] [B, Al, 4] deltas, Scores[l] [B, Al, C], Anchors[l]
    [Al, 4]; ImInfo [B, 3]. Out [B, keep_top_k, 6] padded
    (class, score, box) + NmsRoisNum [B]."""
    bbox_list = [jnp.asarray(v) for v in ins["BBoxes"]]
    score_list = [jnp.asarray(v) for v in ins["Scores"]]
    anchor_list = [jnp.asarray(v) for v in ins["Anchors"]]
    im_info = x_of(ins, "ImInfo")
    score_thresh = float(attrs.get("score_threshold", 0.05))
    nms_top_k = int(attrs.get("nms_top_k", 100))
    keep_top_k = int(attrs.get("keep_top_k", 100))
    nms_thresh = float(attrs.get("nms_threshold", 0.3))
    B = bbox_list[0].shape[0]
    C = score_list[0].shape[-1]

    def one_image(args):
        deltas, scores, info = args
        lvl_boxes, lvl_scores = [], []
        for dl, sc, an in zip(deltas, scores, anchor_list):
            k = min(nms_top_k, sc.shape[0] * C)
            top_s, top_i = jax.lax.top_k(sc.reshape(-1), k)
            a_idx = top_i // C
            c_idx = top_i % C
            boxes = _decode_boxes(an[a_idx], dl[a_idx])
            boxes = jnp.stack([
                jnp.clip(boxes[:, 0], 0, info[1] / info[2] - 1),
                jnp.clip(boxes[:, 1], 0, info[0] / info[2] - 1),
                jnp.clip(boxes[:, 2], 0, info[1] / info[2] - 1),
                jnp.clip(boxes[:, 3], 0, info[0] / info[2] - 1)],
                axis=-1)
            keep = top_s > score_thresh
            lvl_boxes.append(jnp.where(keep[:, None], boxes, 0.0))
            lvl_scores.append(
                jnp.stack([jnp.where(keep, top_s, -jnp.inf),
                           c_idx.astype(jnp.float32)], axis=-1))
        allb = jnp.concatenate(lvl_boxes, axis=0)
        alls = jnp.concatenate(lvl_scores, axis=0)
        # per-class greedy NMS over the merged set
        n = allb.shape[0]
        order = jnp.argsort(-alls[:, 0])
        allb, alls = allb[order], alls[order]
        iou = _iou_plus1(allb, allb)
        same_cls = alls[:, 1][None, :] == alls[:, 1][:, None]
        alive = alls[:, 0] > -jnp.inf

        def body(i, alive):
            sup = alive[i] & (iou[i] > nms_thresh) & same_cls[i]
            sup = sup.at[i].set(False)
            later = jnp.arange(n) > i
            return jnp.where(sup & later, False, alive)

        alive = jax.lax.fori_loop(0, n, body, alive)
        k = min(keep_top_k, n)
        fin_s = jnp.where(alive, alls[:, 0], -jnp.inf)
        top_s, top_i = jax.lax.top_k(fin_s, k)
        valid = top_s > -jnp.inf
        rows = jnp.concatenate([
            jnp.where(valid, alls[top_i, 1], -1.0)[:, None],
            jnp.where(valid, top_s, 0.0)[:, None],
            jnp.where(valid[:, None], allb[top_i], 0.0)], axis=1)
        return rows, jnp.sum(valid.astype(jnp.int32))

    def wrapped(deltas_tuple, scores_tuple, info):
        return one_image((list(deltas_tuple), list(scores_tuple), info))

    rows, counts = jax.vmap(wrapped)(
        tuple(bbox_list), tuple(score_list), im_info)
    return {"Out": rows, "NmsRoisNum": counts}
