"""Incremental-decoding ops: the KV-cache fast path for autoregressive
LMs (models/gpt.py generate(), serving decode batching).

The reference generates with beam_search/sampling_id over FULL forward
passes — every new token recomputes all S positions, O(S^2) attention
per token. These ops implement the standard prefill/decode split from
the LLM-serving literature (Orca iteration-level scheduling; vLLM's
cache-centric serving): each decoder layer keeps a preallocated
``[B, H, max_len, D]`` key/value cache, new tokens append via a
position-indexed ``lax.dynamic_update_slice`` (vmapped so every row of
the batch can sit at a DIFFERENT position — the decode batch shares one
executable), and causal masking is driven by the per-row position
counters instead of the query/key index triangle. Per-token cost drops
from a full O(S^2) recompute to one O(S) cache-append + cache-wide
attention read, which is bandwidth-bound — the difference between a
demo and a servable LM.
"""
import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import x_of

_NEG_INF = -1e30   # additive mask value; -inf breaks softmax on all-masked rows


@register_op("kv_cache_write", grad=False, infer_shape=False)
def kv_cache_write(ctx, ins, attrs):
    """Append S new key/value vectors into a preallocated cache at each
    row's own position. Cache [B, H, L, D], KV [B, H, S, D], Pos [B]
    int32 -> Out [B, H, L, D] with Out[b, :, pos[b]:pos[b]+S, :] = KV[b].

    ``dynamic_update_slice`` clamps the start index to [0, L-S], so an
    (invalid) overflowing position writes at the end instead of OOB —
    callers enforce position < max_len host-side.
    """
    cache = x_of(ins, "Cache")
    kv = x_of(ins, "KV")
    pos = x_of(ins, "Pos")

    def row(c, u, p):
        z = jnp.int32(0)
        return jax.lax.dynamic_update_slice(
            c, u.astype(c.dtype), (z, p.astype(jnp.int32), z))

    return {"Out": jax.vmap(row)(cache, kv, pos)}


@register_op("kv_cached_attention", grad=False, infer_shape=False)
def kv_cached_attention(ctx, ins, attrs):
    """Causal attention of S fresh queries over a KV cache, masked by
    per-row position counters. Q [B, H, S, D]; K/V caches [B, H, L, D];
    Pos [B] int32 (absolute position of the FIRST query token, i.e. the
    cache index its k/v was just written to). Key slot j is visible to
    query i iff j <= pos[b] + i — rows at different positions share one
    executable, and stale/garbage cache entries beyond a row's position
    are never attended.

    Scores/softmax accumulate in float32 (flash-kernel convention);
    the output is cast back to Q's dtype. Decode (S=1) is a cache-wide
    read per token: bandwidth-bound by design.
    """
    q = x_of(ins, "Q")
    k = x_of(ins, "K")
    v = x_of(ins, "V")
    pos = x_of(ins, "Pos").astype(jnp.int32)
    scale = float(attrs.get("scale", 0.0)) or float(q.shape[-1]) ** -0.5

    scores = jnp.einsum("bhsd,bhld->bhsl", q.astype(jnp.float32),
                        k.astype(jnp.float32)) * scale
    L = k.shape[2]
    S = q.shape[2]
    key_idx = jnp.arange(L, dtype=jnp.int32)[None, None, :]     # [1,1,L]
    qry_pos = pos[:, None, None] + jnp.arange(S, dtype=jnp.int32)[None, :,
                                                                  None]
    mask = key_idx <= qry_pos                                    # [B,S,L]
    scores = jnp.where(mask[:, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhsl,bhld->bhsd", probs, v.astype(jnp.float32))
    return {"Out": out.astype(q.dtype)}


@register_op("paged_kv_cache_write", grad=False, infer_shape=False)
def paged_kv_cache_write(ctx, ins, attrs):
    """Append S new k/v vectors into a BLOCK-PAGED pool at each row's
    own position. Cache [N, H, bs, D] (the shared pool), KV
    [B, H, S, D], Tables [B, nblk] int32 (per-row block table), Pos [B]
    int32 -> Out: pool with row b's vector i written at
    ``(Tables[b, (Pos[b]+i)//bs], :, (Pos[b]+i)%bs)``. The optional
    Limit input [B] int32 marks how many of the S vectors are REAL per
    row (chunked prefill's ragged tail): positions at/past the limit
    are routed to the reserved trash block 0 instead. With an int8 pool
    the op quantizes (kernels/paged_attention.quantize_kv) and the
    optional Scale input [N, H, bs] is updated too (second output
    OutScale).

    One scatter covers the batch: slots own disjoint blocks and COW
    guarantees a written block has refcount 1, so the valid
    (block, offset) pairs are unique; rows whose table entry is the
    trash block (free serving slots / past-limit padding) write garbage
    nobody reads.
    """
    from ..kernels.paged_attention import quantize_kv

    pool = x_of(ins, "Cache")
    kv = x_of(ins, "KV")
    tables = x_of(ins, "Tables").astype(jnp.int32)
    pos = x_of(ins, "Pos").astype(jnp.int32)
    bs = pool.shape[2]
    B = kv.shape[0]
    S = kv.shape[2]
    limit = ins.get("Limit")

    outs = {}
    if S == 1 and not limit:
        # single-token decode fast path (bitwise-identical to the
        # original op)
        block_ids = tables[jnp.arange(B), pos // bs]        # [B]
        offs = pos % bs                                     # [B]
        vec = kv[:, :, 0, :]                                # [B, H, D]
        if pool.dtype == jnp.int8:
            q, sc = quantize_kv(vec)
            outs["Out"] = pool.at[block_ids, :, offs, :].set(q)
            scale = x_of(ins, "Scale")
            outs["OutScale"] = scale.at[block_ids, :, offs].set(sc)
        else:
            outs["Out"] = pool.at[block_ids, :, offs, :].set(
                vec.astype(pool.dtype))
        return outs

    # multi-token path: per-(row, token) absolute positions, invalid
    # (past-limit) entries routed to the trash block. Clip keeps the
    # table gather in-bounds for padded rows whose pos+S would run past
    # the row's table; those entries are invalid by construction.
    steps = jnp.arange(S, dtype=jnp.int32)
    qpos = pos[:, None] + steps[None, :]                    # [B, S]
    if limit:
        valid = steps[None, :] < limit[0].astype(jnp.int32)[:, None]
    else:
        valid = jnp.ones((B, S), dtype=bool)
    safe = jnp.clip(qpos, 0, tables.shape[1] * bs - 1)
    blk = jnp.take_along_axis(tables, safe // bs, axis=1)   # [B, S]
    block_ids = jnp.where(valid, blk, 0).reshape(-1)        # [B*S]
    offs = (safe % bs).reshape(-1)                          # [B*S]
    vals = kv.transpose(0, 2, 1, 3).reshape(B * S, kv.shape[1],
                                            kv.shape[3])
    if pool.dtype == jnp.int8:
        q, sc = quantize_kv(vals)
        outs["Out"] = pool.at[block_ids, :, offs, :].set(q)
        scale = x_of(ins, "Scale")
        outs["OutScale"] = scale.at[block_ids, :, offs].set(sc)
    else:
        outs["Out"] = pool.at[block_ids, :, offs, :].set(
            vals.astype(pool.dtype))
    return outs


@register_op("paged_attention", grad=False, infer_shape=False)
def paged_attention_op(ctx, ins, attrs):
    """Decode attention of one query per row over the block-paged pool:
    Q [B, H, 1, D], K/V pools [N, H, bs, D] (+ KScale/VScale [N, H, bs]
    for int8), Tables [B, nblk] int32, Pos [B] int32 -> Out [B, H, 1, D].
    Dispatches to kernels/paged_attention (Pallas fused gather+attend on
    TPU; jnp.take reference elsewhere — attrs["impl"] overrides)."""
    from ..kernels.paged_attention import paged_attention as _kernel

    q = x_of(ins, "Q")
    k = x_of(ins, "K")
    v = x_of(ins, "V")
    tables = x_of(ins, "Tables")
    pos = x_of(ins, "Pos")
    out = _kernel(q, k, v, tables, pos,
                  k_scale=x_of(ins, "KScale"),
                  v_scale=x_of(ins, "VScale"),
                  scale=float(attrs.get("scale", 0.0)) or None,
                  impl=attrs.get("impl") or None)
    return {"Out": out}


@register_op("row_gather", grad=False, infer_shape=False)
def row_gather(ctx, ins, attrs):
    """Out[b] = X[b, Index[b]] — per-row gather along axis 1 (e.g. the
    last REAL token's hidden state of a right-padded prefill batch).
    X [B, S, ...], Index [B] int -> Out [B, ...]."""
    x = x_of(ins)
    idx = x_of(ins, "Index").astype(jnp.int32)
    idx = jnp.clip(idx, 0, x.shape[1] - 1)
    expand = idx.reshape(idx.shape + (1,) * (x.ndim - 1))
    return {"Out": jnp.take_along_axis(x, expand, axis=1)[:, 0]}


@register_op("sample_tokens", grad=False, needs_rng=True,
             infer_shape=False)
def sample_tokens(ctx, ins, attrs):
    """Next-token selection over logits [B, V] with PER-ROW sampling
    config, so greedy and stochastic requests share one decode batch
    (and one executable):

    - Temperature [B] float32: rows with t <= 0 take argmax (greedy);
      rows with t > 0 sample from softmax(logits / t).
    - TopK [B] int32 (optional input): rows with k > 0 restrict sampling
      to the k highest logits (ties at the threshold stay eligible);
      k <= 0 means the full vocabulary.

    Draws from the framework RNG stream: the op folds its build-time
    ``__rng_seed__`` into the executor's run key (``ctx.op_key``), which
    advances by ``split(key, 1)[0]`` per call — fixed seed => bitwise
    reproducible sequences, and the forward-vjp replay rules of
    dropout apply unchanged. Out [B] int32.
    """
    logits = x_of(ins).astype(jnp.float32)
    temp = x_of(ins, "Temperature").astype(jnp.float32)
    topk = ins.get("TopK")
    key = ctx.op_key(attrs)
    V = logits.shape[-1]

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)

    scaled = logits / jnp.maximum(temp, 1e-6)[:, None]
    if topk:
        k = jnp.clip(topk[0].astype(jnp.int32), 1, V)            # [B]
        sorted_desc = -jnp.sort(-logits, axis=-1)                # [B, V]
        thresh = jnp.take_along_axis(sorted_desc, (k - 1)[:, None],
                                     axis=1)                     # [B, 1]
        allowed = (topk[0].astype(jnp.int32) <= 0)[:, None] | \
            (logits >= thresh)
        scaled = jnp.where(allowed, scaled, _NEG_INF)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(
        jnp.int32)
    return {"Out": jnp.where(temp <= 0.0, greedy, sampled)}


@register_op("spec_accept", grad=False, needs_rng=True,
             infer_shape=False)
def spec_accept(ctx, ins, attrs):
    """Speculative-decoding acceptance (Leviathan 2022 / Chen 2023
    rejection sampling, specialized to a POINT-MASS draft distribution
    — the n-gram drafter proposes tokens, not distributions, so
    q = delta(d_i) and the accept probability min(1, p/q) reduces to
    p(d_i); the residual on rejection is p with d_i removed,
    renormalized). One call scores a whole verified span per row:

    - Logits [B, S, V] float32: the verify pass's span logits —
      position i is the model's next-token distribution AFTER the
      current token and drafts d_1..d_i.
    - Draft [B, K] int32 (K = S-1): the proposed tokens.
    - Temperature [B] float32 / optional TopK [B] int32: the exact
      per-row sampling config of ``sample_tokens`` — p is the same
      temperature-scaled, top-k-masked softmax, so a row that accepts
      nothing emits one token from exactly the distribution a plain
      decode step would have used.
    - NumDraft [B] int32: each row's real draft count (<= K); rows at
      0 degrade to a plain single-token step inside the same call.

    Greedy rows (t <= 0) accept d_i while it matches argmax and emit
    argmax tokens throughout — BITWISE what sequential greedy decode
    would produce. Stochastic rows accept d_i with probability
    p_i(d_i) (one uniform draw per position) and sample the
    correction/bonus from the residual (rejection) or from p_K
    (full acceptance) — the output distribution is exactly the
    non-speculative sampler's.

    Out [B, S] int32: position j holds the token emitted for sequence
    position pos+j+1, valid for j <= Accepted[b] (a+1 tokens per row);
    Accepted [B] int32: leading draft tokens accepted (0..NumDraft).
    """
    logits = x_of(ins).astype(jnp.float32)
    draft = x_of(ins, "Draft").astype(jnp.int32)
    temp = x_of(ins, "Temperature").astype(jnp.float32)
    topk = ins.get("TopK")
    num_draft = x_of(ins, "NumDraft").astype(jnp.int32)
    key = ctx.op_key(attrs)
    u_key, cat_key = jax.random.split(key)
    B, S, V = logits.shape
    K = S - 1

    greedy_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, S]
    scaled = logits / jnp.maximum(temp, 1e-6)[:, None, None]
    if topk:
        k = jnp.clip(topk[0].astype(jnp.int32), 1, V)            # [B]
        sorted_desc = -jnp.sort(-logits, axis=-1)                # [B,S,V]
        thresh = jnp.take_along_axis(
            sorted_desc, (k - 1)[:, None, None], axis=-1)        # [B,S,1]
        allowed = (topk[0].astype(jnp.int32) <= 0)[:, None, None] | \
            (logits >= thresh)
        scaled = jnp.where(allowed, scaled, _NEG_INF)

    # per-position acceptance: greedy compares against argmax,
    # stochastic draws one uniform per position against p_i(d_i)
    p = jax.nn.softmax(scaled[:, :K, :], axis=-1)                # [B,K,V]
    p_draft = jnp.take_along_axis(p, draft[:, :, None],
                                  axis=-1)[:, :, 0]              # [B, K]
    u = jax.random.uniform(u_key, (B, K))
    is_greedy = temp <= 0.0                                      # [B]
    accept = jnp.where(is_greedy[:, None],
                       draft == greedy_tok[:, :K],
                       u < p_draft)
    steps = jnp.arange(K, dtype=jnp.int32)[None, :]
    accept = accept & (steps < num_draft[:, None])
    # leading run of accepts (a rejection stops everything after it)
    a = jnp.sum(jnp.cumprod(accept.astype(jnp.int32), axis=1),
                axis=1).astype(jnp.int32)                        # [B]

    # correction/bonus from position a: on rejection (a < num_draft)
    # the rejected draft token is removed from the support (point-mass
    # residual); on full acceptance p_a = p_K is the bonus distribution
    row_scaled = jnp.take_along_axis(
        scaled, a[:, None, None], axis=1)[:, 0, :]               # [B, V]
    d_at_a = jnp.take_along_axis(
        draft, jnp.clip(a, 0, max(K - 1, 0))[:, None],
        axis=1)[:, 0] if K > 0 else jnp.zeros((B,), jnp.int32)
    rejected = a < num_draft
    excl = (jnp.arange(V, dtype=jnp.int32)[None, :]
            == d_at_a[:, None]) & rejected[:, None]
    corr_sample = jax.random.categorical(
        cat_key, jnp.where(excl, _NEG_INF, row_scaled),
        axis=-1).astype(jnp.int32)
    corr_greedy = jnp.take_along_axis(greedy_tok, a[:, None],
                                      axis=1)[:, 0]
    corr = jnp.where(is_greedy, corr_greedy, corr_sample)        # [B]

    # emitted tokens: accepted drafts then the correction (greedy rows
    # emit argmax everywhere — identical to the accepted drafts on the
    # accepted prefix); past-correction slots repeat it, ignored
    # host-side
    padded_draft = jnp.concatenate(
        [draft, jnp.zeros((B, 1), jnp.int32)], axis=1)           # [B, S]
    emit_steps = jnp.arange(S, dtype=jnp.int32)[None, :]
    out = jnp.where(emit_steps < a[:, None], padded_draft,
                    corr[:, None])
    return {"Out": out, "Accepted": a}
