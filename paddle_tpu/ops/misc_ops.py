"""Miscellaneous ops completing the fluid.layers surface.

Small lowerings for reference ops that had no counterpart yet: 3-D
pooling (pool_op.cc), eye/size/shard_index/sampling_id/hash utility ops,
sequence-decode ops (edit_distance_op.cc, crf_decoding_op.cc,
ctc_align_op.cc), hierarchical sigmoid (hierarchical_sigmoid_op.cc),
detection helpers (bipartite_match_op.cc, box_clip_op.cc,
polygon_box_transform_op.cc), mean_iou_op.cc, add_position_encoding_op.cc,
bilinear_tensor_product_op.cc, random_crop_op.cc, scatter_nd, soft_relu.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op
from .common import x_of, normalize_padding


@register_op("pool3d")
def pool3d(ctx, ins, attrs):
    """reference pool_op.cc 3-D variant: max/avg over [kd, kh, kw]."""
    x = x_of(ins)
    ptype = attrs.get("pooling_type", "max")
    ksize = list(attrs.get("ksize", [2, 2, 2]))
    strides = list(attrs.get("strides", ksize))
    if attrs.get("global_pooling", False):
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": red(x, axis=(2, 3, 4), keepdims=True)}
    if attrs.get("adaptive", False):
        n, c, d, h, w = x.shape
        od, oh, ow = ksize
        if d % od or h % oh or w % ow:
            raise NotImplementedError(
                "adaptive pool3d needs divisible spatial dims on TPU")
        xr = x.reshape(n, c, od, d // od, oh, h // oh, ow, w // ow)
        red = jnp.max if ptype == "max" else jnp.mean
        return {"Out": red(xr, axis=(3, 5, 7))}
    pads = ((0, 0), (0, 0)) + normalize_padding(
        attrs.get("paddings", [0, 0, 0]), 3)
    window = (1, 1) + tuple(ksize)
    wstrides = (1, 1) + tuple(strides)
    if ptype == "max":
        return {"Out": jax.lax.reduce_window(
            x, -jnp.inf, jax.lax.max, window, wstrides, pads)}
    ssum = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, wstrides,
                                 pads)
    if attrs.get("exclusive", True):
        cnt = jax.lax.reduce_window(jnp.ones_like(x), 0.0, jax.lax.add,
                                    window, wstrides, pads)
        return {"Out": ssum / cnt}
    return {"Out": ssum / float(np.prod(ksize))}


@register_op("eye", grad=False, infer_shape=False)
def eye(ctx, ins, attrs):
    from ..framework.dtype import np_dtype
    n = int(attrs["num_rows"])
    m = int(attrs.get("num_columns", -1))
    dt = np_dtype(attrs.get("dtype", "float32"))
    return {"Out": jnp.eye(n, m if m > 0 else n, dtype=dt)}


@register_op("size", grad=False)
def size(ctx, ins, attrs):
    x = x_of(ins, "Input")
    return {"Out": jnp.asarray(int(np.prod(x.shape)) if x.shape else 1,
                               jnp.int32)}


@register_op("shard_index", grad=False)
def shard_index(ctx, ins, attrs):
    """reference shard_index_op.cc: local-ize global ids onto a shard."""
    x = x_of(ins)
    index_num = int(attrs["index_num"])
    nshards = int(attrs["nshards"])
    shard_id = int(attrs["shard_id"])
    ignore = attrs.get("ignore_value", -1)
    shard_size = (index_num + nshards - 1) // nshards
    mine = (x // shard_size) == shard_id
    return {"Out": jnp.where(mine, x % shard_size,
                             jnp.asarray(ignore, x.dtype))}


@register_op("sampling_id", grad=False, needs_rng=True,
             infer_shape=False)
def sampling_id(ctx, ins, attrs):
    """reference sampling_id_op.cc: sample one category id per row from a
    probability matrix."""
    x = x_of(ins)
    key = ctx.op_key(attrs)
    return {"Out": jax.random.categorical(
        key, jnp.log(jnp.maximum(x, 1e-20)), axis=-1).astype(jnp.int32)}


@register_op("hash", grad=False, infer_shape=False)
def hash_op(ctx, ins, attrs):
    """reference hash_op.cc: num_hash hashed views of an id tensor into
    [0, mod_by). The reference uses xxhash over the byte string; this
    lowering uses a Knuth multiplicative hash per hash index — same
    capability (bucketized multi-hash embedding keys), different hash
    family (documented divergence)."""
    x = x_of(ins).astype(jnp.uint32)
    num_hash = int(attrs.get("num_hash", 1))
    mod_by = int(attrs["mod_by"])
    ks = jnp.arange(1, num_hash + 1, dtype=jnp.uint32)[:, None]
    flat = x.reshape(1, -1)
    h = (flat * ks * np.uint32(2654435761)) % np.uint32(mod_by)
    return {"Out": h.astype(jnp.int32).reshape(
        (x.shape[0], num_hash) + tuple(x.shape[1:]))}


@register_op("edit_distance", grad=False, infer_shape=False)
def edit_distance(ctx, ins, attrs):
    """reference edit_distance_op.cc: Levenshtein distance per (hyp, ref)
    row pair; masked-dense with explicit lengths; optionally normalized by
    the reference length."""
    hyp = x_of(ins, "Hyps").astype(jnp.int32)
    ref = x_of(ins, "Refs").astype(jnp.int32)
    B, T1 = hyp.shape[0], hyp.shape[1]
    T2 = ref.shape[1]
    hl_in, rl_in = x_of(ins, "HypsLength"), x_of(ins, "RefsLength")
    hlen = (jnp.reshape(hl_in, (-1,)).astype(jnp.int32)
            if hl_in is not None else jnp.full((B,), T1, jnp.int32))
    rlen = (jnp.reshape(rl_in, (-1,)).astype(jnp.int32)
            if rl_in is not None else jnp.full((B,), T2, jnp.int32))
    normalized = bool(attrs.get("normalized", False))

    js = jnp.arange(T2 + 1, dtype=jnp.float32)

    def per_pair(h, r, hl, rl):
        row0 = js                                   # D[0, j] = j
        def step(row, i):
            # D[i, 0] = i
            def inner(carry, j):
                prev_diag, cur_row = carry
                cost = jnp.where(h[i - 1] == r[j - 1], 0.0, 1.0)
                val = jnp.minimum(jnp.minimum(
                    row[j] + 1.0,                   # delete
                    cur_row[j - 1] + 1.0),          # insert
                    prev_diag + cost)               # substitute
                return (row[j], cur_row.at[j].set(val)), None
            cur = jnp.zeros(T2 + 1).at[0].set(i.astype(jnp.float32))
            (_, cur), _ = jax.lax.scan(
                inner, (row[0], cur), jnp.arange(1, T2 + 1))
            return cur, cur

        # stack every DP row so D[hl, rl] can be gathered afterwards
        _, rows = jax.lax.scan(step, row0, jnp.arange(1, T1 + 1))
        table = jnp.concatenate([row0[None], rows], axis=0)  # [T1+1,T2+1]
        return table[hl, rl]

    d = jax.vmap(per_pair)(hyp, ref, hlen, rlen)
    if normalized:
        d = d / jnp.maximum(rlen.astype(jnp.float32), 1.0)
    return {"Out": d[:, None],
            "SequenceNum": jnp.asarray([B], jnp.int32)}


@register_op("crf_decoding", grad=False, infer_shape=False)
def crf_decoding(ctx, ins, attrs):
    """reference crf_decoding_op.cc: Viterbi decode under the
    linear_chain_crf transition convention (Transition [C+2, C]: row 0
    start scores, row 1 stop scores, rows 2.. pairwise). Emission
    [B, T, C] + Length [B]; returns the best path [B, T] (padding 0) —
    with Label given, returns per-position correctness instead."""
    em = x_of(ins, "Emission")
    trans = x_of(ins, "Transition")
    label = ins.get("Label")
    label = label[0] if label else None
    B, T, C = em.shape
    ln_in = x_of(ins, "Length")
    lengths = (jnp.reshape(ln_in, (-1,)).astype(jnp.int32)
               if ln_in is not None else jnp.full((B,), T, jnp.int32))
    start, stop, pair = trans[0], trans[1], trans[2:]

    def decode(e, ln):
        alpha0 = start + e[0]

        def fwd(alpha, t):
            scores = alpha[:, None] + pair          # [C, C]
            best = jnp.max(scores, axis=0) + e[t]
            arg = jnp.argmax(scores, axis=0)
            live = t < ln
            return jnp.where(live, best, alpha), \
                jnp.where(live, arg, -1)

        alphaN, back = jax.lax.scan(fwd, alpha0, jnp.arange(1, T))
        last = jnp.argmax(alphaN + stop)

        def bwd(tag, t):
            bp = back[t - 1]                        # [C]
            prev = jnp.where(t < ln, bp[tag], tag)
            return prev, prev

        _, path_rev = jax.lax.scan(bwd, last, jnp.arange(T - 1, 0, -1))
        path = jnp.concatenate(
            [path_rev[::-1], jnp.asarray([last])]).astype(jnp.int32)
        mask = jnp.arange(T) < ln
        return jnp.where(mask, path, 0)

    paths = jax.vmap(decode)(em, lengths)
    if label is not None:
        lbl = label[..., 0] if label.ndim == 3 else label
        mask = jnp.arange(T)[None] < lengths[:, None]
        return {"ViterbiPath": jnp.where(
            mask, (paths == lbl.astype(jnp.int32)).astype(jnp.int32), 0)}
    return {"ViterbiPath": paths}


@register_op("hsigmoid", infer_shape=False)
def hsigmoid(ctx, ins, attrs):
    """Hierarchical sigmoid over the default complete binary tree
    (reference hierarchical_sigmoid_op.cc): leaf c's path is the binary
    expansion of c + num_classes below its MSB; internal node k uses
    W[k-1]. loss[b] = sum_path -log sigmoid(sign * (w·x + bias))."""
    x = x_of(ins)                       # [B, D]
    w = x_of(ins, "W")                  # [num_classes - 1, D]
    label = x_of(ins, "Label").reshape(-1).astype(jnp.int32)
    bias = ins.get("Bias")
    bias = bias[0].reshape(-1) if bias else None
    num_classes = int(attrs["num_classes"])
    depth = int(np.ceil(np.log2(num_classes)))
    code = label + num_classes          # [B]
    logits = x @ w.T                    # [B, num_classes-1]
    if bias is not None:
        logits = logits + bias
    loss = jnp.zeros(x.shape[0], x.dtype)
    for d in range(depth, 0, -1):
        node = code >> d                # internal node id (1-rooted)
        bit = (code >> (d - 1)) & 1     # next step: 0=left, 1=right
        valid = node >= 1
        idx = jnp.clip(node - 1, 0, num_classes - 2)
        z = jnp.take_along_axis(logits, idx[:, None], axis=1)[:, 0]
        sign = 1.0 - 2.0 * bit.astype(x.dtype)   # bit0 -> +1, bit1 -> -1
        step_loss = jnp.logaddexp(0.0, -sign * z)
        loss = loss + jnp.where(valid, step_loss, 0.0)
    return {"Out": loss[:, None]}


@register_op("bipartite_match", grad=False, infer_shape=False)
def bipartite_match(ctx, ins, attrs):
    """reference detection/bipartite_match_op.cc (greedy max matching):
    DistMat [B, N, M] (N gt rows, M priors; a 2-D [N, M] input is one
    image); repeatedly take the global argmax, bind that (row, col),
    mask both out. match_type='per_prediction' additionally matches any
    still-unmatched column to its argmax row when that distance >=
    dist_threshold (reference ArgMaxMatch). Outputs
    ColToRowMatchIndices [B, M] (-1 unmatched) and the matched distance."""
    dist = x_of(ins, "DistMat")
    squeeze = dist.ndim == 2
    if squeeze:
        dist = dist[None]
    per_pred = attrs.get("match_type") == "per_prediction"
    thresh = float(attrs.get("dist_threshold", 0.5))
    B, N, M = dist.shape
    steps = min(N, M)

    def one(dm):
        def body(carry, _):
            d, match, mdist = carry
            flat = jnp.argmax(d)
            i, j = flat // M, flat % M
            ok = d[i, j] > 0
            match = jnp.where(ok, match.at[j].set(i.astype(jnp.int32)),
                              match)
            mdist = jnp.where(ok, mdist.at[j].set(d[i, j]), mdist)
            d = jnp.where(ok, d.at[i, :].set(-1.0).at[:, j].set(-1.0), d)
            return (d, match, mdist), None

        init = (dm, jnp.full((M,), -1, jnp.int32), jnp.zeros((M,)))
        (d, match, mdist), _ = jax.lax.scan(body, init, None,
                                            length=steps)
        return match, mdist

    match, mdist = jax.vmap(one)(dist.astype(jnp.float32))
    if per_pred:
        best = jnp.argmax(dist, axis=1).astype(jnp.int32)   # [B, M]
        best_d = jnp.max(dist, axis=1)
        extra = (match == -1) & (best_d >= thresh)
        match = jnp.where(extra, best, match)
        mdist = jnp.where(extra, best_d.astype(mdist.dtype), mdist)
    if squeeze:
        match, mdist = match[0], mdist[0]
    return {"ColToRowMatchIndices": match, "ColToRowMatchDist": mdist}


@register_op("mean_iou", grad=False, infer_shape=False)
def mean_iou(ctx, ins, attrs):
    """reference mean_iou_op.cc: mean IoU over classes present."""
    pred = x_of(ins, "Predictions").reshape(-1).astype(jnp.int32)
    label = x_of(ins, "Labels").reshape(-1).astype(jnp.int32)
    C = int(attrs["num_classes"])
    conf = jnp.zeros((C, C), jnp.float32).at[label, pred].add(1.0)
    inter = jnp.diagonal(conf)
    union = conf.sum(0) + conf.sum(1) - inter
    present = union > 0
    iou = jnp.where(present, inter / jnp.maximum(union, 1.0), 0.0)
    miou = jnp.sum(iou) / jnp.maximum(jnp.sum(present), 1)
    return {"OutMeanIou": miou,
            "OutWrong": (conf.sum(1) - inter).astype(jnp.int32),
            "OutCorrect": inter.astype(jnp.int32)}


@register_op("add_position_encoding")
def add_position_encoding(ctx, ins, attrs):
    """reference add_position_encoding_op.cc: out = alpha*x + beta*PE."""
    x = x_of(ins)                       # [B, T, D]
    alpha = float(attrs.get("alpha", 1.0))
    beta = float(attrs.get("beta", 1.0))
    B, T, D = x.shape
    pos = jnp.arange(T, dtype=jnp.float32)[:, None]
    half = D // 2
    div = jnp.power(10000.0, jnp.arange(half, dtype=jnp.float32) / half)
    pe = jnp.concatenate([jnp.sin(pos / div), jnp.cos(pos / div)],
                         axis=1)
    return {"Out": alpha * x + beta * pe[None].astype(x.dtype)}


@register_op("bilinear_tensor_product")
def bilinear_tensor_product(ctx, ins, attrs):
    """reference bilinear_tensor_product_op.cc:
    out[b, k] = x[b] . W[k] . y[b] (+ bias)."""
    x = x_of(ins)
    y = x_of(ins, "Y")
    w = x_of(ins, "Weight")             # [K, dx, dy]
    bias = ins.get("Bias")
    out = jnp.einsum("bi,kij,bj->bk", x, w, y)
    if bias:
        out = out + bias[0]
    return {"Out": out}


@register_op("box_clip", grad=False)
def box_clip(ctx, ins, attrs):
    """reference detection/box_clip_op.cc: clip xyxy boxes into the
    image. Input [B, N, 4], ImInfo [B, 3] (h, w, scale)."""
    boxes = x_of(ins, "Input")
    im = x_of(ins, "ImInfo")
    h = (im[:, 0] / im[:, 2] - 1.0)[:, None]
    w = (im[:, 1] / im[:, 2] - 1.0)[:, None]
    x1 = jnp.clip(boxes[..., 0], 0, w)
    y1 = jnp.clip(boxes[..., 1], 0, h)
    x2 = jnp.clip(boxes[..., 2], 0, w)
    y2 = jnp.clip(boxes[..., 3], 0, h)
    return {"Output": jnp.stack([x1, y1, x2, y2], axis=-1)}


@register_op("polygon_box_transform", grad=False)
def polygon_box_transform(ctx, ins, attrs):
    """reference detection/polygon_box_transform_op.cc (EAST-style): even
    channels hold x offsets, odd channels y offsets; output is the
    absolute quad coordinate 4*grid_index - offset."""
    x = x_of(ins)                       # [B, 2K, H, W]
    B, C, H, W = x.shape
    idx_w = jnp.arange(W, dtype=x.dtype)[None, None, None, :]
    idx_h = jnp.arange(H, dtype=x.dtype)[None, None, :, None]
    even = jnp.arange(C) % 2 == 0
    grid = jnp.where(even[None, :, None, None],
                     jnp.broadcast_to(idx_w, x.shape),
                     jnp.broadcast_to(idx_h, x.shape))
    return {"Output": 4.0 * grid - x}


@register_op("random_crop", grad=False, needs_rng=True,
             infer_shape=False)
def random_crop(ctx, ins, attrs):
    """reference random_crop_op.cc: random spatial crop of the trailing
    dims to attr shape, same offset across leading dims per sample."""
    x = x_of(ins)
    shape = list(attrs["shape"])
    key = ctx.op_key(attrs)
    nlead = x.ndim - len(shape)
    maxs = [x.shape[nlead + i] - shape[i] for i in range(len(shape))]
    offs = [jax.random.randint(jax.random.fold_in(key, i), (), 0, m + 1)
            for i, m in enumerate(maxs)]
    starts = [0] * nlead + [o for o in offs]
    sizes = list(x.shape[:nlead]) + shape
    return {"Out": jax.lax.dynamic_slice(x, starts, sizes)}


@register_op("scatter_nd", grad=False, infer_shape=False)
def scatter_nd(ctx, ins, attrs):
    """reference scatter_nd_op: zeros(shape) with updates added at
    index."""
    index = x_of(ins, "Index").astype(jnp.int32)
    updates = x_of(ins, "Updates")
    shape = tuple(attrs["shape"])
    out = jnp.zeros(shape, updates.dtype)
    idx = tuple(jnp.moveaxis(index, -1, 0))
    return {"Out": out.at[idx].add(updates)}


@register_op("soft_relu")
def soft_relu(ctx, ins, attrs):
    t = float(attrs.get("threshold", 40.0))
    x = jnp.clip(x_of(ins), -t, t)
    return {"Out": jnp.log1p(jnp.exp(x))}


@register_op("ctc_align", grad=False)
def ctc_align(ctx, ins, attrs):
    """reference ctc_align_op.cc (the op under ctc_greedy_decoder):
    collapse repeats then drop blanks; masked-dense output padded with
    -1 plus per-row output lengths."""
    x = x_of(ins).astype(jnp.int32)     # [B, T] token ids
    lengths = jnp.reshape(x_of(ins, "Length"), (-1,)).astype(jnp.int32)
    blank = int(attrs.get("blank", 0))
    B, T = x.shape
    t = jnp.arange(T, dtype=jnp.int32)[None, :]
    valid = t < lengths[:, None]
    prev = jnp.concatenate([jnp.full((B, 1), -1, jnp.int32), x[:, :-1]],
                           axis=1)
    keep = valid & (x != blank) & (x != prev)
    pos = jnp.cumsum(keep.astype(jnp.int32), axis=1) - 1
    cols = jnp.where(keep, pos, T)
    rows = jnp.broadcast_to(jnp.arange(B)[:, None], (B, T))
    out = jnp.full((B, T), -1, jnp.int32).at[
        rows.reshape(-1), cols.reshape(-1)].set(x.reshape(-1),
                                                mode="drop")
    return {"Output": out,
            "OutputLength": jnp.sum(keep, axis=1, dtype=jnp.int32)}
