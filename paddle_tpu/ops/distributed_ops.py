"""P2P / parameter-server ops.

Capability parity with the reference's distributed op family
(/root/reference/paddle/fluid/operators/distributed_ops/ — send_op.cc,
recv_op.cc, send_barrier_op.cc, fetch_barrier_op.cc, listen_and_serv_op.cc,
prefetch_op.cc, distributed_lookup_table_op.cc).

TPU-native boundary: the trainer step stays one compiled XLA module; each
send/recv is an ORDERED jax.experimental.io_callback into the host PSClient
(distributed/ps.py), so XLA sequences RPC side effects with the token chain
the way the reference sequences them on the RPC client. `listen_and_serv`
is a host event loop, not device code — the Executor intercepts it and
serves (framework/executor.py) instead of tracing.
"""
import numpy as np

import jax
import jax.numpy as jnp
from jax.experimental import io_callback

from ..framework.registry import register_op, register_grad_lower
from .common import x_of


def _client(attrs):
    from ..distributed.ps import PSClient
    return PSClient.instance(attrs.get("client_key", "default"))


@register_op("send", grad=False, infer_shape=False)
def send_op(ctx, ins, attrs):
    """Push grads to their pservers (reference send_op.cc). attrs:
    send_varnames (server-side names, aligned with X), epmap."""
    names = list(attrs["send_varnames"])
    epmap = list(attrs["epmap"])
    xs = list(ins.get("X", []))

    tid = attrs.get("trainer_id")
    from ..framework.selected_rows import is_selected_rows
    for v in xs:
        if is_selected_rows(v):
            raise ValueError(
                "send op got a SelectedRows grad — PS mode sends dense "
                "whole-param grads (the transpiler forces is_sparse=False "
                "on trainer-side lookups); sparse tables go through "
                "distributed_embedding/push_sparse instead")

    def do_send(*vals):
        cli = _client(attrs)
        for name, ep, v in zip(names, epmap, vals):
            cli.push_dense(ep, name, np.asarray(v), trainer_id=tid)
        return np.zeros((), np.int32)

    io_callback(do_send, jax.ShapeDtypeStruct((), jnp.int32), *xs,
                ordered=True)
    return None


@register_op("send_barrier", grad=False, infer_shape=False)
def send_barrier_op(ctx, ins, attrs):
    """Sync-round barrier: blocks until every trainer's grads of this round
    are in and the pserver applied the updates (reference
    send_barrier_op.cc + RunSyncLoop)."""
    endpoints = list(attrs["endpoints"])
    tid = attrs.get("trainer_id")

    def do_barrier():
        _client(attrs).send_barrier(endpoints, trainer_id=tid)
        return np.zeros((), np.int32)

    io_callback(do_barrier, jax.ShapeDtypeStruct((), jnp.int32),
                ordered=True)
    return None


@register_op("fetch_barrier", grad=False, infer_shape=False)
def fetch_barrier_op(ctx, ins, attrs):
    return None  # recv is already ordered after send_barrier's token


@register_op("recv", grad=False, infer_shape=False)
def recv_op(ctx, ins, attrs):
    """Pull fresh params from their pservers (reference recv_op.cc). attrs:
    recv_varnames (aligned with Out), epmap, shapes, dtypes."""
    names = list(attrs["recv_varnames"])
    epmap = list(attrs["epmap"])
    shapes = [tuple(s) for s in attrs["shapes"]]
    dtypes = list(attrs["dtypes"])

    def do_recv():
        cli = _client(attrs)
        return tuple(
            np.asarray(cli.pull_dense(ep, n), dtype=dt).reshape(shape)
            for n, ep, shape, dt in zip(names, epmap, shapes, dtypes))

    out_shapes = tuple(jax.ShapeDtypeStruct(s, np.dtype(dt))
                       for s, dt in zip(shapes, dtypes))
    vals = io_callback(do_recv, out_shapes, ordered=True)
    return {"Out": list(vals)}


@register_op("listen_and_serv", grad=False, infer_shape=False)
def listen_and_serv_op(ctx, ins, attrs):
    raise RuntimeError(
        "listen_and_serv is a host event loop (reference "
        "listen_and_serv_op.cc:333) — it cannot be traced into XLA. "
        "Executor.run detects it and serves on the host; getting here "
        "means the pserver program was compiled like a trainer program.")


@register_op("distributed_lookup_table", grad=None, infer_shape=False)
def distributed_lookup_table(ctx, ins, attrs):
    """Sparse parameter prefetch: pull only the touched embedding rows from
    the pserver's host table (reference parameter_prefetch.cc +
    distributed_lookup_table_op.cc). Backward pushes row-wise sparse grads
    (server applies SGD on arrival — async large-scale-sparse semantics).
    The float "W" input is a local stub whose only job is to give autodiff
    a differentiable path so the custom grad (sparse push) runs."""
    ids = x_of(ins, "Ids")
    table = attrs["table_name"]
    ep = attrs["endpoint"]
    dim = int(attrs["emb_dim"])
    flat = jnp.reshape(ids, (-1,)).astype(jnp.int32)

    def do_pull(ids_np):
        cli = _client(attrs)
        return np.asarray(cli.pull_sparse(ep, table, ids_np),
                          dtype=np.float32)

    rows = io_callback(
        do_pull, jax.ShapeDtypeStruct((flat.shape[0], dim), jnp.float32),
        flat, ordered=True)
    return {"Out": rows.reshape(tuple(ids.shape) + (dim,))}


@register_grad_lower("distributed_lookup_table")
def distributed_lookup_table_grad(ctx, ins, attrs):
    fwd = attrs["__fwd_op__"]
    fattrs = fwd["attrs"]
    ids = x_of(ins, "Ids")
    g = x_of(ins, "Out@GRAD")
    dim = int(fattrs["emb_dim"])
    flat_ids = jnp.reshape(ids, (-1,)).astype(jnp.int32)
    flat_g = jnp.reshape(g, (-1, dim))

    def do_push(ids_np, rows_np):
        cli = _client(fattrs)
        cli.push_sparse(fattrs["endpoint"], fattrs["table_name"],
                        ids_np, rows_np)
        return np.zeros((), np.int32)

    io_callback(do_push, jax.ShapeDtypeStruct((), jnp.int32),
                flat_ids, flat_g, ordered=True)
    # the server applied the update; only the stub's zero grad flows locally
    w = x_of(ins, "W")
    return {"W@GRAD": [jnp.zeros_like(w)]}


# ---- pslib/Downpour sparse ops (reference operators/pull_sparse_op.cc,
# push_sparse ops generated alongside; the host runtime lives in
# distributed/downpour.py) ----

def _fleet_of(attrs):
    from ..distributed.downpour import FleetWrapper
    eps = list(attrs["endpoints"])
    key = tuple(eps)
    cache = _fleet_of.__dict__.setdefault("_cache", {})
    fw = cache.get(key)
    if fw is None:
        fw = FleetWrapper(eps, async_push=False)
        cache[key] = fw
    return fw


@register_op("pull_sparse", grad=False, infer_shape=False)
def pull_sparse_op(ctx, ins, attrs):
    """Pull downpour rows for each Ids input -> Out embeddings
    [..., emb_dim] (reference pull_sparse_op.cc; v2 shares the
    lowering)."""
    dim = int(attrs["EmbeddingDim"])
    table = int(attrs.get("TableId", 0))
    ids_list = [jnp.asarray(v) for v in ins["Ids"]]

    def do_pull(*ids_arrs):
        fw = _fleet_of(attrs)
        outs = []
        for a in ids_arrs:
            a = np.asarray(a)
            emb = fw.pull_sparse(table, a).astype(np.float32)
            outs.append(emb.reshape(a.shape + (dim,)))
        return tuple(outs)

    shapes = tuple(jax.ShapeDtypeStruct(tuple(a.shape) + (dim,),
                                        jnp.float32) for a in ids_list)
    outs = io_callback(do_pull, shapes, *ids_list, ordered=True)
    return {"Out": list(outs)}


@register_op("pull_sparse_v2", grad=False, infer_shape=False)
def pull_sparse_v2_op(ctx, ins, attrs):
    return pull_sparse_op(ctx, ins, attrs)


@register_op("push_sparse", grad=False, infer_shape=False)
def push_sparse_op(ctx, ins, attrs):
    """Push grads + show/click stats for each Ids/Grads pair (reference
    push_sparse semantics of pull_sparse_op.cc's grad)."""
    table = int(attrs.get("TableId", 0))
    ids_list = [jnp.asarray(v) for v in ins["Ids"]]
    grad_list = [jnp.asarray(v) for v in ins["Grads"]]
    labels = ins.get("Labels")
    lab = (jnp.asarray(labels[0]) if labels
           else jnp.zeros((1,), jnp.float32))

    def do_push(lab_a, *flat):
        fw = _fleet_of(attrs)
        n = len(flat) // 2
        for a, g in zip(flat[:n], flat[n:]):
            a = np.asarray(a)
            g = np.asarray(g).reshape(a.size, -1)
            lv = np.asarray(lab_a)
            if lv.size <= 1:
                lv = np.zeros(a.size, np.float32)
            fw.push_sparse_with_label(table, a, g, lv)
        return np.zeros((), np.int32)

    io_callback(do_push, jax.ShapeDtypeStruct((), jnp.int32), lab,
                *ids_list, *grad_list, ordered=True)
    return None


@register_op("push_sparse_v2", grad=False, infer_shape=False)
def push_sparse_v2_op(ctx, ins, attrs):
    return push_sparse_op(ctx, ins, attrs)


@register_op("pull_box_sparse", grad=False, infer_shape=False)
def pull_box_sparse_op(ctx, ins, attrs):
    """BoxPS embedding pull (reference pull_box_sparse_op.cc — the
    PaddleBox GPU-KV service front). The service itself is proprietary
    hardware infra; capability-wise it is the downpour sparse table,
    so this lowers to the same FleetWrapper pull (attr `size` is the
    reference's embedding dim name)."""
    a = dict(attrs)
    a.setdefault("EmbeddingDim", int(attrs.get("size", 1)))
    return pull_sparse_op(ctx, ins, a)


@register_op("push_box_sparse", grad=False, infer_shape=False)
def push_box_sparse_op(ctx, ins, attrs):
    """BoxPS embedding push (reference push_box_sparse kernel in
    pull_box_sparse_op.cc) — downpour push, see pull_box_sparse.
    Grad-op wiring feeds the upstream grads as Out@GRAD; push_sparse
    expects them under Grads."""
    ins = dict(ins)
    if "Out@GRAD" in ins and "Grads" not in ins:
        ins["Grads"] = ins["Out@GRAD"]
    return push_sparse_op(ctx, ins, attrs)
