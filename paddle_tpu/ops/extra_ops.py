"""Long-tail operator coverage.

Small ops closing the remaining gaps against the reference's operator
inventory (/root/reference/paddle/fluid/operators/*.cc): v1 alias names for
already-implemented v2 lowerings, elementwise/loss/vision utilities, CTR
ops (cvm, data_norm), sampling losses (nce, sample_logits), structured
losses (warpctc via optax's CTC, linear_chain_crf via a scan over the
forward algorithm), and the beam-search decode pair (beam_search +
gather_tree) used by While-loop decoders.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import OPS, register_grad_lower, register_op
from .common import roi_batch_indices, x_of


def _alias(new, old):
    """Register a v1 name for an existing lowering."""
    OPS[new] = OPS[old]


_alias("squeeze", "squeeze2")
_alias("unsqueeze", "unsqueeze2")
_alias("flatten", "flatten2")
_alias("expand_as", "expand_as_v2")
_alias("reverse", "flip")
_alias("depthwise_conv2d_transpose", "conv2d_transpose")


@register_op("minus")
def minus(ctx, ins, attrs):
    return {"Out": x_of(ins) - x_of(ins, "Y")}


@register_op("cos_sim")
def cos_sim(ctx, ins, attrs):
    """reference cos_sim_op.h: row-wise cosine similarity; Y may have one
    row (broadcast)."""
    x = x_of(ins)
    y = x_of(ins, "Y")
    xn = jnp.sqrt(jnp.sum(x * x, axis=-1, keepdims=True))
    yn = jnp.sqrt(jnp.sum(y * y, axis=-1, keepdims=True))
    num = jnp.sum(x * y, axis=-1, keepdims=True)
    return {"Out": num / jnp.maximum(xn * yn, 1e-12),
            "XNorm": xn, "YNorm": jnp.broadcast_to(yn, xn.shape)}


@register_op("multiplex", grad=None, infer_shape=False)
def multiplex(ctx, ins, attrs):
    """Row-wise select among candidate tensors by index
    (reference multiplex_op.h)."""
    ids = x_of(ins, "Ids").reshape(-1).astype(jnp.int32)
    xs = jnp.stack(ins["X"], axis=0)          # [C, B, ...]
    return {"Out": jnp.take_along_axis(
        xs, ids[None, :].reshape((1, -1) + (1,) * (xs.ndim - 2)),
        axis=0)[0]}


@register_op("rank_loss")
def rank_loss(ctx, ins, attrs):
    """reference rank_loss_op.h: RankNet pairwise loss."""
    label = x_of(ins, "Label")
    left = x_of(ins, "Left")
    right = x_of(ins, "Right")
    d = left - right
    return {"Out": jnp.logaddexp(0.0, d) - label * d}


@register_op("hinge_loss")
def hinge_loss(ctx, ins, attrs):
    """reference hinge_loss_op.h: max(0, 1 - (2y-1) * pred)."""
    logits = x_of(ins, "Logits")
    labels = x_of(ins, "Labels")
    return {"Loss": jnp.maximum(0.0, 1.0 - (2.0 * labels - 1.0) * logits)}


@register_op("bpr_loss")
def bpr_loss(ctx, ins, attrs):
    """Bayesian personalized ranking (reference bpr_loss_op.h)."""
    x = x_of(ins)                 # [B, C] scores
    label = x_of(ins, "Label").reshape(-1).astype(jnp.int32)
    pos = jnp.take_along_axis(x, label[:, None], axis=1)
    diff = pos - x                                       # [B, C]
    lse = jnp.logaddexp(0.0, -diff)   # stable for large gaps
    C = x.shape[1]
    mask = jax.nn.one_hot(label, C, dtype=x.dtype)
    return {"Y": jnp.sum(lse * (1.0 - mask), axis=1,
                         keepdims=True) / (C - 1)}


@register_op("l1_norm")
def l1_norm(ctx, ins, attrs):
    return {"Out": jnp.sum(jnp.abs(x_of(ins))).reshape(())}


@register_op("frobenius_norm")
def frobenius_norm(ctx, ins, attrs):
    from .common import reduce_axes
    x = x_of(ins)
    axes, keep = reduce_axes(attrs, x.ndim)
    return {"Out": jnp.sqrt(jnp.sum(x * x, axis=axes, keepdims=keep))}


@register_op("dist")
def dist(ctx, ins, attrs):
    """p-norm distance between broadcasted tensors (reference dist_op.h)."""
    x = x_of(ins)
    y = x_of(ins, "Y")
    p = float(attrs.get("p", 2.0))
    d = jnp.abs(x - y)
    if p == float("inf"):
        out = jnp.max(d)
    elif p == 0:
        out = jnp.sum((d != 0).astype(x.dtype))
    else:
        out = jnp.sum(d ** p) ** (1.0 / p)
    return {"Out": out.reshape(())}


@register_op("cross")
def cross(ctx, ins, attrs):
    x = x_of(ins)
    y = x_of(ins, "Y")
    axis = attrs.get("dim", -1)
    if axis in (-1, None):
        axis = next(i for i in range(x.ndim) if x.shape[i] == 3)
    return {"Out": jnp.cross(x, y, axis=axis)}


@register_op("index_sample", grad=None, infer_shape=False)
def index_sample(ctx, ins, attrs):
    """reference index_sample_op.h: out[b, j] = x[b, index[b, j]]."""
    x = x_of(ins)
    idx = x_of(ins, "Index").astype(jnp.int32)
    return {"Out": jnp.take_along_axis(x, idx, axis=1)}


@register_op("unfold")
def unfold(ctx, ins, attrs):
    """im2col (reference unfold_op.h): [N,C,H,W] ->
    [N, C*kh*kw, L] sliding-window columns."""
    x = x_of(ins)
    kh, kw = attrs["kernel_sizes"]
    sh, sw = attrs.get("strides", [1, 1])
    pads = list(attrs.get("paddings", [0, 0]))
    if len(pads) == 2:          # symmetric [ph, pw]
        pt, pl, pb, pr = pads[0], pads[1], pads[0], pads[1]
    else:                       # reference order [top, left, bottom, right]
        pt, pl, pb, pr = pads
    dh, dw = attrs.get("dilations", [1, 1])
    N, C, H, W = x.shape
    xp = jnp.pad(x, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
    oh = (H + pt + pb - dh * (kh - 1) - 1) // sh + 1
    ow = (W + pl + pr - dw * (kw - 1) - 1) // sw + 1
    cols = []
    for i in range(kh):
        for j in range(kw):
            patch = xp[:, :, i * dh:i * dh + sh * (oh - 1) + 1:sh,
                       j * dw:j * dw + sw * (ow - 1) + 1:sw]
            cols.append(patch)
    out = jnp.stack(cols, axis=2)             # [N, C, kh*kw, oh, ow]
    return {"Y": out.reshape(N, C * kh * kw, oh * ow)}


@register_op("space_to_depth")
def space_to_depth(ctx, ins, attrs):
    x = x_of(ins)
    b = int(attrs["blocksize"])
    N, C, H, W = x.shape
    out = x.reshape(N, C, H // b, b, W // b, b)
    out = out.transpose(0, 3, 5, 1, 2, 4)
    return {"Out": out.reshape(N, C * b * b, H // b, W // b)}


@register_op("shuffle_channel")
def shuffle_channel(ctx, ins, attrs):
    x = x_of(ins)
    g = int(attrs.get("group", 1))
    N, C, H, W = x.shape
    return {"Out": x.reshape(N, g, C // g, H, W).transpose(0, 2, 1, 3, 4)
            .reshape(N, C, H, W)}


@register_op("affine_channel")
def affine_channel(ctx, ins, attrs):
    """reference affine_channel_op.cc: per-channel scale/bias, NCHW or
    NHWC; absent Scale/Bias default to identity (1/0)."""
    x = x_of(ins)
    scale = x_of(ins, "Scale")
    bias = x_of(ins, "Bias")
    layout = attrs.get("data_layout", "NCHW")
    caxis = 1 if layout == "NCHW" else x.ndim - 1
    shape = [1] * x.ndim
    shape[caxis] = -1
    out = x
    if scale is not None:
        out = out * scale.reshape(shape)
    if bias is not None:
        out = out + bias.reshape(shape)
    return {"Out": out}


@register_op("lrn")
def lrn(ctx, ins, attrs):
    """Local response norm (reference lrn_op.h), NCHW."""
    x = x_of(ins)
    n = int(attrs.get("n", 5))
    k = float(attrs.get("k", 2.0))
    alpha = float(attrs.get("alpha", 1e-4))
    beta = float(attrs.get("beta", 0.75))
    sq = x * x
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    acc = sum(pad[:, i:i + x.shape[1]] for i in range(n))
    mid = k + alpha * acc
    return {"Out": x / mid ** beta, "MidOut": mid}


@register_op("pad_constant_like")
def pad_constant_like(ctx, ins, attrs):
    x = x_of(ins)                 # target shape donor
    y = x_of(ins, "Y")            # tensor to pad
    value = float(attrs.get("pad_value", 0.0))
    pads = [(0, xs - ys) for xs, ys in zip(x.shape, y.shape)]
    return {"Out": jnp.pad(y, pads, constant_values=value)}


@register_op("unbind", infer_shape=False)
def unbind(ctx, ins, attrs):
    x = x_of(ins)
    axis = attrs.get("axis", 0)
    return {"Out": [jnp.squeeze(s, axis=axis)
                    for s in jnp.split(x, x.shape[axis], axis=axis)]}


@register_op("crop_tensor")
def crop_tensor(ctx, ins, attrs):
    """reference crop_tensor_op.h: Offsets may be a runtime TENSOR
    (dynamic_slice handles it); the output `shape` must be static."""
    x = x_of(ins)
    off_in = ins.get("Offsets")
    if off_in:
        off = jnp.reshape(off_in[0], (-1,)).astype(jnp.int32)
        offsets = [off[i] for i in range(x.ndim)]
    else:
        offsets = attrs.get("offsets", [0] * x.ndim)
    shape = attrs["shape"]
    return {"Out": jax.lax.dynamic_slice(x, offsets, shape)}


_alias("crop", "crop_tensor")


@register_op("scatter_nd_add")
def scatter_nd_add(ctx, ins, attrs):
    x = x_of(ins)
    index = x_of(ins, "Index").astype(jnp.int32)
    updates = x_of(ins, "Updates")
    idx = tuple(index[..., i] for i in range(index.shape[-1]))
    return {"Out": x.at[idx].add(updates)}


@register_op("sigmoid_focal_loss")
def sigmoid_focal_loss(ctx, ins, attrs):
    """reference detection/sigmoid_focal_loss_op.h (per-class focal loss
    with a background-aware one-hot; labels in [0, C], 0 = background)."""
    x = x_of(ins)                 # [N, C] logits
    label = x_of(ins, "Label").reshape(-1).astype(jnp.int32)
    fg_num = jnp.maximum(x_of(ins, "FgNum").reshape(()), 1).astype(x.dtype)
    gamma = float(attrs.get("gamma", 2.0))
    alpha = float(attrs.get("alpha", 0.25))
    C = x.shape[1]
    target = jax.nn.one_hot(label - 1, C, dtype=x.dtype)  # bg -> all zeros
    p = jax.nn.sigmoid(x)
    ce = jnp.logaddexp(0.0, x) - x * target
    p_t = p * target + (1 - p) * (1 - target)
    a_t = alpha * target + (1 - alpha) * (1 - target)
    return {"Out": a_t * ((1 - p_t) ** gamma) * ce / fg_num}


@register_op("roi_pool", grad=False, infer_shape=False)
def roi_pool(ctx, ins, attrs):
    """Max ROI pooling (reference roi_pool_op.h) — the quantized
    predecessor of roi_align."""
    x = x_of(ins)
    rois = x_of(ins, "ROIs")
    ph = int(attrs.get("pooled_height", 1))
    pw = int(attrs.get("pooled_width", 1))
    scale = float(attrs.get("spatial_scale", 1.0))
    N, C, H, W = x.shape
    R = rois.shape[0]
    batch_idx = roi_batch_indices(ins, R)

    def one(roi, bi):
        x1, y1, x2, y2 = jnp.round(roi * scale).astype(jnp.int32)
        h = jnp.maximum(y2 - y1 + 1, 1)
        w = jnp.maximum(x2 - x1 + 1, 1)
        ys = jnp.arange(H)[None, :]
        xs = jnp.arange(W)[None, :]
        out = jnp.full((C, ph, pw), -jnp.inf, x.dtype)
        for i in range(ph):
            for j in range(pw):
                y_lo = y1 + (i * h) // ph
                y_hi = y1 + ((i + 1) * h + ph - 1) // ph
                x_lo = x1 + (j * w) // pw
                x_hi = x1 + ((j + 1) * w + pw - 1) // pw
                my = ((ys >= y_lo) & (ys < jnp.maximum(y_hi, y_lo + 1)))
                mx = ((xs >= x_lo) & (xs < jnp.maximum(x_hi, x_lo + 1)))
                m = my.reshape(1, H, 1) & mx.reshape(1, 1, W)
                cell = jnp.where(m, x[bi], -jnp.inf)
                out = out.at[:, i, j].set(jnp.max(cell, axis=(1, 2)))
        return out

    return {"Out": jax.vmap(one)(rois, batch_idx)}


@register_op("cvm")
def cvm(ctx, ins, attrs):
    """CTR show/click feature op (reference cvm_op.h): with use_cvm keep
    [log(show+1), log(click+1)-log(show+1)] prepended; else strip them."""
    x = x_of(ins)                 # [B, D] (first 2 cols = show, click)
    use_cvm = bool(attrs.get("use_cvm", True))
    show = jnp.log(x[:, 0:1] + 1.0)
    click = jnp.log(x[:, 1:2] + 1.0) - show
    if use_cvm:
        return {"Y": jnp.concatenate([show, click, x[:, 2:]], axis=1)}
    return {"Y": x[:, 2:]}


@register_op("data_norm")
def data_norm(ctx, ins, attrs):
    """Streaming feature normalization for CTR (reference data_norm_op.h):
    means/scales come from accumulated batch sums, updated functionally."""
    x = x_of(ins)
    size = x_of(ins, "BatchSize")
    bsum = x_of(ins, "BatchSum")
    sqsum = x_of(ins, "BatchSquareSum")
    eps = float(attrs.get("epsilon", 1e-4))
    mean = bsum / jnp.maximum(size, 1.0)
    var = sqsum / jnp.maximum(size, 1.0) - mean * mean
    scale = 1.0 / jnp.sqrt(jnp.maximum(var, 0.0) + eps)
    y = (x - mean) * scale
    n = jnp.asarray(x.shape[0], x.dtype)
    return {"Y": y, "Means": jnp.broadcast_to(mean, x.shape[-1:]),
            "Scales": jnp.broadcast_to(scale, x.shape[-1:]),
            "BatchSizeOut": size + n,
            "BatchSumOut": bsum + jnp.sum(x, axis=0),
            "BatchSquareSumOut": sqsum + jnp.sum(x * x, axis=0)}


@register_op("nce", infer_shape=False, needs_rng=True)
def nce(ctx, ins, attrs):
    """Noise-contrastive estimation loss (reference nce_op.h) with uniform
    negative sampling."""
    x = x_of(ins, "Input")        # [B, D]
    label = x_of(ins, "Label").reshape(-1).astype(jnp.int32)
    w = x_of(ins, "Weight")       # [V, D]
    b = ins.get("Bias")
    b = b[0] if b else None
    num_neg = int(attrs.get("num_neg_samples", 10))
    V = w.shape[0]
    key = ctx.op_key(attrs)
    B = x.shape[0]
    neg = jax.random.randint(key, (B, num_neg), 0, V)
    ids = jnp.concatenate([label[:, None], neg], axis=1)  # [B, 1+neg]
    w_s = w[ids]                                          # [B, 1+neg, D]
    logits = jnp.einsum("bd,bkd->bk", x, w_s)
    if b is not None:
        logits = logits + b[ids]
    # NCE logit correction: s - log(k * q(y)) with uniform q = 1/V
    logits = logits - np.log(num_neg / V)
    labels = jnp.concatenate(
        [jnp.ones((B, 1), x.dtype), jnp.zeros((B, num_neg), x.dtype)],
        axis=1)
    loss = jnp.logaddexp(0.0, logits) - logits * labels
    return {"Cost": jnp.sum(loss, axis=1, keepdims=True),
            "SampleLogits": logits, "SampleLabels": ids}


@register_op("sample_logits", infer_shape=False,
             needs_rng=True)
def sample_logits(ctx, ins, attrs):
    """Sampled-softmax candidate sampling (reference sample_logits_op.h):
    gather the true-label logits plus uniform negatives."""
    logits = x_of(ins, "Logits")  # [B, V]
    labels = x_of(ins, "Labels").astype(jnp.int32)  # [B, T]
    num_samples = int(attrs.get("num_samples", 10))
    key = ctx.op_key(attrs)
    B, V = logits.shape
    neg = jax.random.randint(key, (B, num_samples), 0, V)
    ids = jnp.concatenate([labels, neg], axis=1)
    out = jnp.take_along_axis(logits, ids, axis=1)
    return {"SampledLogits": out, "Samples": ids,
            "SampledLabels": jnp.arange(labels.shape[1],
                                        dtype=jnp.int32)[None, :].repeat(
                                            B, axis=0)}


@register_op("warpctc", grad=None, infer_shape=False)
def warpctc(ctx, ins, attrs):
    """CTC loss (reference warpctc_op.h wrapping warp-ctc): here optax's
    pure-XLA CTC over padded [B, T, V] logits + label/logit lengths."""
    import optax
    logits = x_of(ins, "Logits")      # [B, T, V] (batch-major padded)
    labels = x_of(ins, "Label").astype(jnp.int32)   # [B, L]
    ll_in = x_of(ins, "LogitsLength")
    bl_in = x_of(ins, "LabelLength")
    B = logits.shape[0]
    logit_lens = (ll_in.reshape(-1).astype(jnp.int32) if ll_in is not None
                  else jnp.full((B,), logits.shape[1], jnp.int32))
    label_lens = (bl_in.reshape(-1).astype(jnp.int32) if bl_in is not None
                  else jnp.full((B,), labels.shape[1], jnp.int32))
    blank = int(attrs.get("blank", 0))
    T = logits.shape[1]
    L = labels.shape[1]
    logit_pad = (jnp.arange(T)[None, :] >= logit_lens[:, None]).astype(
        logits.dtype)
    label_pad = (jnp.arange(L)[None, :] >= label_lens[:, None]).astype(
        logits.dtype)
    loss = optax.ctc_loss(logits, logit_pad, labels, label_pad,
                          blank_id=blank)
    return {"Loss": loss.reshape(-1, 1)}


@register_op("linear_chain_crf", grad=None, infer_shape=False)
def linear_chain_crf(ctx, ins, attrs):
    """Linear-chain CRF negative log-likelihood (reference
    linear_chain_crf_op.h), batched padded form: Emission [B, T, K],
    Transition [K+2, K] (row 0 start, row 1 end), Label [B, T],
    Length [B]. The partition function is a scan over time (the
    forward algorithm) — XLA-friendly, no per-sequence Python loops."""
    em = x_of(ins, "Emission")
    trans = x_of(ins, "Transition")
    label = x_of(ins, "Label").astype(jnp.int32)
    B, T, K = em.shape
    ln_in = x_of(ins, "Length")
    lens = (ln_in.reshape(-1).astype(jnp.int32)
            if ln_in is not None else jnp.full((B,), T, jnp.int32))
    start, end, w = trans[0], trans[1], trans[2:]     # [K], [K], [K, K]

    # log partition via forward algorithm
    def step(alpha_t, inputs):
        e_t, valid_t = inputs                          # [B, K], [B]
        nxt = jax.nn.logsumexp(
            alpha_t[:, :, None] + w[None, :, :], axis=1) + e_t
        return jnp.where(valid_t[:, None], nxt, alpha_t), None

    alpha0 = start[None, :] + em[:, 0]
    valid = (jnp.arange(1, T)[None, :] < lens[:, None]).T   # [T-1, B]
    alpha, _ = jax.lax.scan(step, alpha0,
                            (em[:, 1:].transpose(1, 0, 2), valid))
    log_z = jax.nn.logsumexp(alpha + end[None, :], axis=1)  # [B]

    # gold path score
    t_idx = jnp.arange(T)
    emit = jnp.take_along_axis(em, label[..., None], axis=2)[..., 0]
    emit = jnp.sum(jnp.where(t_idx[None, :] < lens[:, None], emit, 0.0),
                   axis=1)
    pair = w[label[:, :-1], label[:, 1:]]                   # [B, T-1]
    pair = jnp.sum(
        jnp.where(t_idx[None, 1:] < lens[:, None], pair, 0.0), axis=1)
    first = start[label[:, 0]]
    last_idx = jnp.clip(lens - 1, 0, T - 1)
    last = end[jnp.take_along_axis(label, last_idx[:, None], axis=1)[:, 0]]
    gold = emit + pair + first + last
    # reference linear_chain_crf_op.h returns -log_likelihood (a POSITIVE
    # value callers minimize directly)
    return {"LogLikelihood": (log_z - gold).reshape(-1, 1)}


@register_op("beam_search", grad=False, infer_shape=False)
def beam_search(ctx, ins, attrs):
    """One beam-search expansion step (reference beam_search_op.h, padded
    form): pre_scores [B, beam], scores [B*beam, V] log-probs ->
    top-beam continuations per batch row. Finished beams (pre_id ==
    end_id) only propagate themselves."""
    pre_ids = x_of(ins, "pre_ids").astype(jnp.int32)      # [B, beam]
    pre_scores = x_of(ins, "pre_scores")                  # [B, beam]
    scores = x_of(ins, "scores")                          # [B*beam, V]
    beam = int(attrs["beam_size"])
    end_id = int(attrs.get("end_id", 0))
    B = pre_ids.shape[0]
    V = scores.shape[-1]
    sc = scores.reshape(B, beam, V)
    finished = pre_ids == end_id
    # finished beams: only the end token continues, carrying the score
    cont = pre_scores[..., None] + sc
    frozen = jnp.full((B, beam, V), -1e30, sc.dtype)
    frozen = frozen.at[:, :, end_id].set(pre_scores)
    total = jnp.where(finished[..., None], frozen, cont)  # [B, beam, V]
    flat = total.reshape(B, beam * V)
    top_s, top_i = jax.lax.top_k(flat, beam)
    parent = top_i // V
    token = top_i % V
    return {"selected_ids": token, "selected_scores": top_s,
            "parent_idx": parent}


@register_op("gather_tree", grad=False, infer_shape=False)
def gather_tree(ctx, ins, attrs):
    """Back-trace beam parents into full sequences (reference
    gather_tree_op.h): ids/parents [T, B, beam] -> sequences [T, B,
    beam]."""
    ids = x_of(ins, "Ids").astype(jnp.int32)
    parents = x_of(ins, "Parents").astype(jnp.int32)
    T = ids.shape[0]

    def step(beam_idx, t):
        # walking backwards from T-1
        tok = jnp.take_along_axis(ids[t], beam_idx, axis=-1)
        parent = jnp.take_along_axis(parents[t], beam_idx, axis=-1)
        return parent, tok

    init = jnp.broadcast_to(jnp.arange(ids.shape[2], dtype=jnp.int32),
                            ids.shape[1:])
    _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    return {"Out": toks[::-1]}


# ---------------------------------------------------------------------------
# Knowledge-distillation / metric-learning losses
# ---------------------------------------------------------------------------

@register_op("fsp")
def fsp(ctx, ins, attrs):
    """FSP (flow of solution procedure) matrix between two feature maps
    (reference fsp_op.cc): out[b, i, j] = mean_hw x[b,i,h,w] * y[b,j,h,w]."""
    x = x_of(ins)
    y = x_of(ins, "Y")
    hw = x.shape[2] * x.shape[3]
    return {"Out": jnp.einsum("bihw,bjhw->bij", x, y) / hw}


@register_op("center_loss", infer_shape=False)
def center_loss(ctx, ins, attrs):
    """Center loss (reference center_loss_op.cc): pulls features toward a
    running per-class center. Loss = 0.5*||x - c_label||^2; CentersOut is
    the updated center table (c -= alpha * mean diff per class) when
    need_update."""
    x = x_of(ins)                      # [B, D]
    label = x_of(ins, "Label").astype(jnp.int32).reshape(-1)
    centers = x_of(ins, "Centers")     # [C, D]
    rate = x_of(ins, "CenterUpdateRate")
    alpha = (jnp.reshape(rate, (-1,))[0] if rate is not None
             else attrs.get("alpha", 0.5))
    picked = jnp.take(centers, label, axis=0)
    diff = x - picked
    loss = 0.5 * jnp.sum(diff * diff, axis=-1, keepdims=True)
    if attrs.get("need_update", True):
        # reference: centers[c] -= alpha * sum(diff_c) / (1 + count_c)
        cnt = jnp.zeros((centers.shape[0],), x.dtype).at[label].add(1.0)
        acc = jnp.zeros_like(centers).at[label].add(diff)
        centers_out = centers - alpha * acc / (1.0 + cnt)[:, None]
    else:
        centers_out = centers
    return {"Loss": loss, "SampleCenterDiff": diff,
            "CentersOut": centers_out}


@register_op("cross_entropy2")
def cross_entropy2(ctx, ins, attrs):
    """Hard-label CE over probabilities (reference cross_entropy_op.cc
    cross_entropy2 variant): Loss = -log(X[label]); also returns MatchX,
    the matched probability, which the grad kernel reuses."""
    x = x_of(ins)
    label = x_of(ins, "Label").astype(jnp.int32)
    if label.ndim == x.ndim:
        label = label[..., 0]
    ignore = attrs.get("ignore_index", -100)
    safe = jnp.clip(label, 0, x.shape[-1] - 1)
    match = jnp.take_along_axis(x, safe[..., None], axis=-1)
    loss = -jnp.log(jnp.maximum(match, 1e-12))
    # reference zeroes the loss wherever label == ignore_index, whatever
    # its sign (the default sentinel is -100)
    loss = jnp.where(label[..., None] == ignore, 0.0, loss)
    return {"Y": loss, "MatchX": match}


# ---------------------------------------------------------------------------
# Partial / slot-wise dense ops (CTR serving blocks)
# ---------------------------------------------------------------------------

@register_op("partial_concat")
def partial_concat(ctx, ins, attrs):
    """Concat a [start, start+length) column slice of every input
    (reference partial_concat_op.cc). length=-1 means to the end."""
    xs = list(ins["X"])
    start = int(attrs.get("start_index", 0))
    length = int(attrs.get("length", -1))
    end = None if length < 0 else start + length
    return {"Out": jnp.concatenate([x[:, start:end] for x in xs], axis=1)}


@register_op("partial_sum")
def partial_sum(ctx, ins, attrs):
    """Sum the same column slice of every input (reference
    partial_sum_op.cc)."""
    xs = list(ins["X"])
    start = int(attrs.get("start_index", 0))
    length = int(attrs.get("length", -1))
    end = None if length < 0 else start + length
    out = xs[0][:, start:end]
    for x in xs[1:]:
        out = out + x[:, start:end]
    return {"Out": out}


@register_op("batch_fc")
def batch_fc(ctx, ins, attrs):
    """Per-slot batched FC (reference batch_fc_op.cc): Input [S, B, in],
    W [S, in, out], Bias [S, 1, out] -> relu-free batched matmul."""
    x = x_of(ins, "Input")
    w = x_of(ins, "W")
    b = x_of(ins, "Bias")
    out = jnp.einsum("sbi,sio->sbo", x, w)
    if b is not None:
        out = out + b
    return {"Out": out}


@register_op("shuffle_batch", infer_shape=False, needs_rng=True)
def shuffle_batch(ctx, ins, attrs):
    """Random row permutation (reference shuffle_batch_op.cc); emits the
    permutation so callers can un-shuffle."""
    x = x_of(ins)
    key = ctx.op_key(attrs)
    idx = jax.random.permutation(key, x.shape[0])
    return {"Out": jnp.take(x, idx, axis=0),
            "ShuffleIdx": idx.astype(jnp.int32)}


# ---------------------------------------------------------------------------
# Control-flow routing + LoD split/merge (IfElse/Switch plumbing)
# ---------------------------------------------------------------------------

@register_op("select_input")
def select_input(ctx, ins, attrs):
    """Route one of N same-shaped inputs by a scalar index (reference
    controlflow/select_input_op.cc, used by case/switch_case)."""
    xs = list(ins["X"])
    mask = jnp.reshape(x_of(ins, "Mask"), (-1,))[0].astype(jnp.int32)
    stacked = jnp.stack(xs, axis=0)
    return {"Out": jnp.take(stacked, jnp.clip(mask, 0, len(xs) - 1),
                            axis=0)}


@register_op("select_output")
def select_output(ctx, ins, attrs):
    """Inverse of select_input (reference select_output_op.cc): copy X to
    output branch `mask`; other branches get zeros (the reference leaves
    them uninitialized — zeros keep XLA shapes total)."""
    x = x_of(ins)
    mask = jnp.reshape(x_of(ins, "Mask"), (-1,))[0].astype(jnp.int32)
    if "num_outputs" not in attrs:
        raise ValueError("select_output requires attr num_outputs (the "
                         "lowering cannot see the op's output slot count)")
    n = int(attrs["num_outputs"])
    outs = [jnp.where(mask == i, x, jnp.zeros_like(x)) for i in range(n)]
    return {"Out": outs}


@register_op("split_lod_tensor")
def split_lod_tensor(ctx, ins, attrs):
    """Split rows by a boolean mask into (true, false) tensors (reference
    split_lod_tensor_op.cc, the IfElse input router). Masked-dense: both
    outputs keep the full [B, ...] shape, compacted to their prefix, plus
    valid counts."""
    from .common import compact_rows
    x = x_of(ins)
    mask = jnp.reshape(x_of(ins, "Mask"), (-1,)).astype(bool)
    out_true, n_true = compact_rows(x, mask)
    out_false, n_false = compact_rows(x, ~mask)
    return {"OutTrue": out_true, "OutFalse": out_false,
            "TrueCount": n_true.reshape(1), "FalseCount": n_false.reshape(1)}


@register_op("merge_lod_tensor")
def merge_lod_tensor(ctx, ins, attrs):
    """Merge (true, false) row sets back by the same mask (reference
    merge_lod_tensor_op.cc)."""
    in_true = x_of(ins, "InTrue")
    in_false = x_of(ins, "InFalse")
    mask = jnp.reshape(x_of(ins, "Mask"), (-1,)).astype(bool)
    pos_t = jnp.cumsum(mask.astype(jnp.int32)) - 1
    pos_f = jnp.cumsum((~mask).astype(jnp.int32)) - 1
    t = jnp.take(in_true, jnp.clip(pos_t, 0, in_true.shape[0] - 1), axis=0)
    f = jnp.take(in_false, jnp.clip(pos_f, 0, in_false.shape[0] - 1),
                 axis=0)
    m = mask.reshape((-1,) + (1,) * (in_true.ndim - 1))
    return {"Out": jnp.where(m, t, f)}


# ---------------------------------------------------------------------------
# Shard routing + SelectedRows utilities (PS plumbing)
# ---------------------------------------------------------------------------

@register_op("split_ids", grad=False)
def split_ids(ctx, ins, attrs):
    """Route ids to N shards by id % N (reference
    distributed_ops/split_ids_op.cc). Static form: each output keeps the
    input length, compacted to a prefix, with a count vector."""
    ids = x_of(ins, "Ids").reshape(-1).astype(jnp.int32)
    if "num_shards" not in attrs:
        raise ValueError("split_ids requires attr num_shards (the lowering "
                         "cannot see the op's output slot count)")
    from .common import compact_rows
    n = int(attrs["num_shards"])
    outs, counts = [], []
    for s in range(n):
        out, cnt = compact_rows(ids, (ids % n) == s)
        outs.append(out)
        counts.append(cnt)
    return {"Out": outs, "Count": jnp.stack(counts)}


@register_op("merge_ids", grad=False)
def merge_ids(ctx, ins, attrs):
    """Gather rows looked up per shard back into original id order
    (reference distributed_ops/merge_ids_op.cc): for id i the row comes
    from shard i % N at that shard's running position."""
    ids = x_of(ins, "Ids").reshape(-1).astype(jnp.int32)
    rows = list(ins["X"])               # per-shard row blocks
    n = len(rows)
    shard = ids % n
    # position of each id within its shard's compacted block
    pos = jnp.zeros_like(ids)
    for s in range(n):
        mine = shard == s
        pos = jnp.where(mine, jnp.cumsum(mine.astype(jnp.int32)) - 1, pos)
    stacked = jnp.stack(rows, axis=0)   # [n, L, D]
    return {"Out": stacked[shard, pos]}


@register_op("merge_selected_rows", grad=False)
def merge_selected_rows(ctx, ins, attrs):
    """Coalesce duplicate rows of a SelectedRows (reference
    merge_selected_rows_op.cc -> framework/selected_rows.py coalesce)."""
    from ..framework.selected_rows import coalesce, is_selected_rows
    x = x_of(ins)
    return {"Out": coalesce(x) if is_selected_rows(x) else x}


@register_op("get_tensor_from_selected_rows", grad=False)
def get_tensor_from_selected_rows(ctx, ins, attrs):
    """Expose a SelectedRows' value tensor (reference
    get_tensor_from_selected_rows_op.cc)."""
    from ..framework.selected_rows import is_selected_rows
    x = x_of(ins)
    return {"Out": x.values if is_selected_rows(x) else x}


# ---------------------------------------------------------------------------
# py_func: user Python in the graph
# ---------------------------------------------------------------------------

PY_FUNC_REGISTRY = []


def register_py_func(fn):
    """Register a host callable; returns its id for the py_func op attr
    (mirrors the reference's PythonFuncRegistry, py_func_op.cc)."""
    PY_FUNC_REGISTRY.append(fn)
    return len(PY_FUNC_REGISTRY) - 1


@register_op("py_func", infer_shape=False)
def py_func(ctx, ins, attrs):
    """Call registered host Python inside the compiled program via
    jax.pure_callback (reference py_func_op.cc runs the callable on the
    executor thread). Output shapes/dtypes must be declared statically in
    attrs out_shapes/out_dtypes; the callable must be pure (it may be
    re-invoked or constant-folded by XLA)."""
    import numpy as _np
    fn = PY_FUNC_REGISTRY[int(attrs["func_id"])]
    xs = list(ins.get("X", []))
    shapes = attrs["out_shapes"]
    dtypes = attrs["out_dtypes"]
    specs = [jax.ShapeDtypeStruct(tuple(s), _np.dtype(d))
             for s, d in zip(shapes, dtypes)]

    def host(*arrays):
        out = fn(*arrays)
        if not isinstance(out, (list, tuple)):
            out = (out,)
        return tuple(_np.asarray(o, dtype=sp.dtype)
                     for o, sp in zip(out, specs))

    outs = jax.pure_callback(host, tuple(specs), *xs)
    return {"Out": list(outs)}


@register_grad_lower("py_func")
def py_func_grad(ctx, ins, attrs):
    """User-supplied backward (reference py_func_op.cc backward_func):
    called with (inputs..., outputs..., out_grads...) numpy arrays and
    returns per-input grads (None allowed). The forward callable is
    re-invoked to produce outputs — both must be pure (declared contract
    of the op). Without a backward_func, inputs get no grads."""
    import numpy as _np
    fattrs = attrs["__fwd_op__"]["attrs"]
    bid = fattrs.get("bwd_func_id")
    xs = list(ins.get("X", []))
    if bid is None:
        return {"X@GRAD": [None] * len(xs)}
    fwd = PY_FUNC_REGISTRY[int(fattrs["func_id"])]
    bwd = PY_FUNC_REGISTRY[int(bid)]
    gs = list(ins.get("Out@GRAD", []))
    gs = [g for g in gs if g is not None]
    # the backward builder COMPACTS Out@GRAD to present entries and
    # records which outputs have one (__out_grad_mask__) — realign so
    # bwd always receives one grad per declared output (zeros when the
    # output is unused downstream)
    n_out = len(fattrs["out_shapes"])
    mask = (attrs.get("__out_grad_mask__") or {}).get("Out")
    if mask is None:
        # without the mask, partial grads cannot be aligned to outputs —
        # guessing "first len(gs) outputs" would hand bwd grads for the
        # wrong slots when an earlier output is unused downstream
        if len(gs) != n_out:
            raise ValueError(
                "py_func backward: %d of %d output grads present but no "
                "__out_grad_mask__ to align them" % (len(gs), n_out))
        mask = [True] * n_out

    def host(*arrays):
        n = len(xs)
        x_np = tuple(_np.asarray(a) for a in arrays[:n])
        present = list(arrays[n:])
        out = fwd(*x_np)
        if not isinstance(out, (list, tuple)):
            out = (out,)
        g_np, gi = [], 0
        for i in range(n_out):
            if i < len(mask) and mask[i]:
                g_np.append(_np.asarray(present[gi]))
                gi += 1
            else:
                g_np.append(_np.zeros(tuple(fattrs["out_shapes"][i]),
                                      _np.dtype(fattrs["out_dtypes"][i])))
        grads = bwd(*x_np, *tuple(_np.asarray(o) for o in out), *g_np)
        if not isinstance(grads, (list, tuple)):
            grads = (grads,)
        return tuple(
            _np.zeros(x.shape, _np.asarray(x).dtype) if g is None
            else _np.asarray(g, _np.asarray(x).dtype)
            for x, g in zip(x_np, grads))

    specs = tuple(jax.ShapeDtypeStruct(tuple(x.shape), x.dtype)
                  for x in xs)
    outs = jax.pure_callback(host, specs, *xs, *gs)
    return {"X@GRAD": list(outs)}


@register_op("fsp", infer_shape=False)
def fsp(ctx, ins, attrs):
    """Flow-of-solution-procedure matrix for distillation (reference
    fsp_op.h): Out[b] = X[b].reshape(C1, HW) @ Y[b].reshape(C2, HW)^T
    / (H*W). X [B,C1,H,W], Y [B,C2,H,W] -> [B,C1,C2]."""
    x = x_of(ins)
    y = x_of(ins, "Y")
    B, C1, H, W = x.shape
    C2 = y.shape[1]
    xm = x.reshape(B, C1, H * W)
    ym = y.reshape(B, C2, H * W)
    return {"Out": jnp.einsum("bcx,bdx->bcd", xm, ym) / float(H * W)}


@register_op("cvm", infer_shape=False)
def cvm(ctx, ins, attrs):
    """Continuous-value model op for CTR (reference cvm_op.h): X rows
    lead with (show, click); use_cvm=True keeps the width and rewrites
    col0=log(show+1), col1=log(click+1)-log(show+1); use_cvm=False
    strips the two lead columns."""
    x = x_of(ins)
    use_cvm = bool(attrs.get("use_cvm", True))
    if not use_cvm:
        return {"Y": x[:, 2:]}
    c0 = jnp.log(x[:, 0] + 1.0)
    c1 = jnp.log(x[:, 1] + 1.0) - c0
    return {"Y": jnp.concatenate([c0[:, None], c1[:, None], x[:, 2:]],
                                 axis=1)}


@register_grad_lower("cvm")
def cvm_grad(ctx, ins, attrs):
    """reference CvmGradComputeKernel: DY copies back at the offset and
    the two lead grad columns are OVERWRITTEN with the CVM input values
    (show/click) — the reference's exact, if unusual, contract."""
    fattrs = attrs["__fwd_op__"]["attrs"]
    use_cvm = bool(fattrs.get("use_cvm", True))
    x = x_of(ins)
    g = x_of(ins, "Y@GRAD")
    cvm_in = ins.get("CVM")
    lead = (jnp.asarray(cvm_in[0])[:, :2] if cvm_in
            else jnp.zeros((x.shape[0], 2), x.dtype))
    body = g[:, 2:] if use_cvm else g
    return {"X@GRAD": [jnp.concatenate([lead.astype(x.dtype), body],
                                       axis=1)]}


@register_op("sampled_softmax_with_cross_entropy", infer_shape=False,
             needs_rng=True)
def sampled_softmax_with_cross_entropy(ctx, ins, attrs):
    """Sampled softmax CE (reference sample_logits_op.cc behind
    layers/nn.py sampled_softmax_with_cross_entropy): draw num_samples
    uniform negatives per row, build logits over [true, samples] with
    the -log(q) correction, and return full-softmax-CE over that
    subset. Differentiable w.r.t. Logits via the gather."""
    logits = x_of(ins, "Logits")                  # [B, V]
    label = x_of(ins, "Label").reshape(-1).astype(jnp.int32)
    S = int(attrs.get("num_samples", 5))
    V = logits.shape[-1]
    B = logits.shape[0]
    key = ctx.op_key(attrs)
    neg = jax.random.randint(key, (B, S), 0, V)
    ids = jnp.concatenate([label[:, None], neg], axis=1)   # [B, 1+S]
    picked = jnp.take_along_axis(logits, ids, axis=1)
    # uniform proposal q = 1/V for negatives; true class not corrected
    # (reference: remove_accidental_hits + log-q subtraction)
    corr = jnp.concatenate(
        [jnp.zeros((B, 1), logits.dtype),
         jnp.full((B, S), np.log(S / V), logits.dtype)], axis=1)
    adj = picked - corr
    if bool(attrs.get("remove_accidental_hits", True)):
        # a sampled negative equal to the label is masked out
        hit = ids[:, 1:] == label[:, None]
        adj = adj.at[:, 1:].add(jnp.where(hit, -1e30, 0.0))
    lse = jax.nn.logsumexp(adj, axis=1)
    return {"Loss": (lse - adj[:, 0]).reshape(-1, 1)}
