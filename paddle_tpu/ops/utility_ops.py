"""Long-tail utility ops (reference top-level operators/*.cc family):
tensor factories (linspace, randperm, diag), predicates (allclose,
is_empty, where_index, unique_with_counts), losses (squared_l2_distance,
modified_huber_loss), spatial pyramid pooling, proximal optimizers,
ModelAverage accumulators, sequence-tagging chunk evaluation, and the
beam-search decode pair's final gather.

TPU design notes: ops whose reference output is dynamically sized
(where_index, unique_with_counts) return PADDED static-shape tensors plus
a valid count, the same scheme the sequence and NMS ops use. chunk_eval
— a per-sequence C++ state machine in the reference
(chunk_eval_op.h GetSegments) — is re-derived here as vectorized
begin/end masks: a chunk begins/ends at a position purely as a function
of the (prev, cur) / (cur, next) tag pairs, so segment matching becomes
dense boolean algebra XLA can fuse, instead of a host loop.
"""
import jax
import jax.numpy as jnp

from ..framework.registry import register_op
from .common import as_dtype, int64_t, normalize_padding, x_of


# --------------------------------------------------------------- factories

@register_op("linspace", grad=False, infer_shape=False)
def linspace(ctx, ins, attrs):
    """reference linspace_op.cc: evenly spaced values in [start, stop].
    Num must be a build-time constant on TPU (static shapes); the layer
    wrapper folds Python ints into the `num` attr."""
    start = jnp.reshape(x_of(ins, "Start"), ())
    stop = jnp.reshape(x_of(ins, "Stop"), ())
    if "num" in attrs:
        num = int(attrs["num"])
    else:
        num = int(ins["Num"][0])  # concrete only outside jit
    dtype = start.dtype
    if num == 1:
        # reference linspace_op.h: step=0, out[0]=start (numpy semantics)
        return {"Out": jnp.reshape(start, (1,)).astype(dtype)}
    i = jnp.arange(num, dtype=jnp.float32)
    step = (stop.astype(jnp.float32) - start.astype(jnp.float32)) / (num - 1)
    out = start.astype(jnp.float32) + i * step
    # reference writes stop exactly into the last slot
    out = out.at[-1].set(stop.astype(jnp.float32))
    return {"Out": out.astype(dtype)}


@register_op("randperm", grad=False, infer_shape=False, needs_rng=True)
def randperm(ctx, ins, attrs):
    """reference randperm_op.cc: random permutation of [0, n)."""
    n = int(attrs["n"])
    key = ctx.op_key(attrs)
    perm = jax.random.permutation(key, n)
    return {"Out": perm.astype(as_dtype(attrs, default="int64"))}


@register_op("diag", grad=False, infer_shape=False)
def diag(ctx, ins, attrs):
    """reference diag_op.cc (v1): vector [N] -> diagonal matrix [N, N]."""
    d = x_of(ins, "Diagonal")
    return {"Out": jnp.diag(jnp.reshape(d, (-1,)))}


# -------------------------------------------------------------- predicates

@register_op("allclose", grad=False, infer_shape=False)
def allclose(ctx, ins, attrs):
    """reference allclose_op.cc: elementwise |a-b| <= atol + rtol*|b|,
    reduced to one bool."""
    a = x_of(ins, "Input")
    b = x_of(ins, "Other")
    rtol = float(attrs.get("rtol", 1e-5))
    atol = float(attrs.get("atol", 1e-8))
    equal_nan = bool(attrs.get("equal_nan", False))
    close = jnp.abs(a - b) <= atol + rtol * jnp.abs(b)
    if equal_nan:
        close = close | (jnp.isnan(a) & jnp.isnan(b))
    else:
        close = close & ~(jnp.isnan(a) | jnp.isnan(b))
    return {"Out": jnp.all(close)}


@register_op("is_empty", grad=False, infer_shape=False)
def is_empty(ctx, ins, attrs):
    """reference is_empty_op.cc: numel(X) == 0 (a compile-time constant
    here — shapes are static)."""
    x = x_of(ins)
    return {"Out": jnp.asarray(x.size == 0)}


@register_op("where_index", grad=False, infer_shape=False)
def where_index(ctx, ins, attrs):
    """reference where_index_op.cc (`layers.where`): coordinates of
    nonzero elements. Dynamic [num_true, rank] in the reference; here a
    padded [numel, rank] int64 (pad rows -1) plus Count [1]."""
    cond = x_of(ins, "Condition")
    n = cond.size
    idxs = jnp.nonzero(cond.reshape(-1), size=n, fill_value=-1)[0]
    valid = idxs >= 0
    coords = jnp.stack(
        jnp.unravel_index(jnp.maximum(idxs, 0), cond.shape), axis=-1)
    coords = jnp.where(valid[:, None], coords, -1)
    return {"Out": coords.astype(int64_t()),
            "Count": jnp.sum(valid).astype(int64_t()).reshape(1)}


@register_op("unique_with_counts", grad=False, infer_shape=False)
def unique_with_counts(ctx, ins, attrs):
    """reference unique_with_counts_op.cc: first-occurrence-ordered unique
    values (tf.unique semantics). Out/Count are padded to [N] (valid
    prefix length = max(Index)+1); Index [N] maps each element to its
    unique slot."""
    x = jnp.reshape(x_of(ins), (-1,))
    n = x.shape[0]
    eq = x[None, :] == x[:, None]                      # [N, N]
    first = jnp.argmax(eq, axis=1)                     # first j: x[j]==x[i]
    is_first = first == jnp.arange(n)
    rank = jnp.cumsum(is_first) - 1                    # unique slot per pos
    index = rank[first]
    out = jnp.zeros((n,), x.dtype).at[index].set(x)
    counts = jnp.zeros((n,), int64_t()).at[index].add(1)
    itype = as_dtype(attrs, default="int32")
    return {"Out": out, "Index": index.astype(itype),
            "Count": counts.astype(itype)}


# ------------------------------------------------------------------ losses

@register_op("squared_l2_distance")
def squared_l2_distance(ctx, ins, attrs):
    """reference squared_l2_distance_op.h: row-wise ||x - y||^2; Y may be
    a single row broadcast over X's rows."""
    x = x_of(ins)
    y = x_of(ins, "Y")
    sub = x - y                                        # [B, D]
    out = jnp.sum(sub * sub, axis=-1, keepdims=True)
    return {"sub_result": sub, "Out": out}


@register_op("modified_huber_loss")
def modified_huber_loss(ctx, ins, attrs):
    """reference modified_huber_loss_op.h: v = (2y-1)*x with y in {0,1};
    loss = -4v if v < -1, (1-v)^2 if -1 <= v < 1, else 0."""
    x = x_of(ins)
    y = x_of(ins, "Y")
    v = (2.0 * y - 1.0) * x
    loss = jnp.where(v < -1.0, -4.0 * v,
                     jnp.where(v < 1.0, (1.0 - v) ** 2, 0.0))
    return {"IntermediateVal": v, "Out": loss.astype(x.dtype)}


@register_op("spp", infer_shape=False)
def spp(ctx, ins, attrs):
    """Spatial pyramid pooling (reference spp_op.h): level p pools with
    bins=2^p per dim, kernel=ceil(dim/bins), stride=kernel,
    pad=(kernel*bins-dim+1)//2; levels flattened and concatenated to
    [N, C * sum(4^p)]."""
    x = x_of(ins)
    n, c, h, w = x.shape
    height = int(attrs.get("pyramid_height", 1))
    ptype = attrs.get("pooling_type", "max")
    outs = []
    for p in range(height):
        bins = 2 ** p
        kh = -(-h // bins)
        kw = -(-w // bins)
        ph = (kh * bins - h + 1) // 2
        pw = (kw * bins - w + 1) // 2
        pad = ((0, 0), (0, 0), (ph, kh * bins - h - ph),
               (pw, kw * bins - w - pw))
        if ptype == "max":
            xp = jnp.pad(x, pad, constant_values=-jnp.inf)
            red = jax.lax.reduce_window(
                xp, -jnp.inf, jax.lax.max,
                (1, 1, kh, kw), (1, 1, kh, kw), "VALID")
            red = jnp.where(jnp.isneginf(red), 0.0, red)
        else:
            # reference AvgPool divides by the FULL kernel size
            # (exclusive=false): padded zeros count in the denominator
            xp = jnp.pad(x, pad)
            red = jax.lax.reduce_window(
                xp, 0.0, jax.lax.add,
                (1, 1, kh, kw), (1, 1, kh, kw), "VALID") / (kh * kw)
        outs.append(red.reshape(n, -1))
    return {"Out": jnp.concatenate(outs, axis=-1).astype(x.dtype)}


# ---------------------------------------------------- proximal optimizers

def _prox(prox_param, lr, l1, l2):
    if l1 > 0:
        return (jnp.sign(prox_param)
                * jnp.maximum(jnp.abs(prox_param) - lr * l1, 0.0)
                / (1.0 + lr * l2))
    return prox_param / (1.0 + lr * l2)


@register_op("proximal_gd", grad=False)
def proximal_gd(ctx, ins, attrs):
    """reference optimizers/proximal_gd_op.h."""
    p = x_of(ins, "Param")
    g = x_of(ins, "Grad")
    lr = jnp.reshape(x_of(ins, "LearningRate"), ())
    l1 = float(attrs.get("l1", 0.0))
    l2 = float(attrs.get("l2", 0.0))
    return {"ParamOut": _prox(p - lr * g, lr, l1, l2).astype(p.dtype)}


@register_op("proximal_adagrad", grad=False)
def proximal_adagrad(ctx, ins, attrs):
    """reference optimizers/proximal_adagrad_op.h."""
    p = x_of(ins, "Param")
    m = x_of(ins, "Moment")
    g = x_of(ins, "Grad")
    lr = jnp.reshape(x_of(ins, "LearningRate"), ())
    l1 = float(attrs.get("l1", 0.0))
    l2 = float(attrs.get("l2", 0.0))
    m_out = m + g * g
    prox_param = p - lr * g / jnp.sqrt(m_out)
    return {"ParamOut": _prox(prox_param, lr, l1, l2).astype(p.dtype),
            "MomentOut": m_out.astype(m.dtype)}


@register_op("average_accumulates", grad=False, infer_shape=False)
def average_accumulates(ctx, ins, attrs):
    """ModelAverage accumulator update (reference
    average_accumulates_op.h). Scalar state rides as [1] int64 tensors;
    the reference's host-side branches become jnp.where so the op stays
    jittable."""
    k_max = 16384  # kMaxNumAccumulates
    param = x_of(ins, "param")
    s1 = x_of(ins, "in_sum_1")
    s2 = x_of(ins, "in_sum_2")
    s3 = x_of(ins, "in_sum_3")
    num_acc = jnp.reshape(x_of(ins, "in_num_accumulates"), ()).astype(
        int64_t())
    old_num = jnp.reshape(x_of(ins, "in_old_num_accumulates"), ()).astype(
        int64_t())
    num_upd = jnp.reshape(x_of(ins, "in_num_updates"), ()).astype(int64_t())
    avg_win = float(attrs.get("average_window", 0.0))
    # clamp to int32 range: jax runs x32 by default and the reference's
    # INT64_MAX sentinel would overflow
    max_win = min(int(attrs.get("max_average_window", 1 << 62)), 2**31 - 1)
    min_win = int(attrs.get("min_average_window", 10000))

    num_upd = num_upd + 1
    num_acc = num_acc + 1
    o1 = s1 + param
    o2 = s2
    o3 = s3
    spill = num_upd % k_max == 0
    o2 = jnp.where(spill, o2 + o1, o2)
    o1 = jnp.where(spill, jnp.zeros_like(o1), o1)
    window = jnp.minimum(
        jnp.asarray(max_win, int64_t()),
        (num_upd.astype(jnp.float32) * avg_win).astype(int64_t()))
    roll = (num_acc >= min_win) & (num_acc >= window)
    o3 = jnp.where(roll, o1 + o2, o3)
    o1 = jnp.where(roll, jnp.zeros_like(o1), o1)
    o2 = jnp.where(roll, jnp.zeros_like(o2), o2)
    old_num = jnp.where(roll, num_acc, old_num)
    num_acc = jnp.where(roll, jnp.zeros_like(num_acc), num_acc)
    return {"out_sum_1": o1, "out_sum_2": o2, "out_sum_3": o3,
            "out_num_accumulates": num_acc.reshape(1),
            "out_old_num_accumulates": old_num.reshape(1),
            "out_num_updates": num_upd.reshape(1)}


# ----------------------------------------------------------- tensor array

@register_op("tensor_array_to_tensor", grad=False, infer_shape=False)
def tensor_array_to_tensor(ctx, ins, attrs):
    """reference tensor_array_to_tensor_op.cc: concat (or stack, with
    use_stack) a LoDTensorArray along `axis`; OutIndex records each
    entry's size along that axis."""
    arr = ctx.env[attrs["array_name"]]
    axis = int(attrs.get("axis", 0))
    if bool(attrs.get("use_stack", False)):
        out = jnp.stack(arr, axis=axis)
        sizes = [1] * len(arr)
    else:
        out = jnp.concatenate(arr, axis=axis)
        sizes = [int(a.shape[axis]) for a in arr]
    return {"Out": out, "OutIndex": jnp.asarray(sizes, jnp.int32)}


# ----------------------------------------------------- sequence tagging

_CHUNK_SCHEMES = {
    # scheme -> (num_tag_types, begin, inside, end, single); -1 = absent
    "IOB": (2, 0, 1, -1, -1),
    "IOE": (2, -1, 0, 1, -1),
    "IOBES": (4, 0, 1, 2, 3),
    "plain": (1, -1, -1, -1, -1),
}


def _chunk_masks(labels, lengths, num_types, scheme):
    """Vectorized GetSegments (reference chunk_eval_op.h): returns
    (begin[B,T], end[B,T], type[B,T]). A chunk is open after position i
    iff type[i] != Other, so begin/end reduce to pairwise tag tests."""
    n_tag, t_beg, t_in, t_end, t_sgl = _CHUNK_SCHEMES[scheme]
    other = num_types
    tag = labels % n_tag
    typ = labels // n_tag
    B, T = labels.shape
    pos = jnp.arange(T)
    valid = pos[None, :] < lengths[:, None]
    typ = jnp.where(valid, typ, other)  # pad acts like Other

    # prev arrays (initial state: tag=-1, type=Other)
    ptag = jnp.concatenate(
        [jnp.full((B, 1), -1, tag.dtype), tag[:, :-1]], axis=1)
    ptyp = jnp.concatenate(
        [jnp.full((B, 1), other, typ.dtype), typ[:, :-1]], axis=1)
    # next arrays (final state: type=Other ends any open chunk)
    ntag = jnp.concatenate(
        [tag[:, 1:], jnp.full((B, 1), -1, tag.dtype)], axis=1)
    ntyp = jnp.concatenate(
        [typ[:, 1:], jnp.full((B, 1), other, typ.dtype)], axis=1)

    def chunk_begin(pt, pty, t, ty):
        in_prev = pty != other
        cur = ty != other
        tagged = ((t == t_beg) | (t == t_sgl)
                  | ((t == t_in) & ((pt == t_end) | (pt == t_sgl)))
                  | ((t == t_end) & ((pt == t_end) | (pt == t_sgl))))
        return cur & (~in_prev | (ty != pty) | tagged)

    begin = chunk_begin(ptag, ptyp, tag, typ)
    # chunk ends at i iff one begins at i+1's "end test": symmetric —
    # a chunk open at i ends at i iff position i+1 is not a continuation
    def chunk_end(t, ty, nt, nty):
        opened = ty != other
        nxt_other = nty != ty
        tagged = ((nt == t_beg) | (nt == t_sgl)
                  | (t == t_end) | (t == t_sgl))
        return opened & (nxt_other | tagged)

    end = chunk_end(tag, typ, ntag, ntyp)
    return begin & valid, end & valid, typ


@register_op("chunk_eval", grad=False, infer_shape=False)
def chunk_eval(ctx, ins, attrs):
    """reference chunk_eval_op.h over padded [B, T] + SeqLength [B]
    batches (the reference's own padding path). Matching: an inference
    chunk is correct iff a label chunk begins at the same position with
    the same type and ends at the same position."""
    inference = x_of(ins, "Inference").reshape(
        ins["Inference"][0].shape[0], -1).astype(int64_t())
    label = x_of(ins, "Label").reshape(
        ins["Label"][0].shape[0], -1).astype(int64_t())
    seq_len = ins.get("SeqLength")
    B, T = label.shape
    if seq_len:
        lengths = jnp.reshape(seq_len[0], (-1,)).astype(jnp.int32)
    else:
        lengths = jnp.full((B,), T, jnp.int32)
    num_types = int(attrs["num_chunk_types"])
    scheme = attrs.get("chunk_scheme", "IOB")
    excluded = [int(e) for e in attrs.get("excluded_chunk_types", [])]

    ib, ie, ityp = _chunk_masks(inference, lengths, num_types, scheme)
    lb, le, ltyp = _chunk_masks(label, lengths, num_types, scheme)

    def next_end(end):
        # for each position, the index of the first end >= that position
        T_ = end.shape[1]
        idx = jnp.where(end, jnp.arange(T_)[None, :], T_ * 2)
        # reverse cumulative minimum
        rev = jnp.flip(idx, axis=1)
        run = jax.lax.associative_scan(jnp.minimum, rev, axis=1)
        return jnp.flip(run, axis=1)

    i_end = next_end(ie)
    l_end = next_end(le)

    def count(begin, typ):
        keep = begin
        for e in excluded:
            keep = keep & (typ != e)
        return keep

    ikeep = count(ib, ityp)
    lkeep = count(lb, ltyp)
    correct = (ikeep & lkeep & (ityp == ltyp) & (i_end == l_end))
    n_inf = jnp.sum(ikeep).astype(int64_t())
    n_lab = jnp.sum(lkeep).astype(int64_t())
    n_cor = jnp.sum(correct).astype(int64_t())
    prec = jnp.where(n_inf > 0, n_cor / jnp.maximum(n_inf, 1), 0.0)
    rec = jnp.where(n_lab > 0, n_cor / jnp.maximum(n_lab, 1), 0.0)
    f1 = jnp.where(n_cor > 0, 2 * prec * rec /
                   jnp.maximum(prec + rec, 1e-38), 0.0)
    return {"Precision": prec.astype(jnp.float32).reshape(1),
            "Recall": rec.astype(jnp.float32).reshape(1),
            "F1-Score": f1.astype(jnp.float32).reshape(1),
            "NumInferChunks": n_inf.reshape(1),
            "NumLabelChunks": n_lab.reshape(1),
            "NumCorrectChunks": n_cor.reshape(1)}


# -------------------------------------------------------- beam decode

@register_op("beam_search_decode", grad=False, infer_shape=False)
def beam_search_decode(ctx, ins, attrs):
    """Final gather of a beam search (reference
    beam_search_decode_op.cc). The reference walks LoD parent links over
    TensorArrays; here the padded form takes the per-step stacks the
    beam_search op emits — Ids/ParentIdx [T, B, beam] and Scores
    [T, B, beam] — and backtraces to SentenceIds [B, beam, T] +
    SentenceScores [B, beam] (the final cumulative log-prob per beam)."""
    ids = x_of(ins, "Ids").astype(jnp.int32)
    parents = x_of(ins, "ParentIdx").astype(jnp.int32)
    scores = x_of(ins, "Scores")
    T = ids.shape[0]

    def step(beam_idx, t):
        tok = jnp.take_along_axis(ids[t], beam_idx, axis=-1)
        parent = jnp.take_along_axis(parents[t], beam_idx, axis=-1)
        return parent, tok

    init = jnp.broadcast_to(
        jnp.arange(ids.shape[2], dtype=jnp.int32), ids.shape[1:])
    _, toks = jax.lax.scan(step, init, jnp.arange(T - 1, -1, -1))
    sent = jnp.flip(toks, axis=0)                      # [T, B, beam]
    return {"SentenceIds": jnp.transpose(sent, (1, 2, 0)),
            "SentenceScores": scores[-1]}


@register_op("runtime_assert", grad=False, infer_shape=False)
def runtime_assert(ctx, ins, attrs):
    """Host-checked runtime assertion: raises `msg` when Cond is false.
    The [1] int64 zero output exists to be folded into downstream values
    so XLA cannot dead-code-eliminate the check (used by the
    dygraph_to_static tensor-list overflow guard; the reference's analog
    is PADDLE_ENFORCE inside its CPU kernels)."""
    import numpy as _np
    cond = x_of(ins, "Cond")
    msg = attrs.get("msg", "runtime_assert failed")

    def chk(c):
        if not bool(_np.asarray(c).reshape(-1)[0]):
            raise RuntimeError(msg)
        # int32: a 64-bit callback result needs jax_enable_x64
        return _np.zeros((1,), _np.int32)

    if attrs.get("ordered", False):
        # assert statements (dygraph_to_static convert_assert) have no
        # downstream consumer to fold Out into; an ordered io_callback
        # has token-ordering effects, so XLA cannot dead-code-eliminate
        # the check the way it may an unused pure callback
        from jax.experimental import io_callback
        out = io_callback(chk, jax.ShapeDtypeStruct((1,), _np.int32),
                          cond, ordered=True)
        return {"Out": out}
    out = jax.pure_callback(
        chk, jax.ShapeDtypeStruct((1,), _np.int32), cond)
    return {"Out": out}
