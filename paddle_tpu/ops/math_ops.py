"""Elementwise / matmul / reduction math ops.

TPU-native lowerings for the reference's elementwise family
(/root/reference/paddle/fluid/operators/elementwise/, with fluid's `axis`
mid-broadcast semantics), matmul/mul
(/root/reference/paddle/fluid/operators/matmul_op.cc, mul_op.cc) and
reductions (/root/reference/paddle/fluid/operators/reduce_ops/). Matmuls are
emitted as single jnp.matmul/dot_general calls so XLA tiles them onto the MXU;
bf16 inputs hit the systolic array natively.
"""
import jax
import jax.numpy as jnp
import numpy as np

from ..framework.registry import register_op
from .common import x_of, bcast_y, reduce_axes, host_concrete


def _ew(name, fn, grad=None):
    @register_op(name, grad=grad)
    def _op(ctx, ins, attrs, _fn=fn):
        x = x_of(ins)
        y = bcast_y(x, x_of(ins, "Y"), attrs.get("axis", -1))
        if host_concrete(x, y):
            # host-side shape arithmetic (see common.host_concrete):
            # jnp.* names match their numpy originals. numpy's 64-bit
            # promotions (int/int div -> f64, int+f32 -> f64) are
            # narrowed to match jax's x64-off promotion rules.
            nfn = getattr(np, _fn.__name__, None)
            if nfn is not None:
                out = np.asarray(nfn(x, y))
                if out.dtype == np.float64:
                    out = out.astype(np.float32)
                elif out.dtype in (np.int64, np.uint64):
                    out = out.astype(np.int32)
                return {"Out": out}
        return {"Out": _fn(x, y)}
    return _op


_ew("elementwise_add", jnp.add)
_ew("elementwise_sub", jnp.subtract)
_ew("elementwise_mul", jnp.multiply)
_ew("elementwise_div", jnp.divide)
_ew("elementwise_min", jnp.minimum)
_ew("elementwise_max", jnp.maximum)
_ew("elementwise_pow", jnp.power)
_ew("elementwise_mod", jnp.mod, grad=False)
_ew("elementwise_floordiv", jnp.floor_divide, grad=False)


@register_op("sum")
def sum_op(ctx, ins, attrs):
    xs = ins["X"]
    from ..framework.selected_rows import (is_selected_rows, merge,
                                           to_dense)
    if any(is_selected_rows(x) for x in xs):
        if all(is_selected_rows(x) for x in xs):
            # sparse + sparse: keep sparse (reference sum_op SelectedRows
            # branch); duplicates coalesce at apply time
            return {"Out": merge(xs)}
        dense_shape = next(x.shape for x in xs if not is_selected_rows(x))
        out = None
        for x in xs:
            d = to_dense(x, dense_shape) if is_selected_rows(x) else x
            out = d if out is None else out + d
        return {"Out": out}
    out = xs[0]
    for x in xs[1:]:
        out = out + x
    return {"Out": out}


@register_op("scale")
def scale(ctx, ins, attrs):
    x = x_of(ins)
    s = ins.get("ScaleTensor")
    s = s[0] if s else attrs.get("scale", 1.0)
    b = attrs.get("bias", 0.0)
    if host_concrete(x, s):
        # host-side shape arithmetic (common.host_concrete)
        out = x * s + b if attrs.get("bias_after_scale", True) \
            else (x + b) * s
        return {"Out": np.asarray(out, x.dtype)}
    if attrs.get("bias_after_scale", True):
        out = x * s + jnp.asarray(b, x.dtype)
    else:
        out = (x + jnp.asarray(b, x.dtype)) * s
    return {"Out": out.astype(x.dtype)}


@register_op("matmul")
def matmul(ctx, ins, attrs):
    x, y = x_of(ins), x_of(ins, "Y")
    if attrs.get("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    out = jnp.matmul(x, y)
    alpha = attrs.get("alpha", 1.0)
    if alpha != 1.0:
        out = out * jnp.asarray(alpha, out.dtype)
    return {"Out": out}


@register_op("matmul_v2")
def matmul_v2(ctx, ins, attrs):
    x, y = x_of(ins), x_of(ins, "Y")
    if attrs.get("trans_x", False):
        x = jnp.swapaxes(x, -1, -2) if x.ndim > 1 else x
    if attrs.get("trans_y", False):
        y = jnp.swapaxes(y, -1, -2) if y.ndim > 1 else y
    return {"Out": jnp.matmul(x, y)}


@register_op("mul")
def mul(ctx, ins, attrs):
    """Flattening matmul (reference operators/mul_op.cc): X flattened to 2D
    at x_num_col_dims, Y at y_num_col_dims."""
    x, y = x_of(ins), x_of(ins, "Y")
    xn = attrs.get("x_num_col_dims", 1)
    yn = attrs.get("y_num_col_dims", 1)
    xshape = x.shape
    x2 = x.reshape(int(np.prod(xshape[:xn])), -1)
    y2 = y.reshape(int(np.prod(y.shape[:yn])), -1)
    out = x2 @ y2
    return {"Out": out.reshape(xshape[:xn] + y.shape[yn:])}


@register_op("bmm")
def bmm(ctx, ins, attrs):
    return {"Out": jnp.matmul(x_of(ins), x_of(ins, "Y"))}


@register_op("dot")
def dot(ctx, ins, attrs):
    x, y = x_of(ins), x_of(ins, "Y")
    return {"Out": jnp.sum(x * y, axis=-1, keepdims=x.ndim == 1)}


def _reduce(name, fn, grad=None):
    @register_op(name, grad=grad)
    def _op(ctx, ins, attrs, _fn=fn):
        x = x_of(ins)
        axes, keep = reduce_axes(attrs, x.ndim)
        return {"Out": _fn(x, axis=axes, keepdims=keep)}
    return _op


_reduce("reduce_sum", jnp.sum)
_reduce("reduce_mean", jnp.mean)
_reduce("reduce_max", jnp.max)
_reduce("reduce_min", jnp.min)
_reduce("reduce_prod", jnp.prod)
_reduce("reduce_all", jnp.all, grad=False)
_reduce("reduce_any", jnp.any, grad=False)


@register_op("logsumexp")
def logsumexp(ctx, ins, attrs):
    x = x_of(ins)
    # accept both attr spellings: dim/keep_dim (reduce_* family, the
    # reference's python/paddle/tensor/math.py logsumexp composition) and
    # axis/keepdim (Paddle 2.x user-facing spelling)
    attrs = dict(attrs)
    if "axis" in attrs:
        attrs.setdefault("dim", attrs["axis"])
    if "keepdim" in attrs:
        attrs.setdefault("keep_dim", attrs["keepdim"])
    axes, keep = reduce_axes(attrs, x.ndim)
    return {"Out": jax.scipy.special.logsumexp(x, axis=axes, keepdims=keep)}


@register_op("mean")
def mean(ctx, ins, attrs):
    return {"Out": jnp.mean(x_of(ins))}


@register_op("clip")
def clip(ctx, ins, attrs):
    x = x_of(ins)
    return {"Out": jnp.clip(x, attrs.get("min"), attrs.get("max"))}


@register_op("clip_by_norm")
def clip_by_norm(ctx, ins, attrs):
    x = x_of(ins)
    max_norm = attrs["max_norm"]
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.where(norm > max_norm, max_norm / jnp.maximum(norm, 1e-12),
                      1.0)
    return {"Out": x * scale.astype(x.dtype)}


@register_op("squared_l2_norm")
def squared_l2_norm(ctx, ins, attrs):
    x = x_of(ins)
    return {"Out": jnp.sum(jnp.square(x))}


@register_op("p_norm")
def p_norm(ctx, ins, attrs):
    x = x_of(ins)
    p = attrs.get("porder", 2.0)
    axis = attrs.get("axis", -1)
    keep = attrs.get("keepdim", False)
    out = jnp.sum(jnp.abs(x) ** p, axis=axis, keepdims=keep) ** (1.0 / p)
    return {"Out": out}


@register_op("norm")
def norm(ctx, ins, attrs):
    """l2_normalize (reference operators/norm_op.cc)."""
    x = x_of(ins)
    axis = attrs.get("axis", -1)
    eps = attrs.get("epsilon", 1e-10)
    norm = jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)
    return {"Out": x / norm, "Norm": norm}


@register_op("maximum")
def maximum(ctx, ins, attrs):
    return {"Out": jnp.maximum(x_of(ins), x_of(ins, "Y"))}


@register_op("minimum")
def minimum(ctx, ins, attrs):
    return {"Out": jnp.minimum(x_of(ins), x_of(ins, "Y"))}


def _cmp(name, fn):
    @register_op(name, grad=False)
    def _op(ctx, ins, attrs, _fn=fn):
        x = x_of(ins)
        y = bcast_y(x, x_of(ins, "Y"), attrs.get("axis", -1))
        return {"Out": _fn(x, y)}
    return _op


_cmp("less_than", jnp.less)
_cmp("less_equal", jnp.less_equal)
_cmp("greater_than", jnp.greater)
_cmp("greater_equal", jnp.greater_equal)
_cmp("equal", jnp.equal)
_cmp("not_equal", jnp.not_equal)


@register_op("logical_and", grad=False)
def logical_and(ctx, ins, attrs):
    return {"Out": jnp.logical_and(x_of(ins), x_of(ins, "Y"))}


@register_op("logical_or", grad=False)
def logical_or(ctx, ins, attrs):
    return {"Out": jnp.logical_or(x_of(ins), x_of(ins, "Y"))}


@register_op("logical_xor", grad=False)
def logical_xor(ctx, ins, attrs):
    return {"Out": jnp.logical_xor(x_of(ins), x_of(ins, "Y"))}


@register_op("logical_not", grad=False)
def logical_not(ctx, ins, attrs):
    return {"Out": jnp.logical_not(x_of(ins))}


@register_op("isfinite", grad=False)
def isfinite(ctx, ins, attrs):
    x = x_of(ins)
    return {"Out": jnp.all(jnp.isfinite(x)).reshape(1)}


@register_op("isfinite_v2", grad=False)
def isfinite_v2(ctx, ins, attrs):
    return {"Out": jnp.isfinite(x_of(ins))}


@register_op("isinf_v2", grad=False)
def isinf_v2(ctx, ins, attrs):
    return {"Out": jnp.isinf(x_of(ins))}


@register_op("isnan_v2", grad=False)
def isnan_v2(ctx, ins, attrs):
    return {"Out": jnp.isnan(x_of(ins))}


@register_op("kron")
def kron(ctx, ins, attrs):
    return {"Out": jnp.kron(x_of(ins), x_of(ins, "Y"))}


@register_op("trace")
def trace(ctx, ins, attrs):
    x = x_of(ins, "Input")
    return {"Out": jnp.trace(x, offset=attrs.get("offset", 0),
                             axis1=attrs.get("axis1", 0),
                             axis2=attrs.get("axis2", 1))}


@register_op("addmm")
def addmm(ctx, ins, attrs):
    inp = x_of(ins, "Input")
    x, y = x_of(ins), x_of(ins, "Y")
    return {"Out": attrs.get("Beta", 1.0) * inp +
            attrs.get("Alpha", 1.0) * (x @ y)}


@register_op("cholesky")
def cholesky(ctx, ins, attrs):
    x = x_of(ins)
    if attrs.get("upper", False):
        return {"Out": jnp.linalg.cholesky(x).swapaxes(-1, -2)}
    return {"Out": jnp.linalg.cholesky(x)}


@register_op("inverse")
def inverse(ctx, ins, attrs):
    return {"Output": jnp.linalg.inv(x_of(ins, "Input"))}


@register_op("matrix_power")
def matrix_power(ctx, ins, attrs):
    return {"Out": jnp.linalg.matrix_power(x_of(ins), attrs["n"])}


@register_op("einsum")
def einsum(ctx, ins, attrs):
    """Einstein summation over the Operands list (paddle 2.x einsum API;
    also the internal attention path's way to express head-split matmuls
    without materializing transposed copies — XLA folds the permutations
    into the dot's dimension numbers)."""
    ops = [jnp.asarray(v) for v in ins["Operands"]]
    return {"Out": jnp.einsum(attrs["equation"], *ops)}
