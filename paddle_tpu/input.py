"""Top-level input helpers (reference python/paddle/fluid/input.py):
`fluid.one_hot` and `fluid.embedding` — the v2 semantics that drop the
v1 layers' trailing-[.,1] conventions: one_hot APPENDS the depth axis
(input.py:24), embedding accepts ids of any rank and appends the
emb_size axis via lookup_table_v2 (input.py:127)."""
from .layers.layer_helper import LayerHelper


def one_hot(input, depth, allow_out_of_range=False):
    """fluid.one_hot: out.shape = input.shape + [depth] (reference
    input.py:24; contrast layers.one_hot, which keeps the v1 squeeze
    of a trailing [., 1] dim)."""
    helper = LayerHelper("one_hot_v2")
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(type="one_hot_v2", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"depth": depth,
                            "allow_out_of_range": allow_out_of_range})
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32"):
    """fluid.embedding: ids of ANY rank, out.shape = ids.shape +
    [emb_size] (reference input.py:127 -> lookup_table_v2; contrast
    layers.embedding's v1 lookup_table). Shares the emission body —
    incl. negative-padding_idx normalization — with layers.embedding."""
    from .layers.nn import _emit_embedding
    return _emit_embedding("lookup_table_v2", input, size, is_sparse,
                           is_distributed, padding_idx, param_attr,
                           dtype)
