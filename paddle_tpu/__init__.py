"""paddle_tpu — a TPU-native deep-learning framework with the capabilities of
PaddlePaddle Fluid 1.7 (reference at /root/reference), re-designed for
JAX/XLA/Pallas/pjit: a serializable program IR lowered to single XLA modules,
GSPMD sharding over a named-axis device mesh instead of NCCL rings, and
functional state threading instead of in-place scope mutation.

The top-level namespace mirrors `paddle.fluid`.
"""
from .framework.core import (  # noqa: F401
    Program, Variable, Operator, Block, Parameter,
    default_main_program, default_startup_program, program_guard,
    switch_main_program, switch_startup_program,
    CPUPlace, CUDAPlace, CUDAPinnedPlace, TPUPlace, OpRole,
    grad_var_name, ComplexVariable,
    name_scope, device_guard, require_version,
)
from .framework.executor import (  # noqa: F401
    Executor, FetchHandler, Scope, global_scope, scope_guard,
)
from .framework.backward import append_backward, gradients  # noqa: F401
from .framework import backward  # noqa: F401  (fluid.backward module)
from .framework import initializer  # noqa: F401
from .framework import unique_name  # noqa: F401
from .framework import passes  # noqa: F401  (Pass/register_pass/apply_passes)
from .framework.dtype import convert_dtype  # noqa: F401
from .param_attr import ParamAttr, WeightNormParamAttr  # noqa: F401
from . import layers  # noqa: F401
from . import optimizer  # noqa: F401
from . import regularizer  # noqa: F401
from . import nets  # noqa: F401
from . import dataset  # noqa: F401
from . import clip  # noqa: F401
from .parallel.compiler import (  # noqa: F401
    CompiledProgram, BuildStrategy, ExecutionStrategy, ParallelExecutor,
)
from . import parallel  # noqa: F401
from .layers.tensor import data  # noqa: F401
from .dataio import DataLoader, PyReader, DataFeeder, DatasetFactory  # noqa: F401
from . import dataio  # noqa: F401
from . import io  # noqa: F401
from . import contrib  # noqa: F401
from . import metrics  # noqa: F401
from . import transpiler  # noqa: F401
from . import incubate  # noqa: F401
from . import inference  # noqa: F401
from . import profiler  # noqa: F401
from . import observability  # noqa: F401  (unified telemetry substrate)
from . import flags  # noqa: F401
from . import debugger  # noqa: F401
from . import install_check  # noqa: F401
from . import capi_train  # noqa: F401  (C-native training entry backing)
from .framework.registry import (  # noqa: F401  (custom-op extension point)
    load_op_library, register_grad_lower, register_op)
from . import complex  # noqa: F401  (2.0-preview complex namespace)
from . import nn  # noqa: F401  (2.0-preview namespace)
from . import tensor  # noqa: F401  (2.0-preview namespace)
from .flags import get_flags, set_flags  # noqa: F401
from . import distributed  # noqa: F401
from .transpiler import (  # noqa: F401
    DistributeTranspiler, DistributeTranspilerConfig, GeoSgdTranspiler,
    HashName, RoundRobin, memory_optimize, release_memory,
)
from .lod import (  # noqa: F401
    Tensor, LoDTensor, LoDTensorArray, create_lod_tensor,
    create_random_int_lodtensor,
)
from .trainer_desc import (  # noqa: F401
    TrainerDesc, MultiTrainer, DistMultiTrainer, PipelineTrainer,
)
from .input import embedding, one_hot  # noqa: F401  (v2 semantics)
from .dataio import DataFeedDesc  # noqa: F401
from .dygraph.base import (  # noqa: F401
    enable_dygraph, disable_dygraph, in_dygraph_mode, VarBase,
)
from .layers import learning_rate_scheduler as learning_rate_decay  # noqa: F401
from .io import (  # noqa: F401
    save_params, load_params, save_persistables, load_persistables,
    save_inference_model, load_inference_model, save, load,
    save_checkpoint, load_checkpoint,
    CheckpointSaver,
)
from . import resilience  # noqa: F401
from . import train  # noqa: F401  (elastic training supervisor)
from . import serving  # noqa: F401
from .resilience import (  # noqa: F401
    CheckpointCorruptError, EnforceNotMet, NonFiniteError,
    RpcDeadlineError, WatchdogTimeout,
)
# paddle.reader-style decorator namespace + fluid.dataset module parity
reader = dataio
dataset = dataio

__version__ = "0.1.0"

# `fluid`-style namespace alias so reference user code ports 1:1:
#   import paddle_tpu as fluid
fluid = None  # set below to this module


def _install_alias():
    import sys
    global fluid
    fluid = sys.modules[__name__]


_install_alias()


def is_compiled_with_cuda():
    return False


def is_compiled_with_tpu():
    return True


def cuda_places(device_ids=None):
    import jax
    n = len(jax.devices())
    ids = device_ids if device_ids is not None else range(n)
    return [TPUPlace(i) for i in ids]


def cpu_places(device_count=None):
    return [CPUPlace() for _ in range(device_count or 1)]


def cuda_pinned_places(device_count=None):
    return [CUDAPinnedPlace() for _ in range(device_count or 1)]


def device_count():
    import jax
    return len(jax.devices())
