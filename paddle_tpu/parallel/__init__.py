from .mesh import (  # noqa: F401
    MeshConfig, make_mesh, set_mesh, get_mesh, default_mesh, sharding_for,
    axis_size,
)
from .compiler import CompiledProgram, BuildStrategy, ExecutionStrategy  # noqa: F401
