"""CompiledProgram — data-parallel compilation facade.

Capability parity with the reference's CompiledProgram.with_data_parallel
(/root/reference/python/paddle/fluid/compiler.py:158) and the C++
ParallelExecutor it constructs
(/root/reference/paddle/fluid/framework/parallel_executor.cc:442). TPU-first:
there is no graph replication, no SSA allreduce insertion, no thread pool —
`with_data_parallel` just attaches a Mesh; the Executor pjit-compiles the same
program over it, feeds shard on the batch dim, and GSPMD inserts the gradient
all-reduces the reference built by hand
(ir/multi_devices_graph_pass/multi_devices_graph_pass.cc:456).
"""
from .mesh import default_mesh, get_mesh


class BuildStrategy:
    """Accepted for API parity (reference details/build_strategy.h:37); the
    knobs it carried (fuse_all_reduce, num_trainers, reduce strategy...) are
    XLA/GSPMD decisions now."""

    class ReduceStrategy:
        AllReduce = 0
        Reduce = 1

    class GradientScaleStrategy:
        CoeffNumDevice = 0
        One = 1
        Customized = 2

    def __init__(self):
        self.reduce_strategy = BuildStrategy.ReduceStrategy.AllReduce
        self.gradient_scale_strategy = \
            BuildStrategy.GradientScaleStrategy.CoeffNumDevice
        self.fuse_all_reduce_ops = True
        self.fuse_all_optimizer_ops = False
        self.fuse_elewise_add_act_ops = False
        self.enable_inplace = True
        self.memory_optimize = True
        self.num_trainers = 1
        self.trainer_id = 0
        self.sync_batch_norm = False


class ExecutionStrategy:
    """Reference details/execution_strategy.h:22 — retained for parity."""

    def __init__(self):
        self.num_threads = 0
        self.num_iteration_per_drop_scope = 1
        self.use_experimental_executor = False


class CompiledProgram:
    def __init__(self, program_or_graph, build_strategy=None):
        self.program = program_or_graph
        self.mesh = None
        self.build_strategy = build_strategy or BuildStrategy()
        self.exec_strategy = None
        self.loss_name = None

    def with_data_parallel(self, loss_name=None, build_strategy=None,
                           exec_strategy=None, share_vars_from=None,
                           places=None, mesh=None):
        self.loss_name = loss_name
        if build_strategy is not None:
            self.build_strategy = build_strategy
        self.exec_strategy = exec_strategy
        if mesh is not None:
            self.mesh = mesh
        else:
            self.mesh = get_mesh() or default_mesh(
                len(places) if places else None)
        bs = self.build_strategy
        if bs.gradient_scale_strategy != \
                BuildStrategy.GradientScaleStrategy.CoeffNumDevice:
            import warnings
            warnings.warn(
                "gradient_scale_strategy One/Customized is not honored: "
                "mean-loss over the globally sharded batch already yields "
                "CoeffNumDevice semantics under GSPMD; rescale the loss in "
                "the program instead", stacklevel=2)
        # knobs whose job XLA/GSPMD owns: accepted for parity, but a user
        # who CHANGES one from its default gets a signal, not silence
        _xla_owned = {
            "reduce_strategy": (
                BuildStrategy.ReduceStrategy.AllReduce,
                "GSPMD always emits all-reduce collectives; Reduce-mode "
                "parameter placement does not exist on a TPU mesh"),
            "fuse_all_reduce_ops": (
                True, "XLA fuses/schedules collectives itself"),
            "fuse_all_optimizer_ops": (
                False, "the whole step is one XLA computation; optimizer "
                "ops are already fused by the compiler"),
            "fuse_elewise_add_act_ops": (
                False, "XLA elementwise fusion subsumes this pass"),
            "enable_inplace": (
                True, "buffer reuse is the XLA allocator's decision; "
                "donated inputs are already updated in place"),
            "memory_optimize": (
                True, "XLA owns buffer lifetimes/rematerialization"),
        }
        for knob, (default, why) in _xla_owned.items():
            if getattr(bs, knob, default) != default:
                import warnings
                warnings.warn(
                    "BuildStrategy.%s=%r has no effect: %s"
                    % (knob, getattr(bs, knob), why), stacklevel=2)
        if bs.sync_batch_norm:
            # the reference's sync_batch_norm_pass
            # (framework/ir/sync_batch_norm_pass.cc) rewrites batch_norm ->
            # sync_batch_norm on a graph copy owned by the executor; same
            # here — apply the registered pass to a clone, never the
            # user's Program (framework/passes.py registry)
            if any(op.type == "batch_norm"
                   for blk in self.program.blocks for op in blk.ops):
                from ..framework.passes import apply_passes
                self.program = self.program.clone()
                apply_passes(self.program, ["sync_batch_norm"])
        if self.mesh is not None and "dcn_dp" in self.mesh.axis_names:
            # multi-slice mesh: make the gradient sync EXPLICIT so the
            # executor's hierarchical path can decompose it per fabric
            # (framework/passes.py hier_grad_sync). Applied to a clone,
            # never the user's Program; unconditional for dcn meshes —
            # the inserted ops are identities outside shard_map, so the
            # flat-GSPMD baseline (FLAGS_dcn_hierarchical=False) runs
            # the SAME compiled program and an A/B needs no rebuild
            if not any(op.type == "hier_allreduce"
                       for blk in self.program.blocks for op in blk.ops):
                from ..framework.passes import apply_passes
                self.program = self.program.clone()
                apply_passes(self.program, ["hier_grad_sync"])
        return self

    def with_inference_optimize(self, config=None):
        self.program = self.program.clone(for_test=True)
        return self

    def _compile(self, *args, **kwargs):
        return self


class ParallelExecutor:
    """Legacy multi-device executor front (reference
    parallel_executor.py ParallelExecutor, itself a wrapper over
    CompiledProgram since 1.6): builds a data-parallel CompiledProgram
    over the mesh and runs it through an internal Executor. Kept for
    API parity; CompiledProgram is the first-class path."""

    def __init__(self, use_cuda=True, loss_name=None, main_program=None,
                 share_vars_from=None, exec_strategy=None,
                 build_strategy=None, num_trainers=1, trainer_id=0,
                 scope=None):
        from ..framework.core import default_main_program
        from ..framework.executor import Executor, global_scope
        program = main_program or default_main_program()
        self._compiled = CompiledProgram(
            program, build_strategy=build_strategy).with_data_parallel(
                loss_name=loss_name, exec_strategy=exec_strategy,
                share_vars_from=getattr(share_vars_from, "_compiled",
                                        share_vars_from))
        self._exe = Executor()
        self._scope = scope or global_scope()

    def run(self, fetch_list, feed=None, feed_dict=None,
            return_numpy=True):
        from ..framework.executor import scope_guard
        with scope_guard(self._scope):
            return self._exe.run(self._compiled,
                                 feed=feed if feed is not None
                                 else feed_dict,
                                 fetch_list=fetch_list,
                                 return_numpy=return_numpy)

    def drop_local_exe_scopes(self):
        """reference ParallelExecutor.drop_local_exe_scopes: local
        scopes are XLA-owned buffers here; nothing to drop."""
