"""Device mesh runtime.

TPU-native replacement for the reference's NCCL ring registry
(/root/reference/paddle/fluid/platform/collective_helper.h:62 NCCLCommContext,
nccl_helper.h:91 NCCLContextMap, nccl_helper.h:180 multi-ring/hierarchical
NCCLCommunicator): ONE jax.sharding.Mesh with named axes replaces every ring.
Axis names are the framework-wide contract:

  dp — data parallel        tp — tensor (model) parallel
  pp — pipeline stages      sp — sequence/context parallel
  ep — expert parallel      dcn_dp — cross-slice data parallel (DCN)

Intra-slice traffic rides ICI, cross-slice DCN — both chosen by XLA from the
same named-axis collectives, which is why there is no ring bootstrap, no
NCCL-id RPC (c_gen_nccl_id_op.cc), and no comm/calc stream split here.
``dcn_dp`` is the one axis DECLARED to cross slices: it sits outermost
(the slowest fabric gets the outermost placement, like pp before it), the
comms ledger prices its collectives at DCN bandwidth
(``FLAGS_comms_dcn_axes``), and the executor runs dcn_dp meshes through
the hierarchical grad-sync path (framework/passes.py hier_grad_sync).
"""
import math
from dataclasses import dataclass, field

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

AXIS_ORDER = ("dcn_dp", "pp", "dp", "ep", "sp", "tp")

_current_mesh = None


@dataclass
class MeshConfig:
    dp: int = 1
    tp: int = 1
    pp: int = 1
    sp: int = 1
    ep: int = 1
    dcn_dp: int = 1

    def axis_sizes(self):
        return {"dcn_dp": self.dcn_dp, "pp": self.pp, "dp": self.dp,
                "ep": self.ep, "sp": self.sp, "tp": self.tp}


def make_mesh(config=None, devices=None, **axes):
    """Build a Mesh. tp/sp innermost so their collectives ride the
    fastest ICI links; pp outermost (lowest-bandwidth axis)."""
    if config is None:
        config = MeshConfig(**{k: v for k, v in axes.items() if v})
    devices = devices if devices is not None else jax.devices()
    sizes = config.axis_sizes()
    used = [(name, sizes[name]) for name in AXIS_ORDER if sizes[name] > 1]
    if not used:
        used = [("dp", 1)]
    total = math.prod(s for _, s in used)
    if total > len(devices):
        raise ValueError(f"mesh needs {total} devices, have {len(devices)}")
    dev = np.asarray(devices[:total]).reshape([s for _, s in used])
    return Mesh(dev, tuple(n for n, _ in used))


def set_mesh(mesh):
    global _current_mesh
    _current_mesh = mesh
    return mesh


def get_mesh():
    return _current_mesh


def default_mesh(n_devices=None):
    """All devices on one dp axis — the ParallelExecutor-equivalent default."""
    devices = jax.devices()
    n = n_devices or len(devices)
    return Mesh(np.asarray(devices[:n]), ("dp",))


def set_param_dist_attr(program, name, spec):
    """Annotate a program variable with a mesh-axis sharding spec (the
    model-agnostic helper behind bert/gpt.apply_tp_sharding). Call
    BEFORE optimizer.minimize(): accumulators copy the parameter's
    dist_attr at creation, so annotating afterwards leaves optimizer
    state replicated."""
    var = program.global_block().vars.get(name)
    if var is not None:
        var.dist_attr = tuple(spec)


def partition_spec(mesh, spec, shape=None):
    """Validate a raw axis-name spec against a mesh: unknown axes replicate,
    and (when `shape` is given) axes that don't divide their dim are dropped.
    The single source of truth for spec sanitation — used by param placement,
    feed sharding, and the sharding_constraint op."""
    spec = tuple(spec or ())
    if shape is not None:
        spec = spec[:len(shape)] + (None,) * (len(shape) - len(spec))
    out = []
    for i, a in enumerate(spec):
        if isinstance(a, (tuple, list)):
            # joint sharding of one dim over several axes (the batch dim
            # of a multi-slice mesh shards over ("dcn_dp", "dp")):
            # unknown component axes drop, and the dim must divide by
            # the PRODUCT of the surviving sizes
            sub = tuple(x for x in a if x in mesh.axis_names)
            prod = math.prod(int(mesh.shape[x]) for x in sub) if sub else 1
            if not sub or (shape is not None and shape[i] % prod != 0):
                out.append(None)
            else:
                out.append(sub if len(sub) > 1 else sub[0])
            continue
        if a is None or a not in mesh.axis_names:
            out.append(None)
        elif shape is not None and shape[i] % mesh.shape[a] != 0:
            out.append(None)
        else:
            out.append(a)
    return P(*out)


def sharding_for(mesh, var):
    """NamedSharding for a Variable from its dist_attr annotation
    (None axes replicate)."""
    if var is None or getattr(var, "dist_attr", None) is None:
        return NamedSharding(mesh, P())
    return NamedSharding(mesh, partition_spec(mesh, var.dist_attr,
                                              getattr(var, "shape", None)))


def axis_size(mesh, name):
    return mesh.shape[name] if mesh is not None and name in mesh.axis_names \
        else 1
