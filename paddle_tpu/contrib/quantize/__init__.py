"""fluid.contrib.quantize (reference contrib/quantize/
quantize_transpiler.py QuantizeTranspiler): the pre-slim QAT entry.
Front over the slim QuantizationTransformPass (the same fake-quant
instrumentation the reference's transpiler performs op-by-op)."""

__all__ = ["QuantizeTranspiler"]


class QuantizeTranspiler:
    def __init__(self, weight_bits=8, activation_bits=8,
                 activation_quantize_type="abs_max",
                 weight_quantize_type="abs_max", window_size=10000):
        self.weight_bits = int(weight_bits)
        self.activation_bits = int(activation_bits)
        self.activation_quantize_type = activation_quantize_type
        self.weight_quantize_type = weight_quantize_type
        self.window_size = window_size

    def training_transpile(self, program=None, startup_program=None):
        """Insert fake-quant/dequant ops for QAT (reference
        QuantizeTranspiler.training_transpile)."""
        from ..slim.quantization.quantization_pass import (
            QuantizationTransformPass)
        from ...framework.core import default_main_program
        program = program or default_main_program()
        QuantizationTransformPass(
            weight_bits=self.weight_bits,
            activation_bits=self.activation_bits,
            activation_quantize_type=self.activation_quantize_type,
            weight_quantize_type=self.weight_quantize_type,
            window_size=self.window_size).apply(
                program, startup_program=startup_program)
        return program

    def freeze_program(self, program, place=None, fuse_bn=False,
                       scope=None):
        """Reference freeze_program folds quant scales for inference;
        here the fake-quant graph is already inference-executable (STE
        ops are identity at eval), so freezing is a no-op that returns
        the program."""
        return program
