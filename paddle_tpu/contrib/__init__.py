"""fluid.contrib namespace (reference: python/paddle/fluid/contrib/)."""
from . import mixed_precision  # noqa: F401
from . import slim  # noqa: F401
from . import layers  # noqa: F401
from .layers import *  # noqa: F401,F403  (reference: from .layers import *)
from . import decoder  # noqa: F401
from .decoder import (  # noqa: F401
    InitState, StateCell, TrainingDecoder, BeamSearchDecoder,
)
from . import memory_usage_calc  # noqa: F401
from .memory_usage_calc import memory_usage  # noqa: F401
from . import op_frequence  # noqa: F401
from .op_frequence import op_freq_statistic  # noqa: F401
from . import quantize  # noqa: F401
from .quantize import QuantizeTranspiler  # noqa: F401
from . import reader  # noqa: F401
from .reader import distributed_batch_reader  # noqa: F401
from . import utils  # noqa: F401
from .utils import (  # noqa: F401
    HDFSClient, multi_download, multi_upload,
    convert_dist_to_sparse_program, load_persistables_for_increment,
    load_persistables_for_inference,
)
from . import extend_optimizer  # noqa: F401
from .extend_optimizer import extend_with_decoupled_weight_decay  # noqa: F401
