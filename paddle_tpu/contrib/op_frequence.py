"""Op-frequency statistics (reference contrib/op_frequence.py
op_freq_statistic): unigram op-type counts and adjacent-pair counts
over a program, both sorted descending."""
from collections import Counter, OrderedDict

__all__ = ["op_freq_statistic"]


def op_freq_statistic(program):
    from ..framework.core import Program
    if not isinstance(program, Program):
        raise TypeError("op_freq_statistic expects a Program")
    uni = Counter()
    adj = Counter()
    for block in program.blocks:
        prev = None
        for op in block.ops:
            uni[op.type] += 1
            if prev is not None:
                adj[f"{prev}->{op.type}"] += 1
            prev = op.type
    uni_sorted = OrderedDict(sorted(uni.items(), key=lambda kv: -kv[1]))
    adj_sorted = OrderedDict(sorted(adj.items(), key=lambda kv: -kv[1]))
    return uni_sorted, adj_sorted
