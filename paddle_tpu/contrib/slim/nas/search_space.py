"""Search-space contract (reference contrib/slim/nas/search_space.py):
a space exposes init_tokens / range_table / create_net(tokens)."""


class SearchSpace:
    def init_tokens(self):
        """Initial token vector."""
        raise NotImplementedError

    def range_table(self):
        """Per-position token range: tokens[i] in [0, range_table()[i])."""
        raise NotImplementedError

    def create_net(self, tokens=None):
        """Build (startup_program, train_program, eval_program, ...) or
        any model handle for the given tokens."""
        raise NotImplementedError
