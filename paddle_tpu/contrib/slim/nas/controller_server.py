"""Controller server/client (reference
contrib/slim/nas/controller_server.py + search_agent.py): one process
hosts the SA controller; distributed search clients request next_tokens
and report rewards over TCP (json lines)."""
import json
import socket
import threading


class ControllerServer:
    def __init__(self, controller, address=("127.0.0.1", 0),
                 max_client_num=64, search_steps=None):
        self._controller = controller
        self._address = address
        self._max_clients = max_client_num
        self._search_steps = search_steps
        self._sock = None
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind(self._address)
        self._sock.listen(self._max_clients)
        self._sock.settimeout(0.2)
        self._thread = threading.Thread(target=self._loop, daemon=True)
        self._thread.start()
        return self._sock.getsockname()

    def _loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()
        try:
            self._sock.close()
        except OSError:
            pass

    def _serve(self, conn):
        with conn:
            buf = b""
            while not self._stop.is_set():
                try:
                    chunk = conn.recv(65536)
                except OSError:
                    return
                if not chunk:
                    return
                buf += chunk
                while b"\n" in buf:
                    line, buf = buf.split(b"\n", 1)
                    msg = json.loads(line)
                    with self._lock:
                        if msg["cmd"] == "next_tokens":
                            out = {"tokens": self._controller.next_tokens(
                                msg.get("tokens"))}
                        elif msg["cmd"] == "update":
                            self._controller.update(msg["tokens"],
                                                    float(msg["reward"]))
                            out = {"ok": True,
                                   "best": self._controller.best_tokens,
                                   "max_reward":
                                       self._controller.max_reward}
                        elif msg["cmd"] == "stop":
                            self._stop.set()
                            out = {"ok": True}
                        else:
                            out = {"err": f"unknown {msg['cmd']!r}"}
                    conn.sendall((json.dumps(out) + "\n").encode())

    def close(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


class ControllerClient:
    def __init__(self, address):
        self._address = tuple(address)

    def _call(self, msg):
        with socket.create_connection(self._address, timeout=30) as s:
            s.sendall((json.dumps(msg) + "\n").encode())
            buf = b""
            while b"\n" not in buf:
                chunk = s.recv(65536)
                if not chunk:
                    raise ConnectionError("controller server closed")
                buf += chunk
        return json.loads(buf.split(b"\n", 1)[0])

    def next_tokens(self, tokens=None):
        return self._call({"cmd": "next_tokens", "tokens": tokens})["tokens"]

    def update(self, tokens, reward):
        return self._call({"cmd": "update", "tokens": list(tokens),
                           "reward": float(reward)})

    def stop(self):
        try:
            self._call({"cmd": "stop"})
        except (ConnectionError, OSError):
            pass
