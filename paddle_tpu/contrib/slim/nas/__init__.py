"""slim NAS (reference contrib/slim/nas/: light_nas_strategy.py,
controller_server.py, search_space.py + slim/searcher/controller.py
SAController): simulated-annealing architecture search with an optional
TCP controller server so distributed clients share one controller."""
from .controller import EvolutionaryController, SAController  # noqa: F401
from .controller_server import ControllerServer, ControllerClient  # noqa: F401
from .search_space import SearchSpace  # noqa: F401
from .light_nas_strategy import LightNASStrategy  # noqa: F401
