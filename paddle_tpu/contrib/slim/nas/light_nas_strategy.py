"""Light-NAS search driver (reference
contrib/slim/nas/light_nas_strategy.py): wraps a SearchSpace + SA
controller; each search step proposes tokens, the caller's eval_func
trains/evaluates the candidate and returns a reward (optionally
penalized by a latency/flops constraint)."""
from .controller import SAController
from .controller_server import ControllerClient, ControllerServer


class LightNASStrategy:
    def __init__(self, search_space, eval_func, search_steps=50,
                 reduce_rate=0.85, init_temperature=1024,
                 server_address=None, constrain_func=None, seed=None):
        """eval_func(tokens) -> reward (higher is better)."""
        self._space = search_space
        self._eval = eval_func
        self._steps = int(search_steps)
        self._controller = SAController(
            reduce_rate=reduce_rate, init_temperature=init_temperature,
            seed=seed)
        self._controller.reset(search_space.range_table(),
                               search_space.init_tokens(),
                               constrain_func)
        self._server = None
        self._client = None
        if server_address is not None:
            self._server = ControllerServer(self._controller,
                                            address=server_address)
            addr = self._server.start()
            self._client = ControllerClient(addr)

    def search(self):
        """Run the SA loop; returns (best_tokens, max_reward)."""
        ctrl = self._client or self._controller
        try:
            for _ in range(self._steps):
                tokens = ctrl.next_tokens()
                reward = float(self._eval(tokens))
                ctrl.update(tokens, reward)
        finally:
            if self._server is not None:
                self._server.close()
        return self._controller.best_tokens, self._controller.max_reward
