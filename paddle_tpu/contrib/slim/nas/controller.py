"""Token-space controllers (reference
contrib/slim/searcher/controller.py:59 SAController)."""
import math

import numpy as np


class EvolutionaryController:
    def reset(self, range_table, init_tokens, constrain_func=None):
        raise NotImplementedError

    def next_tokens(self, control_token=None):
        raise NotImplementedError

    def update(self, tokens, reward):
        raise NotImplementedError


class SAController(EvolutionaryController):
    """Simulated annealing over integer token vectors: accept a worse
    reward with prob exp((r - r_cur)/T), T decaying by reduce_rate per
    iteration (reference controller.py:105-150)."""

    def __init__(self, range_table=None, reduce_rate=0.85,
                 init_temperature=1024, max_iter_number=300, seed=None):
        self._range_table = range_table
        self._reduce_rate = float(reduce_rate)
        self._init_temperature = float(init_temperature)
        self._max_iter_number = int(max_iter_number)
        self._rng = np.random.default_rng(seed)
        self._constrain_func = None
        self._reward = -1.0
        self._max_reward = -1.0
        self._tokens = None
        self._best_tokens = None
        self._iter = 0

    @property
    def best_tokens(self):
        return self._best_tokens

    @property
    def max_reward(self):
        return self._max_reward

    def reset(self, range_table, init_tokens, constrain_func=None):
        self._range_table = list(range_table)
        self._constrain_func = constrain_func
        self._tokens = list(init_tokens)
        self._iter = 0

    def update(self, tokens, reward):
        self._iter += 1
        temp = self._init_temperature * self._reduce_rate ** self._iter
        if reward > self._reward or self._rng.random() <= math.exp(
                min((reward - self._reward) / max(temp, 1e-9), 0.0)):
            self._reward = reward
            self._tokens = list(tokens)
        if reward > self._max_reward:
            self._max_reward = reward
            self._best_tokens = list(tokens)

    def next_tokens(self, control_token=None):
        tokens = list(control_token) if control_token else \
            list(self._tokens)
        # only positions with >=2 choices can mutate; a range-1 position
        # has exactly one legal token and must stay inside [0, range)
        movable = [i for i, r in enumerate(self._range_table) if r >= 2]
        if not movable:
            return list(tokens)
        new_tokens = list(tokens)
        idx = movable[int(self._rng.integers(0, len(movable)))]
        span = self._range_table[idx]
        new_tokens[idx] = (new_tokens[idx]
                           + int(self._rng.integers(1, span))) % span
        if self._constrain_func is None:
            return new_tokens
        for _ in range(self._max_iter_number):
            if self._constrain_func(new_tokens):
                return new_tokens
            idx = movable[int(self._rng.integers(0, len(movable)))]
            new_tokens = list(tokens)
            new_tokens[idx] = int(self._rng.integers(
                0, self._range_table[idx]))
        # no feasible mutation found: fall back to the last feasible
        # vector rather than returning a constraint-violating one
        return list(tokens)
