"""Knowledge distillation helpers (reference:
python/paddle/fluid/contrib/slim/distillation/distiller.py — FSPDistiller,
L2Distiller, SoftLabelDistiller; and the teacher/student program merge).

`merge` clones the teacher program's ops/vars into the student program
under a name prefix (teacher params become non-trainable persistables
initialized from the teacher scope), sharing the student's data feeds; the
loss builders then combine any teacher/student activation pair."""
import numpy as np

from .... import layers
from ....framework.core import Parameter
from ....layers import math as M
from ....layers import tensor as T


def merge(teacher_program, student_program, data_name_map, place=None,
          scope=None, teacher_scope=None, name_prefix="teacher_"):
    """Graft the teacher graph into the student program. `data_name_map`
    maps teacher feed names -> student feed names (shared inputs).
    Teacher weights are copied from `teacher_scope` into `scope` under the
    prefix and marked non-trainable."""
    from ....framework.executor import global_scope
    scope = scope or global_scope()
    teacher_scope = teacher_scope or scope
    sblock = student_program.global_block()
    tblock = teacher_program.global_block()

    def renamed(n):
        return data_name_map.get(n, name_prefix + n)

    for name, var in tblock.vars.items():
        if name in data_name_map:
            continue
        nv = sblock.create_var(name=renamed(name), shape=var.shape,
                               dtype=var.dtype,
                               persistable=var.persistable,
                               stop_gradient=True)
        if isinstance(var, Parameter) or var.persistable:
            tv = teacher_scope.find_var(name)
            if tv is not None:
                scope.set(nv.name, np.asarray(tv))
    for op in tblock.ops:
        sblock.append_op(
            type=op.type,
            inputs={s: [renamed(n) for n in ns]
                    for s, ns in op.inputs.items()},
            outputs={s: [renamed(n) for n in ns]
                     for s, ns in op.outputs.items()},
            attrs=dict(op.attrs), infer_shape=False)
    student_program._bump_version()


def l2_loss(teacher_var_name, student_var_name, program=None):
    """reference L2Distiller: mean squared error between activations."""
    block = (program or _default()).global_block()
    t = block.var(teacher_var_name)
    s = block.var(student_var_name)
    diff = M.elementwise_sub(s, t)
    return layers.mean(M.elementwise_mul(diff, diff))


def soft_label_loss(teacher_var_name, student_var_name, program=None,
                    teacher_temperature=2.0, student_temperature=2.0):
    """reference SoftLabelDistiller: CE between softened distributions."""
    block = (program or _default()).global_block()
    t = layers.softmax(M.scale(block.var(teacher_var_name),
                               1.0 / teacher_temperature))
    s = layers.log_softmax(M.scale(block.var(student_var_name),
                                   1.0 / student_temperature))
    return layers.mean(M.scale(
        layers.reduce_sum(M.elementwise_mul(t, s), dim=-1), -1.0))


def fsp_loss(teacher_var1_name, teacher_var2_name, student_var1_name,
             student_var2_name, program=None):
    """reference FSPDistiller (fsp_op.cc): match the flow-of-solution
    Gram matrices between two feature maps [N, C, H, W]."""
    block = (program or _default()).global_block()

    def fsp(a_name, b_name):
        a = block.var(a_name)
        b = block.var(b_name)
        n, c1, c2 = a.shape[0], a.shape[1], block.var(b_name).shape[1]
        hw = int(np.prod(a.shape[2:]))
        af = T.reshape(a, [n, c1, hw])
        bf = T.transpose(T.reshape(b, [n, c2, hw]), [0, 2, 1])
        return M.scale(layers.matmul(af, bf), 1.0 / hw)

    diff = M.elementwise_sub(fsp(student_var1_name, student_var2_name),
                             fsp(teacher_var1_name, teacher_var2_name))
    return layers.mean(M.elementwise_mul(diff, diff))


def _default():
    from ....framework.core import default_main_program
    return default_main_program()
