from .distiller import fsp_loss, l2_loss, merge, soft_label_loss  # noqa: F401
