"""Magnitude pruning (reference:
python/paddle/fluid/contrib/slim/prune/pruner.py + prune_walker — ratio
pruning of conv filters / fc weights by L1 norm).

TPU design: pruning is a MASK, not a shape change — XLA's static shapes
make physical channel removal a retrace, so `prune()` computes per-param
binary masks (elementwise magnitude or structured filter-level L1) and
(a) applies them to the scope immediately, and (b) optionally inserts
`elementwise_mul(param, mask)` ops after each optimizer update so the
pruned weights stay zero through continued training (mask-retrain, the
slim fine-tune recipe)."""
import numpy as np


class Pruner:
    def __init__(self, criterion="l1_norm"):
        assert criterion == "l1_norm"
        self.criterion = criterion

    @staticmethod
    def _mask(value, ratio, structured_axis=None):
        a = np.abs(np.asarray(value))
        if structured_axis is None:
            k = int(a.size * ratio)
            if k <= 0:
                return np.ones_like(a)
            thresh = np.partition(a.reshape(-1), k - 1)[k - 1]
            return (a > thresh).astype(a.dtype)
        # structured: rank whole slices (e.g. conv filters on axis 0)
        axes = tuple(i for i in range(a.ndim) if i != structured_axis)
        norms = a.sum(axis=axes)
        k = int(norms.size * ratio)
        if k <= 0:
            return np.ones_like(a)
        thresh = np.partition(norms, k - 1)[k - 1]
        keep = norms > thresh
        shape = [1] * a.ndim
        shape[structured_axis] = -1
        return np.broadcast_to(keep.reshape(shape), a.shape).astype(a.dtype)

    def prune(self, program, scope, params, ratios, place=None,
              lazy=False, only_graph=False, param_backup=None,
              param_shape_backup=None, structured_axis=None,
              mask_in_graph=False):
        """Zero the smallest-|w| fraction `ratios[i]` of each param.
        Returns {param_name: mask}. With mask_in_graph=True, persistable
        mask vars + re-mask ops are appended so optimizer updates cannot
        resurrect pruned weights."""
        masks = {}
        for name, ratio in zip(params, ratios):
            val = scope.find_var(name)
            if val is None:
                raise KeyError(f"param {name!r} not found in scope")
            mask = self._mask(val, float(ratio), structured_axis)
            masks[name] = mask
            if param_backup is not None:
                param_backup[name] = np.asarray(val).copy()
            scope.set(name, np.asarray(val) * mask)
        if mask_in_graph:
            self._append_mask_ops(program, scope, masks)
        return masks

    @staticmethod
    def _append_mask_ops(program, scope, masks):
        from ....framework.core import OP_ROLE_KEY, OpRole
        from ....framework import unique_name
        block = program.global_block()
        for name, mask in masks.items():
            mname = unique_name.generate(f"{name}@PRUNE_MASK")
            block.create_var(name=mname, shape=mask.shape,
                             dtype=str(mask.dtype), persistable=True,
                             stop_gradient=True)
            scope.set(mname, mask)
            block.append_op(
                type="elementwise_mul",
                inputs={"X": [name], "Y": [mname]},
                outputs={"Out": [name]},
                attrs={OP_ROLE_KEY: OpRole.Optimize}, infer_shape=False)
        program._bump_version()


def save_model_masks(masks, path):
    np.savez(path, **{k.replace("/", "%2F"): v for k, v in masks.items()})
    return path
