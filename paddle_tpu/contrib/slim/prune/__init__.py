from .pruner import Pruner, save_model_masks  # noqa: F401
