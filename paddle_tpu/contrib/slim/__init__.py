"""fluid.contrib.slim — model compression toolkit (reference:
python/paddle/fluid/contrib/slim/)."""
from . import quantization  # noqa: F401
