"""fluid.contrib.slim — model compression toolkit (reference:
python/paddle/fluid/contrib/slim/)."""
from . import quantization  # noqa: F401
from . import prune  # noqa: F401
from . import distillation  # noqa: F401
