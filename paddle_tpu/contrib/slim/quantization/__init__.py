from .quantization_pass import (  # noqa: F401
    QuantizationTransformPass, PostTrainingQuantization,
)
