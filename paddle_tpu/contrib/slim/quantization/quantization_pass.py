"""Quantization program passes (QAT + post-training).

Capability parity with the reference's slim quantization
(/root/reference/python/paddle/fluid/contrib/slim/quantization/
quantization_pass.py:147 QuantizationTransformPass — insert fake_quant on
weights/activations feeding quantizable ops; QuantizationFreezePass — bake
test-time scales; post_training_quantization.py — calibrate scales from
sample batches).

The reference rewrites an IrGraph; here the same rewrite runs directly on
the Program IR: each quantizable op's float inputs are routed through
fake-quant ops (channel-wise abs_max for weights, moving-average abs_max
for activations, with per-input persistable scale/state vars), and the
straight-through-estimator grads (ops/quantize_ops.py) make the rewritten
program trainable as-is.
"""
import numpy as np

from ....framework import unique_name
from ....framework.core import OP_ROLE_KEY, OpRole, Parameter


class QuantizationTransformPass:
    """reference quantization_pass.py:147."""

    def __init__(self, scope=None, place=None, weight_bits=8,
                 activation_bits=8, activation_quantize_type="abs_max",
                 weight_quantize_type="channel_wise_abs_max",
                 window_size=10000, moving_rate=0.9,
                 quantizable_op_type=("conv2d", "depthwise_conv2d", "mul")):
        self._weight_bits = int(weight_bits)
        self._activation_bits = int(activation_bits)
        self._act_type = activation_quantize_type
        self._weight_type = weight_quantize_type
        self._moving_rate = float(moving_rate)
        self._window_size = int(window_size)
        self._quantizable = set(quantizable_op_type)
        self._quanted = {}       # var name -> quantized var name

    def apply(self, program, startup_program=None, for_test=False):
        """Insert fake-quant ops before every quantizable op's float
        inputs, in place (pass a clone to keep the original)."""
        block = program.global_block()
        self._quanted = {}      # per-apply: quantized var names are
        #                         program-local, never reuse across programs
        i = 0
        while i < len(block.ops):
            op = block.ops[i]
            if op.type not in self._quantizable or \
                    op.attrs.get("__quanted__"):
                i += 1
                continue
            op.attrs["__quanted__"] = True
            inserted = 0
            for slot, names in list(op.inputs.items()):
                new_names = []
                for n in names:
                    try:
                        var = block.var(n)
                    except ValueError:
                        new_names.append(n)
                        continue
                    if var.dtype not in ("float32", "float64", "bfloat16"):
                        new_names.append(n)
                        continue
                    qn, k = self._insert_quant(block, i, n, var,
                                               is_weight=isinstance(
                                                   var, Parameter),
                                               startup_program=
                                               startup_program,
                                               for_test=for_test)
                    inserted += k
                    i += k
                    new_names.append(qn)
                op.inputs[slot] = new_names
            i += 1
        program._bump_version()
        return program

    def _insert_quant(self, block, pos, name, var, is_weight,
                      startup_program, for_test):
        if name in self._quanted:
            return self._quanted[name], 0
        from ....framework.core import default_startup_program
        from ....framework.initializer import ConstantInitializer
        startup = startup_program or default_startup_program()
        qn = f"{name}.quantized"
        block.create_var(name=qn, shape=var.shape, dtype=var.dtype,
                         stop_gradient=var.stop_gradient)

        def persistable_state(sname, shape):
            v = block.create_var(name=sname, shape=shape, dtype="float32",
                                 persistable=True, stop_gradient=True)
            sblock = startup.global_block()
            sblock.create_var(name=sname, shape=shape, dtype="float32",
                              persistable=True)
            ConstantInitializer(0.0)(v, block=sblock)
            return v

        scale_name = unique_name.generate(f"{name}.scale")
        persistable_state(scale_name, (1,))

        if is_weight:
            op_type = ("fake_channel_wise_quantize_abs_max"
                       if self._weight_type == "channel_wise_abs_max"
                       else "fake_quantize_abs_max")
            block._insert_op(
                pos, type=op_type,
                inputs={"X": [name]},
                outputs={"Out": [qn], "OutScale": [scale_name]},
                attrs={"bit_length": self._weight_bits,
                       OP_ROLE_KEY: OpRole.Forward},
                infer_shape=False)
            self._quanted[name] = qn
            return qn, 1
        if self._act_type == "moving_average_abs_max":
            accum = unique_name.generate(f"{name}.accum")
            state = unique_name.generate(f"{name}.state")
            for sn in (accum, state):
                persistable_state(sn, ())
            block._insert_op(
                pos, type="fake_quantize_moving_average_abs_max",
                inputs={"X": [name], "InAccum": [accum],
                        "InState": [state], "InScale": [scale_name]},
                outputs={"Out": [qn], "OutScale": [scale_name],
                         "StateOut": [state], "AccumOut": [accum]},
                attrs={"bit_length": self._activation_bits,
                       "moving_rate": self._moving_rate,
                       "is_test": bool(for_test),
                       OP_ROLE_KEY: OpRole.Forward},
                infer_shape=False)
        else:
            block._insert_op(
                pos, type="fake_quantize_abs_max",
                inputs={"X": [name]},
                outputs={"Out": [qn], "OutScale": [scale_name]},
                attrs={"bit_length": self._activation_bits,
                       OP_ROLE_KEY: OpRole.Forward},
                infer_shape=False)
        self._quanted[name] = qn
        return qn, 1


class PostTrainingQuantization:
    """reference post_training_quantization.py: run calibration batches
    through the float program, record per-tensor abs-max scales, then
    emit a quantized inference program with frozen scales."""

    def __init__(self, executor, program, feed_names, fetch_targets,
                 batch_generator, quantizable_op_type=("conv2d", "mul"),
                 weight_bits=8, activation_bits=8, scope=None):
        self._exe = executor
        self._program = program
        self._feed_names = list(feed_names)
        self._fetch = fetch_targets
        self._batches = batch_generator
        self._quantizable = tuple(quantizable_op_type)
        self._wbits = weight_bits
        self._abits = activation_bits
        self._scope = scope

    def quantize(self):
        # 1) calibration: track abs-max of every quantizable-op input
        maxes = {}
        block = self._program.global_block()
        watch = set()
        for op in block.ops:
            if op.type in self._quantizable:
                watch.update(op.input_arg_names)
        watch = sorted(watch)
        for feed in self._batches:
            # fetch the watched tensors directly — feed vars, params and
            # intermediate activations are all in the executor env
            vals = self._exe.run(self._program, feed=feed,
                                 fetch_list=list(watch))
            for n, v in zip(watch, vals):
                m = float(np.max(np.abs(np.asarray(v))))
                maxes[n] = max(maxes.get(n, 0.0), m)
        # 2) rewrite a test clone and FREEZE the calibrated scales into
        # the quant ops (reference QuantizationFreezePass bakes scales the
        # same way; without freezing, inference would re-reduce |x|max per
        # call and out-of-range inputs would shift the quant grid)
        quant_prog = self._program.clone(for_test=True)
        tp = QuantizationTransformPass(
            weight_bits=self._wbits, activation_bits=self._abits,
            activation_quantize_type="abs_max",
            weight_quantize_type="abs_max",
            quantizable_op_type=self._quantizable)
        tp.apply(quant_prog, for_test=True)
        for op in quant_prog.global_block().ops:
            if op.type == "fake_quantize_abs_max":
                src = op.inputs["X"][0]
                if src in maxes:
                    op.attrs["frozen_scale"] = float(maxes[src])
        self._calibration_scales = maxes
        return quant_prog
