"""fluid.contrib.decoder (reference contrib/decoder/
beam_search_decoder.py:43 InitState, :159 StateCell, :384
TrainingDecoder, :523 BeamSearchDecoder) — the legacy seq2seq decoder
front.

TPU-first re-design: TrainingDecoder records its step block ONCE into
layers.DynamicRNN (the reference builds a DynamicRNN too; ours lowers
to one masked lax.scan). BeamSearchDecoder reuses the dense beam
machinery of layers.rnn_api (beam_search op + gather_tree) instead of
the reference's LoD-array While loop: decode() wires the user's
StateCell into an RNNCell adapter whose parameters stay SHARED across
the static unroll by replaying the cell's unique-name snapshot, then
dynamic_decode runs the bounded search. Results are padded dense
[T, B, beam] back-traced ids + [B, beam] scores (the framework's beam
convention — layers.gather_tree) rather than LoD tensors."""
import contextlib

import numpy as np

__all__ = ["InitState", "StateCell", "TrainingDecoder",
           "BeamSearchDecoder"]


def _L():
    from ... import layers
    return layers


class InitState:
    """reference beam_search_decoder.py:43: initial decoder state —
    either an existing Variable (`init`) or a zeros/`value`-filled
    tensor of `shape`."""

    def __init__(self, init=None, shape=None, value=0.0,
                 init_boot=None, need_reorder=False, dtype="float32"):
        if init is not None:
            self._init = init
        elif init_boot is None:
            raise ValueError(
                "InitState needs `init` (a Variable) or `init_boot` "
                "(a batch reference for shape)")
        else:
            B = int(init_boot.shape[0])
            self._init = _L().fill_constant(
                [B] + [int(s) for s in (shape or [])], dtype, value)
        self.need_reorder = need_reorder

    @property
    def value(self):
        return self._init


class StateCell:
    """reference beam_search_decoder.py:159: named states + named
    inputs + a user `state_updater` describing one decode step."""

    def __init__(self, inputs, states, out_state, name=None):
        self._inputs = dict(inputs)
        self._init_states = dict(states)
        self._state_names = list(states.keys())
        self._out_state = out_state
        self._cur_states = {}
        self._next_states = {}
        self._updater = None
        # parameter stability across replayed invocations: snapshot the
        # unique-name counters at first compute_state and restore before
        # every later one, so layers.fc etc. inside the updater emit the
        # SAME parameter names each step (name-keyed params share
        # storage; reference records its block once instead)
        self._name_snapshot = None

    # ---- updater registration / execution ----
    def state_updater(self, updater):
        self._updater = updater
        return updater

    def get_input(self, input_name):
        if input_name not in self._inputs:
            raise ValueError(f"StateCell has no input {input_name!r}")
        v = self._inputs[input_name]
        if v is None:
            raise ValueError(
                f"StateCell input {input_name!r} was not fed")
        return v

    def get_state(self, state_name):
        if state_name in self._next_states:
            return self._next_states[state_name]
        if state_name not in self._cur_states:
            self._cur_states[state_name] = \
                self._init_states[state_name].value
        return self._cur_states[state_name]

    def set_state(self, state_name, state_value):
        if state_name not in self._state_names:
            raise ValueError(f"StateCell has no state {state_name!r}")
        self._next_states[state_name] = state_value

    def compute_state(self, inputs):
        if self._updater is None:
            raise ValueError(
                "StateCell.compute_state before @state_updater was "
                "registered")
        from ...framework import unique_name
        for k, v in inputs.items():
            if k not in self._inputs:
                raise ValueError(f"unknown StateCell input {k!r}")
            self._inputs[k] = v
        if self._name_snapshot is None:
            self._name_snapshot = dict(unique_name.generator.ids)
            self._updater(self)
        else:
            saved = dict(unique_name.generator.ids)
            unique_name.generator.ids.clear()
            unique_name.generator.ids.update(self._name_snapshot)
            self._updater(self)
            # names consumed by the updater replay identically; restore
            # the outer stream so unrelated layers don't collide
            unique_name.generator.ids.clear()
            unique_name.generator.ids.update(saved)

    def update_states(self):
        self._cur_states.update(self._next_states)
        self._next_states = {}

    def out_state(self):
        return self.get_state(self._out_state)

    def _set_states(self, mapping):
        self._cur_states = dict(mapping)
        self._next_states = {}


class TrainingDecoder:
    """reference beam_search_decoder.py:384: teacher-forced decoder —
    a with-block over a DynamicRNN step (recorded once, lowered to one
    masked scan)."""

    def __init__(self, state_cell, name=None):
        self._state_cell = state_cell
        self._drnn = _L().DynamicRNN(name=name)
        self._mems = {}

    @property
    def state_cell(self):
        return self._state_cell

    @contextlib.contextmanager
    def block(self):
        with self._drnn.block():
            yield
            for name, mem in self._mems.items():
                self._drnn.update_memory(
                    mem, self._state_cell.get_state(name))
            self._state_cell.update_states()

    def step_input(self, x, lengths=None, level=0):
        """x [B, T, ...] padded + lengths [B] (masked-dense stand-in
        for the reference's LoD step input). The first step_input also
        binds each StateCell state to a DynamicRNN memory (the rnn's
        mask must exist before memories — control_flow.py:667)."""
        out = self._drnn.step_input(x, lengths=lengths, level=level)
        if not self._mems:
            for name in self._state_cell._state_names:
                init = self._state_cell._init_states[name].value
                self._mems[name] = self._drnn.memory(init=init)
            self._state_cell._set_states(dict(self._mems))
        return out

    def static_input(self, x):
        return self._drnn.static_input(x)

    def output(self, *outputs):
        return self._drnn.output(*outputs)

    def __call__(self):
        return self._drnn()


class _StateCellRNNCell:
    """RNNCell adapter: one beam step = feed embedded ids into the
    StateCell, read out_state, project to vocab."""

    def __init__(self, state_cell, target_dict_dim, extra_inputs):
        self._sc = state_cell
        self._V = int(target_dict_dim)
        self._extra = extra_inputs      # {input_name: [B*beam, D] var}
        self._proj_w = None

    def call(self, inputs, states):
        L = _L()
        sc = self._sc
        if not isinstance(states, (list, tuple)):
            states = [states]
        sc._set_states(dict(zip(sc._state_names, states)))
        feed = dict(self._extra)
        for name in sc._inputs:
            if name not in feed:
                feed[name] = inputs
        sc.compute_state(inputs=feed)
        out = sc.out_state()
        sc.update_states()
        new_states = [sc.get_state(n) for n in sc._state_names]
        from ...layers.layer_helper import LayerHelper
        helper = LayerHelper("beam_decoder_proj")
        if self._proj_w is None:
            H = int(out.shape[-1])
            self._proj_w = helper.create_parameter(
                helper.param_attr, shape=[H, self._V], dtype="float32")
        logits = L.matmul(out, self._proj_w)
        return logits, new_states


class BeamSearchDecoder:
    """reference beam_search_decoder.py:523 — the default decode()
    semantics (embed previous ids -> StateCell step -> vocab softmax ->
    beam expansion with end_id termination) over the dense beam
    machinery (layers.rnn_api). The imperative block()/read_array API
    of the reference is subsumed by decode(); a custom step belongs in
    layers.BeamSearchDecoder/dynamic_decode (the modern API)."""

    def __init__(self, state_cell, init_ids, init_scores,
                 target_dict_dim, word_dim, input_var_dict=None,
                 topk_size=50, sparse_emb=True, max_len=100,
                 beam_size=1, end_id=1, name=None):
        self._state_cell = state_cell
        self._init_ids = init_ids
        self._init_scores = init_scores
        self._target_dict_dim = int(target_dict_dim)
        self._word_dim = int(word_dim)
        self._input_var_dict = dict(input_var_dict or {})
        self._max_len = int(max_len)
        self._beam_size = int(beam_size)
        self._end_id = int(end_id)
        self._decoded = False
        self._result = None

    def decode(self):
        self._decoded = True

    @staticmethod
    def _start_token_of(init_ids):
        """The GO token id: the reference feeds it as the init_ids
        tensor's fill value; the dense beam machinery needs the int, so
        read it off the producing fill_constant op."""
        block = init_ids.block
        for op in block.ops:
            if init_ids.name in op.output_arg_names and \
                    op.type == "fill_constant":
                return int(op.attrs.get("value", 0))
        raise ValueError(
            "BeamSearchDecoder could not infer the start token: pass "
            "init_ids produced by layers.fill_constant(..., value=GO)")

    def __call__(self):
        if not self._decoded:
            raise ValueError("call decode() before the decoder")
        if self._result is not None:
            return self._result
        from ...layers import rnn_api
        from ...layers.layer_helper import LayerHelper
        L = _L()
        helper = LayerHelper("beam_decoder_emb")
        emb_w = helper.create_parameter(
            helper.param_attr,
            shape=[self._target_dict_dim, self._word_dim],
            dtype="float32")

        def embedding_fn(ids):
            return _L().gather(emb_w, L.cast(ids, "int64"))

        cell = _StateCellRNNCell(self._state_cell,
                                 self._target_dict_dim, {})
        decoder = rnn_api.BeamSearchDecoder(
            cell, start_token=self._start_token_of(self._init_ids),
            end_token=self._end_id, beam_size=self._beam_size,
            embedding_fn=embedding_fn)
        # shared beam tiling (rnn_api.BeamSearchDecoder._tile)
        cell._extra = {k: decoder._tile(v)
                       for k, v in self._input_var_dict.items()}
        inits = [self._state_cell._init_states[n].value
                 for n in self._state_cell._state_names]
        (ids, scores), _ = rnn_api.dynamic_decode(
            decoder, inits=inits, max_step_num=self._max_len)
        self._result = (ids, scores)
        return self._result
