"""Distributed reader decorator (reference contrib/reader/
distributed_reader.py distributed_batch_reader): each trainer keeps
every trainers_num-th batch, offset by its trainer id (round-robin
batch sharding from the PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM
launcher env, the same contract distributed/launch.py sets)."""
import os

__all__ = ["distributed_batch_reader"]


def distributed_batch_reader(batch_reader):
    trainer_id = int(os.getenv("PADDLE_TRAINER_ID", "0"))
    trainers_num = int(os.getenv("PADDLE_TRAINERS_NUM", "1"))

    def decorated():
        for i, batch in enumerate(batch_reader()):
            if i % trainers_num == trainer_id:
                yield batch

    return decorated
