"""Memory-usage estimation (reference
contrib/memory_usage_calc.py:46 memory_usage): sum the sizes of every
variable in the program with -1 batch dims bound to `batch_size`,
reported as a (low, high) MB range. The reference brackets its
estimate the same way (actual placement adds allocator overhead — XLA
fusion typically LOWERS the real footprint here, so the range is an
upper-bound style estimate)."""
import numpy as np

from ..framework.dtype import np_dtype

__all__ = ["memory_usage"]

_BRACKET = 0.15


def memory_usage(program, batch_size):
    from ..framework.core import Program
    if not isinstance(program, Program):
        raise TypeError("memory_usage expects a Program")
    if not isinstance(batch_size, int) or batch_size <= 0:
        raise ValueError("batch_size must be a positive int")
    total = 0
    for block in program.blocks:
        for var in block.vars.values():
            if var.shape is None:
                continue
            n = 1
            for d in var.shape:
                n *= batch_size if int(d) < 0 else int(d)
            try:
                itemsize = np.dtype(np_dtype(var.dtype)).itemsize
            except TypeError:
                itemsize = 4
            total += n * itemsize
    mb = total / (1024.0 ** 2)
    return mb * (1 - _BRACKET), mb * (1 + _BRACKET)
