"""Decoupled weight decay extension (reference
contrib/extend_optimizer/extend_optimizer_with_weight_decay.py:102):
class decorator producing <Base>OptimizerWithDecoupledWeightDecay.
new_param = optimized_param - coeff * param_before_optimization —
the decay reads a SNAPSHOT of each param taken before the update ops
run (the whole point of decoupling), emitted as assign ops ahead of
the base optimizer's update ops."""

__all__ = ["extend_with_decoupled_weight_decay"]


def extend_with_decoupled_weight_decay(base_optimizer):
    from ..optimizer import Optimizer
    if not (isinstance(base_optimizer, type)
            and issubclass(base_optimizer, Optimizer)):
        raise TypeError(
            "extend_with_decoupled_weight_decay expects an Optimizer "
            "subclass")

    class OptimizerWithDecoupledWeightDecay(base_optimizer):
        def __init__(self, weight_decay, *args, **kwargs):
            super().__init__(*args, **kwargs)
            self._decoupled_coeff = float(weight_decay)

        def minimize(self, loss, startup_program=None,
                     parameter_list=None, no_grad_set=None):
            from ..framework import unique_name
            block = loss.block
            # snapshot params BEFORE the update ops are appended —
            # only the ones this minimize actually optimizes
            # (parameter_list / no_grad_set restrict the decay too)
            allowed = None
            if parameter_list is not None:
                allowed = {p if isinstance(p, str) else p.name
                           for p in parameter_list}
            excluded = {p if isinstance(p, str) else p.name
                        for p in (no_grad_set or ())}
            params = [v for v in block.vars.values()
                      if getattr(v, "is_parameter", False)
                      and getattr(v, "trainable", True)
                      and (allowed is None or v.name in allowed)
                      and v.name not in excluded]
            snaps = []
            for p in params:
                s = block.create_var(
                    name=unique_name.generate(p.name + "_wd_snap"),
                    shape=p.shape, dtype=p.dtype, stop_gradient=True)
                block.append_op(type="assign", inputs={"X": [p.name]},
                                outputs={"Out": [s.name]})
                snaps.append((p, s))
            result = super().minimize(
                loss, startup_program=startup_program,
                parameter_list=parameter_list, no_grad_set=no_grad_set)
            coeff = self._decoupled_coeff
            for p, s in snaps:
                # p -= coeff * snapshot (reference: scale + sum)
                scaled = block.create_var(
                    name=unique_name.generate(p.name + "_wd_term"),
                    shape=p.shape, dtype=p.dtype, stop_gradient=True)
                block.append_op(
                    type="scale", inputs={"X": [s.name]},
                    outputs={"Out": [scaled.name]},
                    attrs={"scale": -coeff})
                block.append_op(
                    type="elementwise_add",
                    inputs={"X": [p.name], "Y": [scaled.name]},
                    outputs={"Out": [p.name]})
            return result

    OptimizerWithDecoupledWeightDecay.__name__ = (
        base_optimizer.__name__ + "WithDecoupledWeightDecay")
    return OptimizerWithDecoupledWeightDecay
