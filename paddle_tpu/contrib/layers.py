"""fluid.contrib.layers (reference
python/paddle/fluid/contrib/layers/nn.py, rnn_impl.py, metric_op.py):
the CTR / text-matching / TDM long tail plus the Basic RNN impls.

Masked-dense conventions: variable-length inputs ride as padded dense
tensors + explicit ROW/COLUMN/Length vectors (PARITY.md), matching the
op lowerings in ops/ctr_ops.py / ops/extra_ops.py."""
import numpy as np

from ..layers.layer_helper import LayerHelper
from ..framework.core import Variable

__all__ = [
    "fused_elemwise_activation", "var_conv_2d", "match_matrix_tensor",
    "sequence_topk_avg_pooling", "tree_conv", "fused_embedding_seq_pool",
    "multiclass_nms2", "search_pyramid_hash", "shuffle_batch",
    "partial_concat", "partial_sum", "tdm_child", "tdm_sampler",
    "rank_attention", "batch_fc", "ctr_metric_bundle",
    "BasicGRUUnit", "BasicLSTMUnit", "basic_gru", "basic_lstm",
]


def _L():
    from .. import layers
    return layers


def fused_elemwise_activation(x, y, functor_list, axis=-1, scale=0.0,
                              save_intermediate_out=True):
    """reference contrib/layers/nn.py:41 — Unary(Binary(x, y)) or
    Binary(x, Unary(y)) for functor_list like
    ['elementwise_add', 'relu'] (= add(x, relu(y))) or
    ['relu', 'elementwise_add'] (= relu(add(x, y))). Composed from the
    constituent ops — XLA fuses the pair exactly as the reference's
    fused kernel does by hand."""
    L = _L()
    if isinstance(functor_list, str):
        functor_list = functor_list.split(",")
    if len(functor_list) != 2:
        raise ValueError("functor_list must name exactly two functors")
    binaries = {"elementwise_add": L.elementwise_add,
                "elementwise_mul": L.elementwise_mul}
    unaries = {"relu": L.relu, "tanh": L.tanh,
               "scale": lambda v: L.scale(v, scale=scale)}
    a, b = functor_list
    if a in binaries and b in unaries:
        return binaries[a](x, unaries[b](y), axis=axis)
    if a in unaries and b in binaries:
        return unaries[a](binaries[b](x, y, axis=axis))
    raise ValueError(
        f"functor_list {functor_list} must pair one of "
        f"{sorted(binaries)} with one of {sorted(unaries)}")


def var_conv_2d(input, row, col, input_channel, output_channel,
                filter_size, stride=1, param_attr=None, act=None,
                dtype="float32", name=None):
    """reference contrib/layers/nn.py:105 var_conv_2d: SAME conv over
    per-sample valid (row[b], col[b]) regions; invalid area zeroed
    (ops/ctr_ops.py var_conv_2d)."""
    helper = LayerHelper("var_conv_2d", param_attr=param_attr,
                         name=name)
    fs = filter_size if isinstance(filter_size, (list, tuple)) \
        else (filter_size, filter_size)
    st = stride if isinstance(stride, (list, tuple)) \
        else (stride, stride)
    w = helper.create_parameter(
        helper.param_attr,
        shape=[output_channel, input_channel * fs[0] * fs[1]],
        dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    col_out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="var_conv_2d",
        inputs={"X": [input], "W": [w], "ROW": [row], "COLUMN": [col]},
        outputs={"Out": [out], "Col": [col_out]},
        attrs={"InputChannel": input_channel,
               "OutputChannel": output_channel,
               "KernelH": fs[0], "KernelW": fs[1],
               "StrideH": st[0], "StrideW": st[1]},
        infer_shape=False)
    return helper.append_activation(out, act)


def match_matrix_tensor(x, y, channel_num, act=None, param_attr=None,
                        dtype="float32", name=None):
    """reference contrib/layers/nn.py:222: out[b,t,i,j] =
    x[b,i] . W[:,t,:] . y[b,j], rows/cols beyond each pair's lengths
    zeroed. Masked-dense: x [B,Lx,D] + XLength, y [B,Ly,D] + YLength —
    pass (tensor, lengths) tuples."""
    helper = LayerHelper("match_matrix_tensor", param_attr=param_attr,
                         name=name)
    if not (isinstance(x, (list, tuple)) and isinstance(y, (list, tuple))):
        raise ValueError(
            "match_matrix_tensor needs x=(tensor [B,Lx,D], lengths [B])"
            " and y=(tensor, lengths) in the masked-dense design")
    xt, xl = x
    yt, yl = y
    D = int(xt.shape[-1])
    Dy = int(yt.shape[-1])
    w = helper.create_parameter(helper.param_attr,
                                shape=[D, channel_num, Dy], dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    tmp = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="match_matrix_tensor",
        inputs={"X": [xt], "Y": [yt], "W": [w],
                "XLength": [xl], "YLength": [yl]},
        outputs={"Out": [out], "Tmp": [tmp]},
        attrs={"dim_t": channel_num}, infer_shape=False)
    return helper.append_activation(out, act), tmp


def sequence_topk_avg_pooling(input, row, col, topks, channel_num):
    """reference contrib/layers/nn.py:309 (ops/extra_ops.py
    sequence_topk_avg_pooling): per (row, channel), average of the
    top-k valid column scores for each k in topks."""
    helper = LayerHelper("sequence_topk_avg_pooling")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    pos = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="sequence_topk_avg_pooling",
        inputs={"X": [input], "ROW": [row], "COLUMN": [col]},
        outputs={"Out": [out], "pos": [pos]},
        attrs={"topks": [int(k) for k in topks],
               "channel_num": int(channel_num)},
        infer_shape=False)
    return out


def tree_conv(nodes_vector, edge_set, output_size, num_filters=1,
              max_depth=2, act="tanh", param_attr=None, bias_attr=None,
              name=None):
    """reference contrib/layers/nn.py:377 (ops/ctr_ops.py tree_conv)."""
    helper = LayerHelper("tree_conv", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    F = int(nodes_vector.shape[-1])
    w = helper.create_parameter(
        helper.param_attr, shape=[F, 3, output_size, num_filters],
        dtype=nodes_vector.dtype)
    out = helper.create_variable_for_type_inference(
        dtype=nodes_vector.dtype)
    helper.append_op(
        type="tree_conv",
        inputs={"NodesVector": [nodes_vector], "EdgeSet": [edge_set],
                "Filter": [w]},
        outputs={"Out": [out]},
        attrs={"max_depth": int(max_depth)}, infer_shape=False)
    if bias_attr:
        b = helper.create_parameter(
            helper.bias_attr, shape=[1, 1, output_size, num_filters],
            dtype=nodes_vector.dtype)
        out = _L().elementwise_add(out, b, axis=-1)
    return helper.append_activation(out, act)


def fused_embedding_seq_pool(input, size, is_sparse=False,
                             padding_idx=None, combiner="sum",
                             param_attr=None, dtype="float32"):
    """reference contrib/layers/nn.py:447: lookup_table + sum
    sequence_pool in one step. Masked-dense: ids [B, T]; padding_idx
    rows embed to zero, so the sum pool needs no separate mask. The
    composition compiles to one fused XLA gather+reduce — the same
    fusion the reference's hand-written kernel provides."""
    if combiner != "sum":
        raise NotImplementedError(
            "fused_embedding_seq_pool supports combiner='sum' "
            "(reference fused_embedding_seq_pool_op.h supports sum "
            "only)")
    from ..input import embedding as _emb_v2
    emb = _emb_v2(input, size, is_sparse=is_sparse,
                  padding_idx=padding_idx, param_attr=param_attr,
                  dtype=dtype)                     # [B, T, D]
    return _L().reduce_sum(emb, dim=[1])           # [B, D]


def multiclass_nms2(bboxes, scores, score_threshold, nms_top_k,
                    keep_top_k, nms_threshold=0.3, normalized=True,
                    nms_eta=1.0, background_label=0,
                    return_index=False, name=None):
    """reference contrib/layers/nn.py:514: multiclass_nms that also
    returns the kept boxes' original indices (padded -1)."""
    helper = LayerHelper("multiclass_nms2", name=name)
    out = helper.create_variable_for_type_inference(dtype=bboxes.dtype)
    index = helper.create_variable_for_type_inference(dtype="int32")
    rois_num = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="multiclass_nms2",
        inputs={"BBoxes": [bboxes], "Scores": [scores]},
        outputs={"Out": [out], "Index": [index],
                 "NmsRoisNum": [rois_num]},
        attrs={"score_threshold": score_threshold,
               "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
               "nms_threshold": nms_threshold, "normalized": normalized,
               "nms_eta": nms_eta, "background_label": background_label},
        infer_shape=False)
    if return_index:
        return out, index
    return out


def search_pyramid_hash(input, num_emb, space_len, pyramid_layer,
                        rand_len, drop_out_percent, is_training,
                        use_filter, white_list_len, black_list_len,
                        seed, lr, param_attr=None, param_attr_wl=None,
                        param_attr_bl=None, name=None,
                        distribute_update_vars=None, dtype="float32"):
    """reference contrib/layers/nn.py:644 (ops/ctr_ops.py pyramid_hash):
    n-gram windows (2..pyramid_layer) hash into a 1-D embedding space;
    the white/black-list filter is not implemented (raises — parity
    policy: unsupported args must not silently change semantics).
    `input` is (ids [B, T] int32, lengths [B]) masked-dense."""
    if use_filter or white_list_len or black_list_len:
        raise NotImplementedError(
            "search_pyramid_hash white/black-list filtering is not "
            "implemented; pass use_filter=False")
    helper = LayerHelper("pyramid_hash", param_attr=param_attr,
                         name=name)
    ids, lens = input if isinstance(input, (list, tuple)) \
        else (input, None)
    if lens is None:
        raise ValueError(
            "search_pyramid_hash needs (ids [B, T], lengths [B]) in "
            "the masked-dense design")
    w = helper.create_parameter(helper.param_attr,
                                shape=[space_len + rand_len],
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="pyramid_hash",
        inputs={"X": [ids], "W": [w], "Length": [lens]},
        outputs={"Out": [out]},
        attrs={"num_hash": 2, "rand_len": int(rand_len),
               "max_pyramid": int(pyramid_layer)},
        infer_shape=False)
    if is_training and drop_out_percent:
        out = _L().dropout(out, dropout_prob=float(drop_out_percent))
    return out


def shuffle_batch(x, seed=None):
    """reference contrib/layers/nn.py:760 (ops/extra_ops.py
    shuffle_batch): random row permutation; the permutation rides the
    op's RNG key."""
    helper = LayerHelper("shuffle_batch")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    idx = helper.create_variable_for_type_inference(dtype="int32")
    attrs = {}
    if seed is not None:
        # 'seed' is what the RNG keying reads (lowering.LowerCtx.op_key)
        attrs["seed"] = int(seed)
    helper.append_op(type="shuffle_batch", inputs={"X": [x]},
                     outputs={"Out": [out], "ShuffleIdx": [idx]},
                     attrs=attrs, infer_shape=False)
    return out


def partial_concat(input, start_index=0, length=-1):
    """reference contrib/layers/nn.py:824 (ops partial_concat)."""
    helper = LayerHelper("partial_concat")
    out = helper.create_variable_for_type_inference(
        dtype=input[0].dtype)
    helper.append_op(
        type="partial_concat", inputs={"X": list(input)},
        outputs={"Out": [out]},
        attrs={"start_index": int(start_index), "length": int(length)},
        infer_shape=False)
    return out


def partial_sum(input, start_index=0, length=-1):
    """reference contrib/layers/nn.py:887 (ops partial_sum)."""
    helper = LayerHelper("partial_sum")
    out = helper.create_variable_for_type_inference(
        dtype=input[0].dtype)
    helper.append_op(
        type="partial_sum", inputs={"X": list(input)},
        outputs={"Out": [out]},
        attrs={"start_index": int(start_index), "length": int(length)},
        infer_shape=False)
    return out


def tdm_child(x, node_nums, child_nums, param_attr=None, dtype="int32"):
    """reference contrib/layers/nn.py:941: per queried node, its
    children and leaf mask from the TreeInfo table (a [node_nums, 3 +
    child_nums] int parameter: item_id, layer_id, ancestor,
    children...)."""
    helper = LayerHelper("tdm_child", param_attr=param_attr)
    tree_info = helper.create_parameter(
        helper.param_attr, shape=[node_nums, 3 + child_nums],
        dtype="int32")
    child = helper.create_variable_for_type_inference(dtype=dtype)
    mask = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="tdm_child", inputs={"X": [x], "TreeInfo": [tree_info]},
        outputs={"Child": [child], "LeafMask": [mask]},
        attrs={"child_nums": int(child_nums), "dtype": dtype},
        infer_shape=False)
    return child, mask


def tdm_sampler(x, neg_samples_num_list, layer_node_num_list,
                leaf_node_num, tree_travel_attr=None,
                tree_layer_attr=None, output_positive=True,
                output_list=False, seed=0, tree_dtype="int32",
                dtype="int32"):
    """reference contrib/layers/nn.py:1026: per item, positive nodes
    from its travel path + per-layer negative samples. Travel
    [leaf_node_num, n_layers] and Layer [sum(layer_node_num_list)] are
    int parameters."""
    helper = LayerHelper("tdm_sampler")
    n_layers = len(layer_node_num_list)
    travel = helper.create_parameter(
        tree_travel_attr or helper.param_attr,
        shape=[leaf_node_num, n_layers], dtype="int32")
    layer = helper.create_parameter(
        tree_layer_attr or helper.param_attr,
        shape=[int(sum(layer_node_num_list))], dtype="int32")
    offsets = [0]
    for n in layer_node_num_list:
        offsets.append(offsets[-1] + int(n))
    out = helper.create_variable_for_type_inference(dtype=dtype)
    labels = helper.create_variable_for_type_inference(dtype=dtype)
    mask = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="tdm_sampler",
        inputs={"X": [x], "Travel": [travel], "Layer": [layer]},
        outputs={"Out": [out], "Labels": [labels], "Mask": [mask]},
        attrs={"neg_samples_num_list": [int(n) for n in
                                        neg_samples_num_list],
               "layer_offset_lod": offsets,
               "output_positive": bool(output_positive),
               "dtype": dtype, "seed": int(seed)},
        infer_shape=False)
    return out, labels, mask


def rank_attention(input, rank_offset, rank_param_shape,
                   rank_param_attr=None, max_rank=3, max_size=0):
    """reference contrib/layers/nn.py:1235 (ops rank_attention): rank-
    conditioned per-instance matmul over a learned rank parameter."""
    helper = LayerHelper("rank_attention",
                         param_attr=rank_param_attr)
    rank_param = helper.create_parameter(
        helper.param_attr, shape=list(rank_param_shape),
        dtype=input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    input_help = helper.create_variable_for_type_inference(
        dtype=input.dtype)
    ins_rank = helper.create_variable_for_type_inference(
        dtype=input.dtype)
    helper.append_op(
        type="rank_attention",
        inputs={"X": [input], "RankOffset": [rank_offset],
                "RankParam": [rank_param]},
        outputs={"Out": [out], "InputHelp": [input_help],
                 "InsRank": [ins_rank]},
        attrs={"MaxRank": int(max_rank), "MaxSize": int(max_size)},
        infer_shape=False)
    return out


def batch_fc(input, param_size, param_attr, bias_size, bias_attr,
             act=None):
    """reference contrib/layers/nn.py:1303 (ops batch_fc): per-slot
    batched FC — Input [S, B, in] x W [S, in, out] + Bias [S, 1, out]."""
    helper = LayerHelper("batch_fc", param_attr=param_attr,
                         bias_attr=bias_attr)
    w = helper.create_parameter(helper.param_attr,
                                shape=list(param_size),
                                dtype=input.dtype)
    b = helper.create_parameter(helper.bias_attr,
                                shape=list(bias_size),
                                dtype=input.dtype, is_bias=True)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="batch_fc", inputs={"Input": [input], "W": [w],
                                 "Bias": [b]},
        outputs={"Out": [out]}, attrs={}, infer_shape=False)
    return helper.append_activation(out, act)


def ctr_metric_bundle(input, label):
    """reference contrib/layers/metric_op.py:30: local sums for the
    CTR metric bundle — (local_sqrerr, local_abserr, local_prob,
    local_q); divide by the (all-reduced) instance count for
    MAE/RMSE/predicted-ctr/q."""
    L = _L()
    label_f = L.cast(label, input.dtype)
    diff = L.elementwise_sub(input, label_f)
    local_sqrerr = L.reduce_sum(L.square(diff))
    local_abserr = L.reduce_sum(L.abs(diff))
    local_prob = L.reduce_sum(input)
    # q = sum of clicks' predicted ctr (label-weighted prob)
    local_q = L.reduce_sum(L.elementwise_mul(input, label_f))
    return local_sqrerr, local_abserr, local_prob, local_q


# -------------------------------------------------- Basic RNN impls

class BasicGRUUnit:
    """reference contrib/layers/rnn_impl.py:25 BasicGRUUnit — one GRU
    step for static-graph composition: unit(input, pre_hidden) ->
    hidden. Thin front over layers.rnn_api.GRUCell (same math, fused
    lowering)."""

    def __init__(self, name_scope=None, hidden_size=None,
                 param_attr=None, bias_attr=None,
                 gate_activation=None, activation=None,
                 dtype="float32"):
        if hidden_size is None and isinstance(name_scope, int):
            name_scope, hidden_size = None, name_scope
        from ..layers.rnn_api import GRUCell
        self._cell = GRUCell(hidden_size, param_attr=param_attr,
                             bias_attr=bias_attr, dtype=dtype,
                             name=name_scope or "basic_gru_unit")

    def __call__(self, input, pre_hidden):
        out, _ = self._cell.call(input, [pre_hidden])
        return out


class BasicLSTMUnit:
    """reference contrib/layers/rnn_impl.py:699 BasicLSTMUnit:
    unit(input, pre_hidden, pre_cell) -> (hidden, cell)."""

    def __init__(self, name_scope=None, hidden_size=None,
                 param_attr=None, bias_attr=None, gate_activation=None,
                 activation=None, forget_bias=1.0, dtype="float32"):
        if hidden_size is None and isinstance(name_scope, int):
            name_scope, hidden_size = None, name_scope
        from ..layers.rnn_api import LSTMCell
        self._cell = LSTMCell(hidden_size, param_attr=param_attr,
                              bias_attr=bias_attr,
                              forget_bias=forget_bias, dtype=dtype,
                              name=name_scope or "basic_lstm_unit")

    def __call__(self, input, pre_hidden, pre_cell):
        _, (h, c) = self._cell.call(input, [pre_hidden, pre_cell])
        return h, c


def _stacked_rnn(cell_factory, input, init_states, hidden_size,
                 num_layers, sequence_length, dropout_prob,
                 bidirectional, batch_first, dtype):
    L = _L()
    from ..layers import rnn_api
    x = input if batch_first else L.transpose(input, [1, 0, 2])
    last_states = []
    for layer in range(num_layers):
        outs = []
        dirs = [False, True] if bidirectional else [False]
        for rev in dirs:
            cell = cell_factory(layer, rev)
            init = None
            if init_states is not None:
                init = init_states[len(last_states)]
            out, final = rnn_api.rnn(cell, x, initial_states=init,
                                     sequence_length=sequence_length,
                                     is_reverse=rev)
            outs.append(out)
            last_states.append(final)
        x = outs[0] if len(outs) == 1 else L.concat(outs, axis=-1)
        if dropout_prob and layer < num_layers - 1:
            x = L.dropout(x, dropout_prob=dropout_prob)
    if not batch_first:
        x = L.transpose(x, [1, 0, 2])
    return x, last_states


def _split_stacked_init(init, num_entries):
    """Normalize an init-state argument to a per-(layer, direction)
    list: the reference's stacked [num_layers*dirs, B, H] tensor splits
    along dim 0; a list/tuple passes through; a single [B, H] tensor
    serves a single entry."""
    L = _L()
    if init is None:
        return None
    if isinstance(init, (list, tuple)):
        entries = list(init)
    elif len(init.shape) == 3:
        parts = L.split(init, num_or_sections=int(init.shape[0]),
                        dim=0)
        entries = [L.reshape(p, [int(init.shape[1]),
                                 int(init.shape[2])]) for p in parts]
    else:
        entries = [init]
    if len(entries) != num_entries:
        raise ValueError(
            f"init state provides {len(entries)} entries but the "
            f"stacked RNN has {num_entries} (num_layers x directions)")
    return entries


def basic_gru(input, init_hidden, hidden_size, num_layers=1,
              sequence_length=None, dropout_prob=0.0,
              bidirectional=False, batch_first=True, param_attr=None,
              bias_attr=None, gate_activation=None, activation=None,
              dtype="float32", name="basic_gru"):
    """reference contrib/layers/rnn_impl.py:164 basic_gru: (possibly
    bidirectional) stacked GRU; returns (rnn_out, last_hidden list).
    Composed over layers.rnn_api.rnn's masked static unroll."""
    from ..layers.rnn_api import GRUCell

    def factory(layer, rev):
        return GRUCell(hidden_size, param_attr=param_attr,
                       bias_attr=bias_attr, dtype=dtype,
                       name=f"{name}_l{layer}{'_r' if rev else ''}")

    n_entries = num_layers * (2 if bidirectional else 1)
    init = None
    if init_hidden is not None:
        init = [[h] for h in _split_stacked_init(init_hidden,
                                                 n_entries)]
    out, finals = _stacked_rnn(factory, input, init, hidden_size,
                               num_layers, sequence_length,
                               dropout_prob, bidirectional,
                               batch_first, dtype)
    last_hidden = [f[0] if isinstance(f, (list, tuple)) else f
                   for f in finals]
    return out, last_hidden


def basic_lstm(input, init_hidden, init_cell, hidden_size,
               num_layers=1, sequence_length=None, dropout_prob=0.0,
               bidirectional=False, batch_first=True, param_attr=None,
               bias_attr=None, gate_activation=None, activation=None,
               forget_bias=1.0, dtype="float32", name="basic_lstm"):
    """reference contrib/layers/rnn_impl.py:405 basic_lstm: stacked
    (bi)LSTM; returns (rnn_out, last_hidden list, last_cell list)."""
    from ..layers.rnn_api import LSTMCell

    def factory(layer, rev):
        return LSTMCell(hidden_size, param_attr=param_attr,
                        bias_attr=bias_attr, forget_bias=forget_bias,
                        dtype=dtype,
                        name=f"{name}_l{layer}{'_r' if rev else ''}")

    n_entries = num_layers * (2 if bidirectional else 1)
    init = None
    if init_hidden is not None and init_cell is not None:
        hs = _split_stacked_init(init_hidden, n_entries)
        cs = _split_stacked_init(init_cell, n_entries)
        init = [[h, c] for h, c in zip(hs, cs)]
    out, finals = _stacked_rnn(factory, input, init, hidden_size,
                               num_layers, sequence_length,
                               dropout_prob, bidirectional,
                               batch_first, dtype)
    last_hidden = [f[0] for f in finals]
    last_cell = [f[1] for f in finals]
    return out, last_hidden, last_cell
