"""Op classification for mixed precision (reference:
python/paddle/fluid/contrib/mixed_precision/fp16_lists.py:21
AutoMixedPrecisionLists).

White ops run in the low-precision compute dtype (bf16 on TPU — they are
the MXU matmul/conv ops where the FLOPs are), black ops are pinned to fp32
(loss/softmax/norm numerics), everything else ("gray") follows its inputs:
low precision when fed by a low-precision producer, fp32 otherwise.
"""

white_list = {
    "mul", "matmul", "matmul_v2", "bmm",
    "conv2d", "conv3d", "conv2d_transpose", "depthwise_conv2d",
}

black_list = {
    "exp", "log", "mean", "reduce_mean", "reduce_sum", "sum",
    "softmax", "log_softmax", "softmax_with_cross_entropy",
    "cross_entropy", "sigmoid_cross_entropy_with_logits", "bce_loss",
    "square_error_cost", "mse_loss", "huber_loss", "nll_loss",
    "layer_norm", "batch_norm", "sync_batch_norm", "group_norm",
    "instance_norm", "squared_l2_norm", "p_norm", "norm",
}

# ops that must never be touched (state/IO/bookkeeping)
_untouched = {
    "feed", "fetch", "fill_constant", "assign", "cast", "print",
    "increment", "while", "cond", "recurrent", "write_to_array",
    "read_from_array", "lod_array_length",
}


class AutoMixedPrecisionLists:
    def __init__(self, custom_white_list=None, custom_black_list=None,
                 custom_black_varnames=None):
        self.white_list = set(white_list)
        self.black_list = set(black_list)
        self.black_varnames = set(custom_black_varnames or ())
        for w in (custom_white_list or ()):
            self.white_list.add(w)
            self.black_list.discard(w)
        for b in (custom_black_list or ()):
            self.black_list.add(b)
            self.white_list.discard(b)
        overlap = self.white_list & self.black_list
        if overlap:
            raise ValueError(f"ops in both white and black lists: {overlap}")

    def classify(self, op_type):
        if op_type in _untouched:
            return "skip"
        if op_type in self.white_list:
            return "white"
        if op_type in self.black_list:
            return "black"
        return "gray"
