"""OptimizerWithMixedPrecision (reference:
python/paddle/fluid/contrib/mixed_precision/decorator.py:27).

minimize = rewrite program to the low-precision compute dtype -> scale loss
-> backward (grads arrive fp32 at the master weights through the cast vjp)
-> unscale + finite check -> dynamic loss-scale update -> optimizer ops,
with the whole parameter/accumulator update rolled back via `where` selects
when any grad overflowed (the reference guards updates the same way with
check_finite_and_unscale + update_loss_scaling ops).

On TPU the default compute dtype is bfloat16: same exponent range as fp32,
so loss scaling rarely triggers — but the machinery is kept for fp16 parity
and for exactness of the capability contract.
"""
from ...framework import unique_name
from ...framework.core import (OpRole, op_role_guard, program_guard,
                               default_startup_program, default_main_program)
from .fp16_lists import AutoMixedPrecisionLists
from .fp16_utils import rewrite_program


class OptimizerWithMixedPrecision:
    def __init__(self, optimizer, amp_lists, init_loss_scaling,
                 use_dynamic_loss_scaling, incr_every_n_steps,
                 decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
                 dest_dtype="bfloat16"):
        self._optimizer = optimizer
        self._amp_lists = amp_lists or AutoMixedPrecisionLists()
        self._init_loss_scaling = float(init_loss_scaling)
        self._use_dynamic_loss_scaling = use_dynamic_loss_scaling
        self._incr_every_n_steps = int(incr_every_n_steps)
        self._decr_every_n_nan_or_inf = int(decr_every_n_nan_or_inf)
        self._incr_ratio = float(incr_ratio)
        self._decr_ratio = float(decr_ratio)
        self._dest_dtype = dest_dtype
        self._loss_scaling = None
        self._scaled_loss = None

    def get_loss_scaling(self):
        return self._loss_scaling

    def get_scaled_loss(self):
        return self._scaled_loss

    def _create_scale_vars(self):
        from ...layers import tensor as T
        self._loss_scaling = T.create_global_var(
            shape=[1], value=self._init_loss_scaling, dtype="float32",
            persistable=True, name=unique_name.generate("loss_scaling"))
        if self._use_dynamic_loss_scaling:
            self._num_good_steps = T.create_global_var(
                shape=[1], value=0.0, dtype="float32", persistable=True,
                name=unique_name.generate("num_good_steps"))
            self._num_bad_steps = T.create_global_var(
                shape=[1], value=0.0, dtype="float32", persistable=True,
                name=unique_name.generate("num_bad_steps"))

    def backward(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None, callbacks=None):
        from ...layers import math as M
        program = loss.block.program
        # scale/unscale/finite-check/rollback are only needed when the loss
        # scale can be != 1 (fp16 parity). The bf16 fast path — static scale
        # 1.0 — is a pure dtype policy: no per-step bookkeeping at all.
        self._needs_scaling = (self._use_dynamic_loss_scaling or
                               self._init_loss_scaling != 1.0)
        with program_guard(program,
                           startup_program or default_startup_program()):
            rewrite_program(program, self._amp_lists, self._dest_dtype)
            self._create_scale_vars()
            if self._needs_scaling:
                self._scaled_loss = loss * self._loss_scaling
            else:
                self._scaled_loss = loss
            params_grads = self._optimizer.backward(
                self._scaled_loss, startup_program, parameter_list,
                no_grad_set, callbacks)
            if self._needs_scaling:
                with op_role_guard(OpRole.Backward):
                    params_grads = self._unscale_and_check(params_grads)
        return params_grads

    def _unscale_and_check(self, params_grads):
        """grad /= loss_scaling; compute @FOUND_INF@ (bool scalar var) —
        the reference's check_finite_and_unscale op
        (operators/amp/check_finite_and_unscale_op.cc semantics)."""
        from ...layers import math as M, tensor as T
        from ...layers.layer_helper import LayerHelper
        helper = LayerHelper("check_finite_and_unscale")
        finites = []
        new_pg = []
        # divide, don't multiply by the reciprocal: 1/scale underflows to a
        # denormal (flushed to 0) for scale near float32 max
        for p, g in params_grads:
            g2 = M.elementwise_div(g, self._loss_scaling)
            if self._use_dynamic_loss_scaling:
                fin = helper.create_variable_for_type_inference(dtype="bool")
                helper.append_op(type="isfinite", inputs={"X": [g2]},
                                 outputs={"Out": [fin]})
                finites.append(fin)
            new_pg.append((p, g2))
        if self._use_dynamic_loss_scaling:
            all_fin = finites[0]
            for f in finites[1:]:
                all_fin = M.logical_and(all_fin, f)
            self._found_inf = M.logical_not(all_fin)
            self._found_inf.persistable = False
            self._update_loss_scaling()
        return new_pg

    def _update_loss_scaling(self):
        """reference update_loss_scaling op semantics
        (operators/amp/update_loss_scaling_op.cc): on overflow, bad+=1 and
        after decr_every_n_nan_or_inf bad steps scale *= decr_ratio; on a
        clean step, good+=1 and after incr_every_n_steps scale *=
        incr_ratio. Counters reset on each scale change (and good resets on
        any overflow)."""
        from ...layers import tensor as T
        scale = self._loss_scaling
        good, bad = self._num_good_steps, self._num_bad_steps
        inf = T.cast(self._found_inf, "float32")
        ok = 1.0 - inf
        good_new = (good + 1.0) * ok            # reset to 0 on overflow
        bad_new = (bad + 1.0) * inf             # reset to 0 on clean step
        hit_incr = T.cast(
            good_new >= float(self._incr_every_n_steps), "float32")
        hit_decr = T.cast(
            bad_new >= float(self._decr_every_n_nan_or_inf), "float32")
        factor = (1.0 + hit_incr * (self._incr_ratio - 1.0)) * \
                 (1.0 + hit_decr * (self._decr_ratio - 1.0))
        scale_new = scale * factor
        # never drop below a tiny floor
        floor = T.fill_constant([1], "float32", 1e-8)
        from ...layers.math import elementwise_max
        scale_new = elementwise_max(scale_new, floor)
        T.assign(scale_new, output=scale)
        T.assign(good_new * (1.0 - hit_incr), output=good)
        T.assign(bad_new * (1.0 - hit_decr), output=bad)

    def apply_gradients(self, params_grads):
        from ...optimizer import rollback_updates_if
        block = default_main_program().global_block()
        mark = len(block.ops)
        optimize_ops = self._optimizer.apply_gradients(params_grads)
        if not self._use_dynamic_loss_scaling:
            return optimize_ops  # no found_inf -> no rollback machinery
        # roll back every persistable the optimizer wrote if grads overflowed
        rollback_updates_if(block, mark, self._found_inf)
        return optimize_ops

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        program = loss.block.program
        with program_guard(program,
                           startup_program or default_startup_program()):
            params_grads = self.backward(loss, startup_program,
                                         parameter_list, no_grad_set)
            optimize_ops = self.apply_gradients(params_grads)
        return optimize_ops, params_grads

    # passthroughs
    def __getattr__(self, item):
        return getattr(self._optimizer, item)


def decorate(optimizer, amp_lists=None, init_loss_scaling=2 ** 15,
             incr_every_n_steps=1000, decr_every_n_nan_or_inf=2,
             incr_ratio=2.0, decr_ratio=0.8,
             use_dynamic_loss_scaling=True, dest_dtype="bfloat16"):
    """Wrap an optimizer for mixed-precision training (reference
    decorator.py:430 decorate). dest_dtype defaults to bfloat16 — the TPU
    MXU's native low-precision type; pass "float16" for fp16 parity."""
    return OptimizerWithMixedPrecision(
        optimizer, amp_lists, init_loss_scaling, use_dynamic_loss_scaling,
        incr_every_n_steps, decr_every_n_nan_or_inf, incr_ratio, decr_ratio,
        dest_dtype=dest_dtype)
