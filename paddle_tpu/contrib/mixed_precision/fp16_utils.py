"""Program rewrite for mixed precision (reference:
python/paddle/fluid/contrib/mixed_precision/fp16_utils.py:137
rewrite_program — cast-op insertion per black/white lists).

TPU-first: the rewrite inserts explicit `cast` ops into the IR (XLA fuses
them into neighboring ops, so a cast costs nothing at the fusion boundary);
parameters stay fp32 in the scope ("master weights") and are cast at their
use sites — their gradients come back fp32 automatically because the cast
op's vjp casts the cotangent up again. Run BEFORE append_backward so the
whole backward graph inherits mixed dtypes through the vjp-derived grads.
"""
from ...framework.core import Operator, Variable
from ...framework.dtype import convert_dtype


def _is_float(dtype):
    return convert_dtype(dtype) in ("float16", "bfloat16", "float32",
                                    "float64")


# Per-op-type slots holding persistent STATE (running statistics, affine
# params) that must stay fp32 even when the op itself computes in the
# low-precision dtype: the BN running-mean EMA accumulated in bf16 drifts
# (8-bit mantissa) and the checkpointed stats degrade eval-mode
# normalization. The op lowerings cast these per-use internally.
_FP32_STATE_SLOTS = {
    "batch_norm": (
        {"Scale", "Bias", "Mean", "Variance"},
        {"MeanOut", "VarianceOut", "SavedMean", "SavedVariance"}),
    "sync_batch_norm": (
        {"Scale", "Bias", "Mean", "Variance"},
        {"MeanOut", "VarianceOut", "SavedMean", "SavedVariance"}),
}


def rewrite_program(main_program, amp_lists, dest_dtype="bfloat16"):
    """Insert casts so white-list ops (and gray ops fed by them) compute in
    `dest_dtype` while black-list ops stay fp32. Mutates main_program."""
    block = main_program.global_block()
    old_ops = list(block.ops)
    new_ops = []
    cast_cache = {}   # (var_name, dtype) -> cast var name
    cur_dtype = {}    # var name -> current runtime dtype string

    def dtype_of(name):
        if name in cur_dtype:
            return cur_dtype[name]
        try:
            return convert_dtype(block.var(name).dtype)
        except ValueError:
            return None

    def cast_to(name, dtype):
        """Get-or-create `name` cast to dtype; emits the cast op."""
        key = (name, dtype)
        if key in cast_cache:
            return cast_cache[key]
        cast_name = f"{name}.cast_{dtype}"
        src_var = block.var(name)
        block.create_var(name=cast_name, shape=src_var.shape, dtype=dtype,
                         stop_gradient=src_var.stop_gradient)
        op = Operator(block, "cast",
                      inputs={"X": [name]}, outputs={"Out": [cast_name]},
                      attrs={"in_dtype": dtype_of(name),
                             "out_dtype": dtype})
        new_ops.append(op)
        cast_cache[key] = cast_name
        return cast_name

    for op in old_ops:
        kind = amp_lists.classify(op.type)
        if kind == "skip":
            new_ops.append(op)
            for n in op.output_arg_names:
                cur_dtype.pop(n, None)
            continue

        float_in_dtypes = [dtype_of(n) for n in op.input_arg_names
                           if dtype_of(n) in ("float32", dest_dtype)]
        if kind == "white":
            compute = dest_dtype
        elif kind == "black":
            compute = "float32"
        else:  # gray: follow producers
            compute = dest_dtype if dest_dtype in float_in_dtypes \
                else "float32"
        if any(n in amp_lists.black_varnames for n in op.input_arg_names):
            compute = "float32"

        state_in, state_out = _FP32_STATE_SLOTS.get(op.type,
                                                    (frozenset(),
                                                     frozenset()))
        changed = False
        new_inputs = {}
        for slot, names in op.inputs.items():
            if slot in state_in:
                new_inputs[slot] = list(names)   # fp32 state: never cast
                continue
            renamed = []
            for n in names:
                d = dtype_of(n)
                if d in ("float32", dest_dtype) and d != compute:
                    renamed.append(cast_to(n, compute))
                    changed = True
                else:
                    renamed.append(n)
            new_inputs[slot] = renamed
        if changed:
            op.inputs = new_inputs

        new_ops.append(op)
        if compute == dest_dtype:
            for slot, names in op.outputs.items():
                if slot in state_out:
                    continue                     # fp32 state: keep dtype
                for n in names:
                    try:
                        var = block.var(n)
                    except ValueError:
                        continue
                    if _is_float(var.dtype):
                        var.dtype = dest_dtype
                        cur_dtype[n] = dest_dtype
        else:
            for n in op.output_arg_names:
                cur_dtype.pop(n, None)

    block.ops = new_ops
    main_program._bump_version()
    return main_program
