"""fluid.contrib.utils (reference contrib/utils/hdfs_utils.py +
lookup_table_utils.py): HDFS transfer helpers and distributed-lookup-
table program surgery.

HDFSClient shells out to `hadoop fs` exactly like the reference; the
binary is probed lazily so import works on machines without a Hadoop
install (calls then raise an actionable error)."""
import os
import subprocess

__all__ = ["HDFSClient", "multi_download", "multi_upload",
           "convert_dist_to_sparse_program",
           "load_persistables_for_increment",
           "load_persistables_for_inference"]


class HDFSClient:
    """reference hdfs_utils.py:35 — thin `hadoop fs` CLI wrapper."""

    def __init__(self, hadoop_home, configs):
        self.hadoop_home = hadoop_home
        self.configs = dict(configs or {})
        self.pre_commands = [os.path.join(hadoop_home, "bin", "hadoop"),
                             "fs"]
        for k, v in self.configs.items():
            self.pre_commands.append(f"-D{k}={v}")

    def _run(self, args, retry_times=5):
        cmd = self.pre_commands + list(args)
        if not os.path.exists(self.pre_commands[0]):
            raise RuntimeError(
                f"hadoop binary not found at {self.pre_commands[0]}; "
                f"HDFSClient needs a Hadoop install (hadoop_home="
                f"{self.hadoop_home!r})")
        last = None
        for _ in range(max(1, retry_times)):
            p = subprocess.run(cmd, capture_output=True, text=True)
            last = p
            if p.returncode == 0:
                return p.stdout
        raise RuntimeError(
            f"hdfs command {' '.join(args)} failed: {last.stderr}")

    def upload(self, hdfs_path, local_path, overwrite=False,
               retry_times=5):
        args = ["-put"] + (["-f"] if overwrite else []) + \
            [local_path, hdfs_path]
        self._run(args, retry_times)
        return True

    def download(self, hdfs_path, local_path, overwrite=False,
                 unzip=False):
        self._run(["-get", hdfs_path, local_path])
        return True

    def is_exist(self, hdfs_path=None):
        try:
            self._run(["-test", "-e", hdfs_path], retry_times=1)
            return True
        except RuntimeError:
            return False

    def is_dir(self, hdfs_path=None):
        try:
            self._run(["-test", "-d", hdfs_path], retry_times=1)
            return True
        except RuntimeError:
            return False

    def delete(self, hdfs_path):
        self._run(["-rm", "-r", hdfs_path])
        return True

    def rename(self, hdfs_src_path, hdfs_dst_path, overwrite=False):
        self._run(["-mv", hdfs_src_path, hdfs_dst_path])
        return True

    def makedirs(self, hdfs_path):
        self._run(["-mkdir", "-p", hdfs_path])
        return True

    def ls(self, hdfs_path):
        out = self._run(["-ls", hdfs_path])
        return [line.split()[-1] for line in out.splitlines()
                if line and not line.startswith("Found")]

    @staticmethod
    def make_local_dirs(local_path):
        os.makedirs(local_path, exist_ok=True)


def multi_download(client, hdfs_path, local_path, trainer_id,
                   trainers, multi_processes=5):
    """reference hdfs_utils.py:437: each trainer downloads its
    round-robin shard of the files under hdfs_path."""
    files = client.ls(hdfs_path)
    mine = [f for i, f in enumerate(sorted(files))
            if i % trainers == trainer_id]
    HDFSClient.make_local_dirs(local_path)
    out = []
    for f in mine:
        dst = os.path.join(local_path, os.path.basename(f))
        client.download(f, dst)
        out.append(dst)
    return out


def multi_upload(client, hdfs_path, local_path, multi_processes=5,
                 overwrite=False, sync=True):
    """reference hdfs_utils.py:518."""
    client.makedirs(hdfs_path)
    for root, _, names in os.walk(local_path):
        for n in names:
            src = os.path.join(root, n)
            rel = os.path.relpath(src, local_path)
            client.upload(os.path.join(hdfs_path, rel), src,
                          overwrite=overwrite)
    return True


def convert_dist_to_sparse_program(program):
    """reference lookup_table_utils.py:85: rewrite the trainer
    program's distributed_lookup_table ops back to LOCAL sparse
    lookup_table ops so the PS-trained model runs single-process
    (the pserver-hosted table becomes an ordinary sparse parameter)."""
    from ...framework.core import Program  # noqa: F401 (type anchor)
    for block in program.blocks:
        for op in block.ops:
            if op.type == "distributed_lookup_table":
                op.type = "lookup_table"
                op.attrs.pop("endpoint", None)
                op.attrs.pop("table_name", None)
                op.attrs["is_sparse"] = True
            elif op.type in ("lookup_table", "lookup_table_v2"):
                op.attrs["is_distributed"] = False
                op.attrs["is_sparse"] = True
    return program


def load_persistables_for_increment(dirname, executor, program,
                                    lookup_table_var,
                                    lookup_table_var_path):
    """reference lookup_table_utils.py:136: load dense persistables
    from dirname plus the lookup-table param from its own path
    (PS-sharded table saves live beside the dense checkpoint)."""
    import numpy as np
    from ... import io
    io.load_persistables(executor, dirname, main_program=program)
    if lookup_table_var is not None and \
            os.path.exists(lookup_table_var_path):
        from ...framework.executor import global_scope
        name = lookup_table_var if isinstance(lookup_table_var, str) \
            else lookup_table_var.name
        global_scope().set(name, np.load(lookup_table_var_path,
                                         allow_pickle=False))


def load_persistables_for_inference(dirname, executor, program,
                                    lookup_table_var_name):
    """reference lookup_table_utils.py:260: same load for the local-
    inference program converted by convert_dist_to_sparse_program."""
    load_persistables_for_increment(
        dirname, executor, program, lookup_table_var_name,
        os.path.join(dirname, f"{lookup_table_var_name}.npy"))
