"""Flash attention as a Pallas TPU kernel (fwd + bwd), with XLA fallback.

The reference's attention story is hand-fused CUDA
(operators/fused/multihead_matmul_op.cu — QKV matmul + softmax fused for
V100); the TPU-native equivalent is a blockwise softmax kernel that never
materializes the [Sq, Sk] score matrix in HBM.

The kernel was VPU-bound in its first form (r4: ~25µs/tile of softmax VPU
passes vs ~5µs of MXU work — neither roofline binding). This version cuts
the VPU work per [bq, Sk] tile to two passes (max + a single fused
exp chain) via:

- base-2 softmax: `scale * log2(e)` is folded into the q tile (a [bq, D]
  multiply instead of a [bq, Sk] one) and `exp2` replaces `exp`; the saved
  log-sum-exp is base-2 as well.
- the additive key bias is fused into BOTH the max-reduction pass and the
  exp chain (Mosaic folds the broadcast add into each loop over s2) — no
  separate materialized biased-score tile, and the row max is exact, so a
  bias-masked key can never underflow the real keys' probabilities.
- the softmax normalizer rides the MXU for free: D=64 values occupy half
  of a 128-lane tile, so V is staged into a [bk, 128] VMEM scratch with
  ones in lane D, and `p @ v_aug` yields both `p @ v` and the row sums in
  one matmul — the cross-lane sum reduction pass disappears.
- `p` is cast to the value dtype inside the same fused chain (one store).

Two forward kernels share those tricks:
- single-block (Sk fits one VMEM tile, the common case up to ~4k): no
  online-softmax state at all — one max, one exp chain, one matmul.
- online (long Sk): running (m, acc_aug) state where acc_aug's lane D IS
  the normalizer, so the rescale correction covers acc and l in one
  [bq, 128] multiply.

Backward: when Sk fits one tile, a single combined kernel grids over
q-blocks, recomputes p once, and produces dq (streamed) plus dk/dv
(accumulated in VMEM scratch) — five matmuls, two VPU chains. For long
Sk the classic two-kernel (dq; dk/dv) decomposition remains, updated to
the same base-2/fused-chain scheme.

Layout: q [B, H, Sq, D], k/v [B, H, Sk, D], optional additive key-position
bias [B, 1, 1, Sk] (the BERT padding-mask layout), optional causal masking.
The bias is treated as a constant mask (zero cotangent) — masks are data,
not parameters, in every caller in this framework.

impl selection: "pallas" (TPU compiled), "interpret" (Pallas interpreter —
exercises the real kernel on CPU, used by tests), "xla" (composite fallback,
exact same math). Default: pallas on TPU backends, xla elsewhere.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128
_LOG2E = 1.4426950408889634   # log2(e); folded into q so exp2 == exp

# VMEM working-set budget for auto block sizing (the chip has ~16 MB;
# leave headroom for Pallas double-buffering of the streamed operands)
_VMEM_BUDGET = 10 * 1024 * 1024
_SINGLE_BLOCK_MAX_SK = 4096


def _auto_impl():
    backend = jax.default_backend()
    return "pallas" if backend in ("tpu", "axon") else "xla"


def _auto_bq(sq, sk, per_elem_bytes):
    """Largest power-of-two q block that divides Sq and keeps the
    [bq, Sk]-class intermediates inside the VMEM budget."""
    for cand in (1024, 512, 256, 128):
        if sq % cand == 0 and cand * sk * per_elem_bytes <= _VMEM_BUDGET:
            return cand
    return sq if sq <= 128 else None


def _block_sizes(sq, sk, bq, bk, per_elem_bytes=6, causal=False):
    """Resolve (bq, bk). bk == sk selects the single-block kernels;
    causal sequences >= 2k that divide into 1024-blocks prefer the
    online path, whose dead-block skipping beats the single-block
    kernel's wasted upper triangle (measured r5: 7.26 vs 7.81 ms fwd at
    S=2048). Causal lengths NOT divisible by 1024 (e.g. 2560) stay
    single-block — correct, just without the skip."""
    if bk is None:
        single_ok = sk <= _SINGLE_BLOCK_MAX_SK and not (
            causal and sk >= 2048 and sk % 1024 == 0)
        bk = sk if single_ok else (
            1024 if sk % 1024 == 0 else 512 if sk % 512 == 0
            else 256 if sk % 256 == 0 else 128 if sk % 128 == 0 else sk)
    if bq is None:
        bq = _auto_bq(sq, bk, per_elem_bytes) or sq
    if sq % bq or sk % bk:
        raise ValueError(
            f"flash_attention: Sq={sq}/Sk={sk} must divide block sizes "
            f"({bq}, {bk}); pad the sequence")
    return bq, bk


def _causal_mask(s, qi, ki, bq, bk):
    rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(rows >= cols, s, _NEG_INF)


def _block_live(causal, qi, ki, bq, bk):
    """Whether k-block ki intersects the causal lower triangle of q-block
    qi (always true without causal)."""
    if not causal:
        return True
    return ki * bk <= qi * bq + bq - 1


def _bias2(bias_ref):
    """Key bias as a base-2 row [1, bk] (constant-mask contract)."""
    return (bias_ref[0, 0, 0, :].astype(jnp.float32) * _LOG2E)[None, :]


# The augmented-V normalizer trick only pays when D < 128 (the ones
# column rides the tile padding the MXU computes anyway); for D >= 128
# heads the kernels fall back to an explicit cross-lane sum and use the
# V block directly — still O(S) memory, one extra VPU reduce pass.

# ---------------------------------------------------------------- forward

def _fwd_single_kernel(q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref,
                       v_sc, *, scale, bq, causal):
    """Whole Sk in one tile: no online state. Grid (B, H, nq)."""
    b, h, i = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    d = q_ref.shape[-1]
    aug = v_sc is not None

    if aug:
        @pl.when((b == 0) & (h == 0) & (i == 0))
        def _once():
            # zeros in lanes d+1.. and ones in lane d never change
            v_sc[:] = jnp.zeros_like(v_sc)
            v_sc[:, d:d + 1] = jnp.ones((v_sc.shape[0], 1), v_sc.dtype)

        @pl.when(i == 0)
        def _stage_v():
            # the V block is constant across i: staged once per (b, h)
            v_sc[:, :d] = v_ref[0, 0].astype(v_sc.dtype)

    q = (q_ref[0, 0].astype(jnp.float32) * (scale * _LOG2E)).astype(
        q_ref.dtype)                                        # [bq, D] tiny
    s2 = jax.lax.dot_general(
        q, k_ref[0, 0], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                 # [bq, Sk]
    if causal:
        s2 = _causal_mask(s2, i, 0, bq, k_ref.shape[2])
    if bias_ref is not None:
        # the broadcast add fuses into both s2 passes (same VMEM
        # traffic); an unbiased max could underflow every real key when
        # a masked key's raw score dominates
        s2 = s2 + _bias2(bias_ref)
    m2 = jnp.max(s2, axis=-1, keepdims=True)                # [bq, 1]
    arg = s2 - m2
    if aug:
        p = jnp.exp2(arg).astype(v_sc.dtype)                # fused chain
        acc = jax.lax.dot_general(
            p, v_sc[:], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, 128]
        l = acc[:, d:d + 1]
    else:
        p = jnp.exp2(arg).astype(v_ref.dtype)
        acc = jax.lax.dot_general(
            p, v_ref[0, 0], (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, D]
        l = jnp.sum(p.astype(jnp.float32), axis=-1, keepdims=True)
    l = jnp.where(l == 0.0, 1.0, l)
    out_ref[0, 0] = (acc[:, :d] / l).astype(out_ref.dtype)
    # lse rows live on lanes ([B, H, 1, Sq] avoids the 128x lane padding
    # a trailing-1 dim would get); base-2: lse2 = m2 + log2(l)
    lse_ref[0, 0] = (m2 + jnp.log2(l)).reshape(1, -1)


def _fwd_online_kernel(q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref,
                       m_sc, acc_sc, l_sc, v_sc, *, scale, bq, bk, nk,
                       causal):
    """Running (m, acc_aug) state; acc_aug lane D is the normalizer, so
    the rescale correction covers acc and l in one [bq, 128] multiply.
    Grid (B, H, nq, nk)."""
    b, h = pl.program_id(0), pl.program_id(1)
    qi, ki = pl.program_id(2), pl.program_id(3)
    d = q_ref.shape[-1]
    aug = v_sc is not None

    if aug:
        @pl.when((b == 0) & (h == 0) & (qi == 0) & (ki == 0))
        def _once():
            v_sc[:] = jnp.zeros_like(v_sc)
            v_sc[:, d:d + 1] = jnp.ones((v_sc.shape[0], 1), v_sc.dtype)

    @pl.when(ki == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        acc_sc[:] = jnp.zeros_like(acc_sc)
        if not aug:
            l_sc[:] = jnp.zeros_like(l_sc)

    @pl.when(_block_live(causal, qi, ki, bq, bk))
    def _fold():
        q = (q_ref[0, 0].astype(jnp.float32) * (scale * _LOG2E)).astype(
            q_ref.dtype)
        s2 = jax.lax.dot_general(
            q, k_ref[0, 0], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)             # [bq, bk]
        if causal:
            s2 = _causal_mask(s2, qi, ki, bq, bk)
        if bias_ref is not None:
            s2 = s2 + _bias2(bias_ref)
        m_prev = m_sc[:, :1]                                # [bq, 1]
        m_cur = jnp.max(s2, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp2(m_prev - m_new)
        arg = s2 - m_new
        m_sc[:, :1] = m_new
        if aug:
            v_sc[:, :d] = v_ref[0, 0].astype(v_sc.dtype)
            p = jnp.exp2(arg).astype(v_sc.dtype)
            acc_sc[:] = acc_sc[:] * corr + jax.lax.dot_general(
                p, v_sc[:], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
        else:
            p = jnp.exp2(arg).astype(v_ref.dtype)
            acc_sc[:] = acc_sc[:] * corr + jax.lax.dot_general(
                p, v_ref[0, 0], (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            l_sc[:, :1] = l_sc[:, :1] * corr + jnp.sum(
                p.astype(jnp.float32), axis=-1, keepdims=True)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = acc_sc[:, d:d + 1] if aug else l_sc[:, :1]
        l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = (acc_sc[:, :d] / l).astype(out_ref.dtype)
        lse_ref[0, 0] = (m_sc[:, :1] + jnp.log2(l)).reshape(1, -1)


def _fwd_pallas(q, k, v, bias, scale, causal, bq, bk, interpret):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    aug = D < _LANES
    bq, bk = _block_sizes(Sq, Sk, bq, bk, per_elem_bytes=6,
                          causal=causal)
    nq, nk = Sq // bq, Sk // bk
    single = nk == 1

    in_specs = [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, i, *j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bk, D), lambda b, h, i, *j: (b, h, j[0], 0)
                     if j else (b, h, 0, 0)),
        pl.BlockSpec((1, 1, bk, D), lambda b, h, i, *j: (b, h, j[0], 0)
                     if j else (b, h, 0, 0)),
    ]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, 1, bk), lambda b, h, i, *j:
                         (b, 0, 0, j[0]) if j else (b, 0, 0, 0)))
        args.append(bias)

    if single:
        body = functools.partial(_fwd_single_kernel, scale=scale, bq=bq,
                                 causal=causal)
        grid = (B, H, nq)
        scratch = [pltpu.VMEM((bk, _LANES), v.dtype)] if aug else []
        n_sc = len(scratch)

        def kern(q_ref, k_ref, v_ref, *rest):
            bias_ref, t = (rest[0], rest[1:]) if bias is not None \
                else (None, rest)
            out_ref, lse_ref = t[0], t[1]
            v_sc = t[2] if n_sc else None
            body(q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref, v_sc)
    else:
        body = functools.partial(_fwd_online_kernel, scale=scale, bq=bq,
                                 bk=bk, nk=nk, causal=causal)
        grid = (B, H, nq, nk)
        scratch = [
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES if aug else D), jnp.float32),
        ]
        if aug:
            scratch.append(pltpu.VMEM((bk, _LANES), v.dtype))
        else:
            scratch.append(pltpu.VMEM((bq, _LANES), jnp.float32))

        def kern(q_ref, k_ref, v_ref, *rest):
            bias_ref, t = (rest[0], rest[1:]) if bias is not None \
                else (None, rest)
            out_ref, lse_ref, m_sc, acc_sc, third = t
            l_sc, v_sc = (None, third) if aug else (third, None)
            body(q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref,
                 m_sc, acc_sc, l_sc, v_sc)
    out, lse = pl.pallas_call(
        kern,
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, *j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, i, *j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, 1, Sq), jnp.float32),
        ],
        scratch_shapes=scratch,
        interpret=interpret,
    )(*args)
    return out, lse


# --------------------------------------------------------------- backward

def _bwd_single_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref,
                       delta_ref, dq_ref, dk_ref, dv_ref, dk_sc, dv_sc,
                       *, scale, bq, causal, nq):
    """Combined dq/dk/dv when Sk fits one tile: p recomputed once, dq
    streamed per q-block, dk/dv accumulated in VMEM. Grid (B, H, nq)."""
    i = pl.program_id(2)

    @pl.when(i == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    q_raw = q_ref[0, 0]                                     # [bq, D]
    k_blk = k_ref[0, 0]                                     # [Sk, D]
    v_blk = v_ref[0, 0]
    do = do_ref[0, 0]
    lse = lse_ref[0, 0].reshape(-1, 1)                      # [bq, 1]
    delta = delta_ref[0, 0].reshape(-1, 1)
    q2 = (q_raw.astype(jnp.float32) * (scale * _LOG2E)).astype(q_raw.dtype)
    s2 = jax.lax.dot_general(
        q2, k_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                 # [bq, Sk]
    if causal:
        s2 = _causal_mask(s2, i, 0, bq, k_ref.shape[2])
    arg = s2 - lse
    if bias_ref is not None:
        arg = arg + _bias2(bias_ref)
    p = jnp.exp2(arg)                                       # [bq, Sk] f32
    pb = p.astype(do.dtype)
    dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
        pb, do, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                 # [Sk, D]
    dp = jax.lax.dot_general(
        do, v_blk, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32)                 # [bq, Sk]
    ds = (p * (dp - delta) * scale).astype(k_blk.dtype)     # fused chain
    dq_ref[0, 0] = jax.lax.dot_general(
        ds, k_blk, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(dq_ref.dtype)
    dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
        ds, q_raw, (((0,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)                 # [Sk, D]

    @pl.when(i == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_sc, *, scale, bq, bk, nk, causal):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    @pl.when(_block_live(causal, qi, ki, bq, bk))
    def _fold():
        q_raw = q_ref[0, 0]                                # [bq, D]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0].reshape(-1, 1)                 # [1,bq]->[bq,1]
        delta = delta_ref[0, 0].reshape(-1, 1)
        k_blk = k_ref[0, 0]                                # [bk, D]
        v_blk = v_ref[0, 0]
        q2 = (q_raw.astype(jnp.float32) * (scale * _LOG2E)).astype(
            q_raw.dtype)
        s2 = jax.lax.dot_general(
            q2, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if causal:
            s2 = _causal_mask(s2, qi, ki, bq, bk)
        arg = s2 - lse
        if bias_ref is not None:
            arg = arg + _bias2(bias_ref)
        p = jnp.exp2(arg)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k_blk.dtype)
        dq_sc[:] = dq_sc[:] + jax.lax.dot_general(
            ds, k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_sc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, scale, bq, bk, nq, causal):
    ki, qi = pl.program_id(2), pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    @pl.when(_block_live(causal, qi, ki, bq, bk))
    def _fold():
        k_blk = k_ref[0, 0]                                # [bk, D]
        v_blk = v_ref[0, 0]
        q_raw = q_ref[0, 0]                                # [bq, D]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0].reshape(-1, 1)                 # [1,bq]->[bq,1]
        delta = delta_ref[0, 0].reshape(-1, 1)
        q2 = (q_raw.astype(jnp.float32) * (scale * _LOG2E)).astype(
            q_raw.dtype)
        s2 = jax.lax.dot_general(
            q2, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if causal:
            s2 = _causal_mask(s2, qi, ki, bq, bk)
        arg = s2 - lse
        if bias_ref is not None:
            arg = arg + _bias2(bias_ref)
        p = jnp.exp2(arg)
        dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q_raw.dtype)
        dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
            ds, q_raw, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, bias, scale, causal, bq, bk, interpret,
                out, lse, do):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    # the backward holds ~2x the [bq, Sk]-class intermediates of the
    # forward (s, p, dp, ds): budget with 12 bytes/elem
    bq, bk = _block_sizes(Sq, Sk, bq, bk, per_elem_bytes=12,
                          causal=causal)
    nq, nk = Sq // bq, Sk // bk
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, :, None, :]                # [B, H, 1, Sq]

    if nk == 1:
        qspec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i: (b, h, i, 0))
        kspec = pl.BlockSpec((1, 1, bk, D), lambda b, h, i: (b, h, 0, 0))
        rspec = pl.BlockSpec((1, 1, 1, bq), lambda b, h, i: (b, h, 0, i))
        body = functools.partial(_bwd_single_kernel, scale=scale, bq=bq,
                                 causal=causal, nq=nq)
        specs = [qspec, kspec, kspec]
        args = [q, k, v]
        if bias is not None:
            specs.append(
                pl.BlockSpec((1, 1, 1, bk), lambda b, h, i: (b, 0, 0, 0)))
            args.append(bias)
            kern = body
        else:
            def kern(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dq_ref, dk_ref, dv_ref, dk_sc, dv_sc):
                body(q_ref, k_ref, v_ref, None, do_ref, lse_ref,
                     delta_ref, dq_ref, dk_ref, dv_ref, dk_sc, dv_sc)
        dq, dk, dv = pl.pallas_call(
            kern,
            grid=(B, H, nq),
            in_specs=specs + [qspec, rspec, rspec],
            out_specs=[qspec, kspec, kspec],
            out_shape=[jax.ShapeDtypeStruct(q.shape, q.dtype),
                       jax.ShapeDtypeStruct(k.shape, k.dtype),
                       jax.ShapeDtypeStruct(v.shape, v.dtype)],
            scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                            pltpu.VMEM((bk, D), jnp.float32)],
            interpret=interpret,
        )(*args, do, lse, delta)
        return dq, dk, dv

    qspec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0))
    kspec_i = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0))
    rspec = pl.BlockSpec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i))

    dq_body = functools.partial(_dq_kernel, scale=scale, bq=bq, bk=bk,
                                nk=nk, causal=causal)
    dq_specs = [qspec, kspec_i, kspec_i]
    dq_args = [q, k, v]
    if bias is not None:
        dq_specs.append(
            pl.BlockSpec((1, 1, 1, bk), lambda b, h, i, j: (b, 0, 0, j)))
        dq_args.append(bias)
        dq_kern = dq_body
    else:
        def dq_kern(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dq_ref, dq_sc):
            dq_body(q_ref, k_ref, v_ref, None, do_ref, lse_ref, delta_ref,
                    dq_ref, dq_sc)
    dq = pl.pallas_call(
        dq_kern,
        grid=(B, H, nq, nk),
        in_specs=dq_specs + [qspec, rspec, rspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(*dq_args, do, lse, delta)

    # dkv: k-block is the outer (carried) dim, q-blocks stream innermost
    kspec_o = pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0))
    qspec_i = pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0))
    rspec_i = pl.BlockSpec((1, 1, 1, bq), lambda b, h, j, i: (b, h, 0, i))
    dkv_body = functools.partial(_dkv_kernel, scale=scale, bq=bq, bk=bk,
                                 nq=nq, causal=causal)
    dkv_specs = [qspec_i, kspec_o, kspec_o]
    dkv_args = [q, k, v]
    if bias is not None:
        dkv_specs.append(
            pl.BlockSpec((1, 1, 1, bk), lambda b, h, j, i: (b, 0, 0, j)))
        dkv_args.append(bias)
        dkv_kern = dkv_body
    else:
        def dkv_kern(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_sc, dv_sc):
            dkv_body(q_ref, k_ref, v_ref, None, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_sc, dv_sc)
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(B, H, nk, nq),
        in_specs=dkv_specs + [qspec_i, rspec_i, rspec_i],
        out_specs=[kspec_o, kspec_o],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(*dkv_args, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------- public entry

def _xla_attention(q, k, v, bias, scale, causal):
    """Composite fallback: identical math, materialized scores."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        # match the Pallas path's constant-mask contract (zero cotangent)
        s = s + jax.lax.stop_gradient(bias).astype(s.dtype)
    if causal:
        Sq, Sk = q.shape[2], k.shape[2]
        rows = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, bias, scale, causal, bq, bk, interpret):
    out, _ = _fwd_pallas(q, k, v, bias, scale, causal, bq, bk, interpret)
    return out


def _flash_fwd(q, k, v, bias, scale, causal, bq, bk, interpret):
    out, lse = _fwd_pallas(q, k, v, bias, scale, causal, bq, bk, interpret)
    return out, (q, k, v, bias, out, lse)


def _flash_bwd(scale, causal, bq, bk, interpret, res, do):
    q, k, v, bias, out, lse = res
    dq, dk, dv = _bwd_pallas(q, k, v, bias, scale, causal, bq, bk,
                             interpret, out, lse, do)
    dbias = None if bias is None else jnp.zeros_like(bias)
    return dq, dk, dv, dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, bias=None, scale=None, causal=False,
                    impl=None, block_q=None, block_k=None):
    """Blockwise fused attention. q [B,H,Sq,D], k/v [B,H,Sk,D], optional
    additive key bias [B,1,1,Sk] (constant — zero cotangent). Returns
    [B,H,Sq,D]. impl: None (auto), "pallas", "interpret", "xla"."""
    if scale is None or scale == 0.0:
        scale = float(q.shape[-1]) ** -0.5
    requested = impl
    impl = impl or _auto_impl()
    if bias is not None and (bias.ndim != 4 or bias.shape[1] != 1
                             or bias.shape[2] != 1):
        if requested in ("pallas", "interpret"):
            raise ValueError(
                f"flash_attention impl={requested!r} supports only a "
                f"[B, 1, 1, Sk] key bias, got {tuple(bias.shape)}; use a "
                f"key mask (+ causal=True for causality) or impl='xla'")
        impl = "xla"   # general [B,H,Sq,Sk] bias: composite path
    if impl == "xla":
        return _xla_attention(q, k, v, bias, scale, causal)
    return _flash(q, k, v, bias, float(scale), bool(causal),
                  block_q, block_k, impl == "interpret")
