"""Flash attention as a Pallas TPU kernel (fwd + bwd), with XLA fallback.

The reference's attention story is hand-fused CUDA
(operators/fused/multihead_matmul_op.cu — QKV matmul + softmax fused for
V100); the TPU-native equivalent is a blockwise online-softmax kernel that
never materializes the [Sq, Sk] score matrix in HBM: scores for one
(q-block, k-block) tile live in VMEM, folded into running (max, normalizer,
accumulator) state — O(S) memory instead of O(S^2), and the score/softmax
work stays fused with both matmuls on the MXU/VPU.

Kernels grid over (batch, head, q-block, k-block) so Pallas's automatic
pipelining double-buffers the K/V block DMAs against compute; the online
state (m, l, acc) lives in VMEM scratch, carried across the innermost
k-block grid steps and finalized on the last one.

Layout: q [B, H, Sq, D], k/v [B, H, Sk, D], optional additive key-position
bias [B, 1, 1, Sk] (the BERT padding-mask layout), optional causal masking.
The bias is treated as a constant mask (zero cotangent) — masks are data,
not parameters, in every caller in this framework.

Backward follows the standard two-kernel flash decomposition: a dq kernel
gridded over q-blocks (innermost: k-blocks) and a dk/dv kernel gridded over
k-blocks (innermost: q-blocks), both recomputing p = exp(s - lse) from the
saved log-sum-exp rather than storing probabilities.

impl selection: "pallas" (TPU compiled), "interpret" (Pallas interpreter —
exercises the real kernel on CPU, used by tests), "xla" (composite fallback,
exact same math). Default: pallas on TPU backends, xla elsewhere.
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_LANES = 128   # m/l scratch is stored lane-broadcast to keep the VPU happy


def _auto_impl():
    backend = jax.default_backend()
    return "pallas" if backend in ("tpu", "axon") else "xla"


def _block_sizes(sq, sk, bq, bk):
    # large q/k tiles amortize the per-tile online-softmax state updates
    # and keep the MXU fed: 1024x1024 measured 1.6x faster than 256x512
    # at S=2048/D=64 on v5e (r4); smaller tiles only when S doesn't
    # divide.
    def auto(s):
        for cand in (1024, 512, 256, 128):
            if s % cand == 0:
                return cand
        return s
    bq = bq or auto(sq)
    bk = bk or auto(sk)
    if sq % bq or sk % bk:
        raise ValueError(
            f"flash_attention: Sq={sq}/Sk={sk} must divide block sizes "
            f"({bq}, {bk}); pad the sequence")
    return bq, bk


def _causal_mask(s, qi, ki, bq, bk):
    rows = qi * bq + jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    cols = ki * bk + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
    return jnp.where(rows >= cols, s, _NEG_INF)


def _block_live(causal, qi, ki, bq, bk):
    """Whether k-block ki intersects the causal lower triangle of q-block
    qi (always true without causal)."""
    if not causal:
        return True
    return ki * bk <= qi * bq + bq - 1


# ---------------------------------------------------------------- forward

def _fwd_kernel(q_ref, k_ref, v_ref, bias_ref, out_ref, lse_ref,
                m_sc, l_sc, acc_sc, *, scale, bq, bk, nk, causal):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    @pl.when(_block_live(causal, qi, ki, bq, bk))
    def _fold():
        q = q_ref[0, 0]                                    # [bq, D]
        k_blk = k_ref[0, 0]                                # [bk, D]
        v_blk = v_ref[0, 0]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if bias_ref is not None:
            s = s + bias_ref[0, 0, 0, :].astype(jnp.float32)[None, :]
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        m_prev = m_sc[:, :1]                               # [bq, 1]
        l_prev = l_sc[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        corr = jnp.exp(m_prev - m_new)
        p = jnp.exp(s - m_new)
        l_new = l_prev * corr + jnp.sum(p, axis=-1, keepdims=True)
        m_sc[:] = jnp.broadcast_to(m_new, m_sc.shape)
        l_sc[:] = jnp.broadcast_to(l_new, l_sc.shape)
        acc_sc[:] = acc_sc[:] * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        l = l_sc[:, :1]
        out_ref[0, 0] = (acc_sc[:] / l).astype(out_ref.dtype)
        # lse rows live on lanes ([B, H, 1, Sq] avoids the 128x lane
        # padding a trailing-1 dim would get); (bq,1)->(1,bq) reshape
        lse_ref[0, 0] = (m_sc[:, :1] + jnp.log(l)).reshape(1, -1)


def _fwd_pallas(q, k, v, bias, scale, causal, bq, bk, interpret):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq, bk = _block_sizes(Sq, Sk, bq, bk)
    nq, nk = Sq // bq, Sk // bk

    body = functools.partial(_fwd_kernel, scale=scale, bq=bq, bk=bk,
                             nk=nk, causal=causal)
    in_specs = [
        pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
        pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
        pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0)),
    ]
    args = [q, k, v]
    if bias is not None:
        in_specs.append(
            pl.BlockSpec((1, 1, 1, bk), lambda b, h, i, j: (b, 0, 0, j)))
        args.append(bias)
        kern = body
    else:
        def kern(q_ref, k_ref, v_ref, out_ref, lse_ref, m, l, acc):
            body(q_ref, k_ref, v_ref, None, out_ref, lse_ref, m, l, acc)
    out, lse = pl.pallas_call(
        kern,
        grid=(B, H, nq, nk),
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0)),
            pl.BlockSpec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            jax.ShapeDtypeStruct((B, H, 1, Sq), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, _LANES), jnp.float32),
            pltpu.VMEM((bq, D), jnp.float32),
        ],
        interpret=interpret,
    )(*args)
    return out, lse


# --------------------------------------------------------------- backward

def _dq_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
               dq_ref, dq_sc, *, scale, bq, bk, nk, causal):
    qi, ki = pl.program_id(2), pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        dq_sc[:] = jnp.zeros_like(dq_sc)

    @pl.when(_block_live(causal, qi, ki, bq, bk))
    def _fold():
        q = q_ref[0, 0]                                    # [bq, D]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0].reshape(-1, 1)                 # [1,bq]->[bq,1]
        delta = delta_ref[0, 0].reshape(-1, 1)
        k_blk = k_ref[0, 0]                                # [bk, D]
        v_blk = v_ref[0, 0]
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        if bias_ref is not None:
            s = s + bias_ref[0, 0, 0, :].astype(jnp.float32)[None, :]
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        p = jnp.exp(s - lse)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dq_sc[:] = dq_sc[:] + jax.lax.dot_general(
            ds.astype(k_blk.dtype), k_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0, 0] = dq_sc[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, bias_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_sc, dv_sc, *, scale, bq, bk, nq, causal):
    ki, qi = pl.program_id(2), pl.program_id(3)

    @pl.when(qi == 0)
    def _init():
        dk_sc[:] = jnp.zeros_like(dk_sc)
        dv_sc[:] = jnp.zeros_like(dv_sc)

    @pl.when(_block_live(causal, qi, ki, bq, bk))
    def _fold():
        k_blk = k_ref[0, 0]                                # [bk, D]
        v_blk = v_ref[0, 0]
        q = q_ref[0, 0]                                    # [bq, D]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0].reshape(-1, 1)                 # [1,bq]->[bq,1]
        delta = delta_ref[0, 0].reshape(-1, 1)
        s = scale * jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)            # [bq, bk]
        if bias_ref is not None:
            s = s + bias_ref[0, 0, 0, :].astype(jnp.float32)[None, :]
        if causal:
            s = _causal_mask(s, qi, ki, bq, bk)
        p = jnp.exp(s - lse)
        dv_sc[:] = dv_sc[:] + jax.lax.dot_general(
            p.astype(do.dtype), do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(
            do, v_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32)
        ds = p * (dp - delta) * scale
        dk_sc[:] = dk_sc[:] + jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0, 0] = dk_sc[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_sc[:].astype(dv_ref.dtype)


def _bwd_pallas(q, k, v, bias, scale, causal, bq, bk, interpret,
                out, lse, do):
    B, H, Sq, D = q.shape
    Sk = k.shape[2]
    bq, bk = _block_sizes(Sq, Sk, bq, bk)
    nq, nk = Sq // bq, Sk // bk
    delta = jnp.sum(do.astype(jnp.float32) * out.astype(jnp.float32),
                    axis=-1)[:, :, None, :]                # [B, H, 1, Sq]

    qspec = pl.BlockSpec((1, 1, bq, D), lambda b, h, i, j: (b, h, i, 0))
    kspec_i = pl.BlockSpec((1, 1, bk, D), lambda b, h, i, j: (b, h, j, 0))
    rspec = pl.BlockSpec((1, 1, 1, bq), lambda b, h, i, j: (b, h, 0, i))

    dq_body = functools.partial(_dq_kernel, scale=scale, bq=bq, bk=bk,
                                nk=nk, causal=causal)
    dq_specs = [qspec, kspec_i, kspec_i]
    dq_args = [q, k, v]
    if bias is not None:
        dq_specs.append(
            pl.BlockSpec((1, 1, 1, bk), lambda b, h, i, j: (b, 0, 0, j)))
        dq_args.append(bias)
        dq_kern = dq_body
    else:
        def dq_kern(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dq_ref, dq_sc):
            dq_body(q_ref, k_ref, v_ref, None, do_ref, lse_ref, delta_ref,
                    dq_ref, dq_sc)
    dq = pl.pallas_call(
        dq_kern,
        grid=(B, H, nq, nk),
        in_specs=dq_specs + [qspec, rspec, rspec],
        out_specs=qspec,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((bq, D), jnp.float32)],
        interpret=interpret,
    )(*dq_args, do, lse, delta)

    # dkv: k-block is the outer (carried) dim, q-blocks stream innermost
    kspec_o = pl.BlockSpec((1, 1, bk, D), lambda b, h, j, i: (b, h, j, 0))
    qspec_i = pl.BlockSpec((1, 1, bq, D), lambda b, h, j, i: (b, h, i, 0))
    rspec_i = pl.BlockSpec((1, 1, 1, bq), lambda b, h, j, i: (b, h, 0, i))
    dkv_body = functools.partial(_dkv_kernel, scale=scale, bq=bq, bk=bk,
                                 nq=nq, causal=causal)
    dkv_specs = [qspec_i, kspec_o, kspec_o]
    dkv_args = [q, k, v]
    if bias is not None:
        dkv_specs.append(
            pl.BlockSpec((1, 1, 1, bk), lambda b, h, j, i: (b, 0, 0, j)))
        dkv_args.append(bias)
        dkv_kern = dkv_body
    else:
        def dkv_kern(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_sc, dv_sc):
            dkv_body(q_ref, k_ref, v_ref, None, do_ref, lse_ref, delta_ref,
                     dk_ref, dv_ref, dk_sc, dv_sc)
    dk, dv = pl.pallas_call(
        dkv_kern,
        grid=(B, H, nk, nq),
        in_specs=dkv_specs + [qspec_i, rspec_i, rspec_i],
        out_specs=[kspec_o, kspec_o],
        out_shape=[jax.ShapeDtypeStruct(k.shape, k.dtype),
                   jax.ShapeDtypeStruct(v.shape, v.dtype)],
        scratch_shapes=[pltpu.VMEM((bk, D), jnp.float32),
                        pltpu.VMEM((bk, D), jnp.float32)],
        interpret=interpret,
    )(*dkv_args, do, lse, delta)
    return dq, dk, dv


# ---------------------------------------------------------- public entry

def _xla_attention(q, k, v, bias, scale, causal):
    """Composite fallback: identical math, materialized scores."""
    s = jnp.einsum("bhqd,bhkd->bhqk", q, k) * scale
    if bias is not None:
        # match the Pallas path's constant-mask contract (zero cotangent)
        s = s + jax.lax.stop_gradient(bias).astype(s.dtype)
    if causal:
        Sq, Sk = q.shape[2], k.shape[2]
        rows = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 0)
        cols = jax.lax.broadcasted_iota(jnp.int32, (Sq, Sk), 1)
        s = jnp.where(rows >= cols, s, _NEG_INF)
    p = jax.nn.softmax(s.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bhkd->bhqd", p, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7, 8))
def _flash(q, k, v, bias, scale, causal, bq, bk, interpret):
    out, _ = _fwd_pallas(q, k, v, bias, scale, causal, bq, bk, interpret)
    return out


def _flash_fwd(q, k, v, bias, scale, causal, bq, bk, interpret):
    out, lse = _fwd_pallas(q, k, v, bias, scale, causal, bq, bk, interpret)
    return out, (q, k, v, bias, out, lse)


def _flash_bwd(scale, causal, bq, bk, interpret, res, do):
    q, k, v, bias, out, lse = res
    dq, dk, dv = _bwd_pallas(q, k, v, bias, scale, causal, bq, bk,
                             interpret, out, lse, do)
    dbias = None if bias is None else jnp.zeros_like(bias)
    return dq, dk, dv, dbias


_flash.defvjp(_flash_fwd, _flash_bwd)


def flash_attention(q, k, v, bias=None, scale=None, causal=False,
                    impl=None, block_q=None, block_k=None):
    """Blockwise fused attention. q [B,H,Sq,D], k/v [B,H,Sk,D], optional
    additive key bias [B,1,1,Sk] (constant — zero cotangent). Returns
    [B,H,Sq,D]. impl: None (auto), "pallas", "interpret", "xla"."""
    if scale is None or scale == 0.0:
        scale = float(q.shape[-1]) ** -0.5
    requested = impl
    impl = impl or _auto_impl()
    if bias is not None and (bias.ndim != 4 or bias.shape[1] != 1
                             or bias.shape[2] != 1):
        if requested in ("pallas", "interpret"):
            raise ValueError(
                f"flash_attention impl={requested!r} supports only a "
                f"[B, 1, 1, Sk] key bias, got {tuple(bias.shape)}; use a "
                f"key mask (+ causal=True for causality) or impl='xla'")
        impl = "xla"   # general [B,H,Sq,Sk] bias: composite path
    if impl == "xla":
        return _xla_attention(q, k, v, bias, scale, causal)
    return _flash(q, k, v, bias, float(scale), bool(causal),
                  block_q, block_k, impl == "interpret")
