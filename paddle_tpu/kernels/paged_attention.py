"""Paged decode-attention as a Pallas TPU kernel, with a pure-JAX oracle.

The decode half of the flash-attention story (kernels/flash_attention.py
fused prefill): one query token per row attends over that row's KV cache
stored as BLOCKS of a shared pool (vLLM/PagedAttention, Kwon et al.
2023) instead of a dense per-slot ``[B, H, max_len, D]`` bank. The
block-table gather IS the kernel's index map — each grid step's
``BlockSpec`` resolves ``(tables[b, j], h, 0, 0)`` from a
scalar-prefetched block table, so the gather and the attention read are
one fused pass over VMEM-resident blocks and the ``[B, max_len]`` dense
cache is never materialized (decode is bandwidth-bound: bytes streamed
per token IS the token rate).

Two implementations, same math:

- ``pallas``: grid ``(B, H, blocks_per_row)``, online-softmax running
  state (m, l, acc) in VMEM scratch carried across a row's blocks,
  dead-block skipping via the per-row position counter (a block past
  ``pos[b]`` is never fetched into the running state — table padding
  rides the same skip), int8 blocks dequantized in-register against
  their per-slot scales. ``interpret`` runs the SAME kernel through the
  Pallas interpreter on CPU.
- ``xla``: a ``jnp.take``-based gather + masked softmax composite — the
  CPU-CI path and the parity oracle the kernel is tested against.

Quantized cache (KVQuant-style bandwidth multiplier): blocks may hold
``int8`` values with a float32 scale per (block, head, slot) stored in a
parallel ``[N, H, block_size]`` array — at bandwidth-bound decode,
quarter-size cache bytes are ~4x tokens/s headroom. ``quantize_kv``/
``dequantize_kv`` are the one symmetric-scale codec every writer/reader
shares (absmax / 127 per head-token, zero-scale guarded).

Layout: q ``[B, H, 1, D]`` (single decode step per row), k/v pools
``[num_blocks, H, block_size, D]``, block tables ``[B, blocks_per_row]``
int32 (entries past a row's allocation point at the reserved trash
block — masked by ``pos``), pos ``[B]`` int32 (index of the query's own
slot: key slot j is visible iff ``j <= pos[b]``).
"""
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_NEG_INF = -1e30
_QMAX = 127.0        # symmetric int8 range


def _auto_impl():
    backend = jax.default_backend()
    return "pallas" if backend in ("tpu", "axon") else "xla"


# ------------------------------------------------------------ quant codec

def quantize_kv(kv):
    """Symmetric per-head-token int8 quantization of ``kv`` [..., D]:
    returns (int8 values, float32 scale [...]) with
    ``scale = absmax(D) / 127`` (0 -> 1.0 so an all-zero vector round-
    trips exactly). The ONE codec shared by the pool writer ops, the
    prefill scatter and the attention readers."""
    kv = kv.astype(jnp.float32)
    scale = jnp.max(jnp.abs(kv), axis=-1) / _QMAX
    scale = jnp.where(scale == 0.0, 1.0, scale)
    q = jnp.round(kv / scale[..., None])
    q = jnp.clip(q, -_QMAX, _QMAX).astype(jnp.int8)
    return q, scale


def dequantize_kv(q, scale):
    """Inverse of :func:`quantize_kv`: int8 values [..., D] * scale
    [...] -> float32."""
    return q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)


# -------------------------------------------------------------- reference

def _xla_paged_attention(q, k_pool, v_pool, tables, pos, k_scale, v_scale,
                         scale):
    """Gather-then-attend composite: per-row ``jnp.take`` of the row's
    blocks, per-row position mask, fp32 softmax — identical math to
    ``ops.decode_ops.kv_cached_attention`` over the gathered layout.
    Runs anywhere (CPU CI) and is the kernel's parity oracle."""
    B, H, S, D = q.shape
    bs = k_pool.shape[2]
    nblk = tables.shape[1]
    L = nblk * bs

    def gather(pool, sc):
        # [B, nblk, H, bs, D] -> [B, H, L, D], dequantized
        g = jnp.take(pool, tables, axis=0)
        if sc is not None:
            gs = jnp.take(sc, tables, axis=0)        # [B, nblk, H, bs]
            g = dequantize_kv(g, gs)
        g = g.astype(jnp.float32)
        return g.transpose(0, 2, 1, 3, 4).reshape(B, H, L, D)

    k = gather(k_pool, k_scale)
    v = gather(v_pool, v_scale)
    scores = jnp.einsum("bhsd,bhld->bhsl", q.astype(jnp.float32),
                        k) * scale
    key_idx = jnp.arange(L, dtype=jnp.int32)[None, None, :]       # [1,1,L]
    qry_pos = pos.astype(jnp.int32)[:, None, None] \
        + jnp.arange(S, dtype=jnp.int32)[None, :, None]
    mask = key_idx <= qry_pos                                     # [B,S,L]
    scores = jnp.where(mask[:, None, :, :], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhsl,bhld->bhsd", probs, v)
    return out.astype(q.dtype)


# ----------------------------------------------------------------- kernel

def _paged_kernel(tables_ref, pos_ref, q_ref, k_ref, v_ref, ks_ref,
                  vs_ref, out_ref, m_sc, l_sc, acc_sc, *, scale, bs,
                  nblk):
    """One (b, h, j) grid step folds block j of row b into the running
    online-softmax state. The block-table gather already happened in the
    BlockSpec index map — k_ref/v_ref hold block ``tables[b, j]``."""
    b, j = pl.program_id(0), pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        m_sc[:] = jnp.full_like(m_sc, _NEG_INF)
        l_sc[:] = jnp.zeros_like(l_sc)
        acc_sc[:] = jnp.zeros_like(acc_sc)

    p = pos_ref[b]

    # dead-block skip: block j covers key slots [j*bs, (j+1)*bs); nothing
    # there is visible once j*bs > pos[b]. Block-table padding (trash
    # block 0) only ever appears PAST a row's allocation, so the same
    # predicate keeps garbage out of the state.
    @pl.when(j * bs <= p)
    def _fold():
        qv = q_ref[0, 0].astype(jnp.float32)                  # [1, D]
        kb = k_ref[0, 0]                                      # [bs, D]
        if ks_ref is not None:
            kb = kb.astype(jnp.float32) \
                * ks_ref[0, 0].reshape(bs, 1).astype(jnp.float32)
        s = jax.lax.dot_general(
            qv, kb, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale       # [1, bs]
        idx = j * bs + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        s = jnp.where(idx <= p, s, _NEG_INF)
        m_prev = m_sc[0, 0]
        m_new = jnp.maximum(m_prev, jnp.max(s))
        corr = jnp.exp(m_prev - m_new)
        pr = jnp.exp(s - m_new)                               # [1, bs]
        vb = v_ref[0, 0]
        if vs_ref is not None:
            vb = vb.astype(jnp.float32) \
                * vs_ref[0, 0].reshape(bs, 1).astype(jnp.float32)
        acc_sc[:, :] = acc_sc[:, :] * corr + jax.lax.dot_general(
            pr.astype(vb.dtype), vb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)               # [1, D]
        l_sc[0, 0] = l_sc[0, 0] * corr + jnp.sum(pr)
        m_sc[0, 0] = m_new

    @pl.when(j == nblk - 1)
    def _finalize():
        l = l_sc[0, 0]
        l = jnp.where(l == 0.0, 1.0, l)
        out_ref[0, 0] = (acc_sc[:, :] / l).astype(out_ref.dtype)


def _pallas_paged_attention(q, k_pool, v_pool, tables, pos, k_scale,
                            v_scale, scale, interpret):
    B, H, S, D = q.shape
    if S != 1:
        raise ValueError(
            f"paged_attention kernel decodes ONE query per row (S=1), "
            f"got S={S}; prefill goes through flash_attention")
    bs = k_pool.shape[2]
    nblk = tables.shape[1]
    quant = k_scale is not None

    # index maps see the grid indices THEN the scalar-prefetch refs:
    # the pool block for (b, j) is whatever the row's table names — the
    # fused gather
    in_specs = [
        pl.BlockSpec((1, 1, 1, D), lambda b, h, j, t, p: (b, h, 0, 0)),
        pl.BlockSpec((1, 1, bs, D),
                     lambda b, h, j, t, p: (t[b, j], h, 0, 0)),
        pl.BlockSpec((1, 1, bs, D),
                     lambda b, h, j, t, p: (t[b, j], h, 0, 0)),
    ]
    args = [q, k_pool, v_pool]
    if quant:
        in_specs += [
            pl.BlockSpec((1, 1, bs),
                         lambda b, h, j, t, p: (t[b, j], h, 0)),
            pl.BlockSpec((1, 1, bs),
                         lambda b, h, j, t, p: (t[b, j], h, 0)),
        ]
        args += [k_scale, v_scale]

    body = functools.partial(_paged_kernel, scale=scale, bs=bs, nblk=nblk)

    if quant:
        kern = body
    else:
        def kern(tables_ref, pos_ref, q_ref, k_ref, v_ref, out_ref,
                 m_sc, l_sc, acc_sc):
            body(tables_ref, pos_ref, q_ref, k_ref, v_ref, None, None,
                 out_ref, m_sc, l_sc, acc_sc)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, H, nblk),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, 1, 1, D),
                               lambda b, h, j, t, p: (b, h, 0, 0)),
        scratch_shapes=[pltpu.VMEM((1, 1), jnp.float32),
                        pltpu.VMEM((1, 1), jnp.float32),
                        pltpu.VMEM((1, D), jnp.float32)],
    )
    return pl.pallas_call(
        kern, grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, 1, D), q.dtype),
        interpret=interpret,
    )(tables.astype(jnp.int32), pos.astype(jnp.int32), *args)


# ----------------------------------------------------------- public entry

def paged_attention(q, k_pool, v_pool, block_tables, pos, k_scale=None,
                    v_scale=None, scale=None, impl=None):
    """Decode attention of one query per row over a block-paged KV pool.

    q ``[B, H, 1, D]``; k_pool/v_pool ``[num_blocks, H, block_size, D]``
    (float32/bfloat16, or int8 with ``k_scale``/``v_scale``
    ``[num_blocks, H, block_size]``); block_tables ``[B, blocks_per_row]``
    int32; pos ``[B]`` int32. Returns ``[B, H, 1, D]`` in q's dtype.
    impl: None (auto — pallas on TPU backends, xla elsewhere),
    "pallas", "interpret" (Pallas interpreter, CPU-runnable), "xla"
    (the gather composite / parity oracle)."""
    if scale is None or scale == 0.0:
        scale = float(q.shape[-1]) ** -0.5
    if (k_scale is None) != (v_scale is None):
        raise ValueError("paged_attention needs BOTH k_scale and "
                         "v_scale for a quantized pool (or neither)")
    if k_pool.dtype == jnp.int8 and k_scale is None:
        raise ValueError("int8 KV pool needs k_scale/v_scale arrays")
    if impl is None and q.shape[2] != 1:
        # the Pallas kernel decodes one query per row; chunked prefill
        # (S>1 queries over the paged pool) reads via the gather
        # composite, which masks key j against pos[b]+i per query i
        impl = "xla"
    impl = impl or _auto_impl()
    if impl == "xla":
        return _xla_paged_attention(q, k_pool, v_pool, block_tables, pos,
                                    k_scale, v_scale, float(scale))
    return _pallas_paged_attention(q, k_pool, v_pool, block_tables, pos,
                                   k_scale, v_scale, float(scale),
                                   impl == "interpret")
