"""Pallas TPU kernels: hand-written kernels for the hot ops where XLA's
default lowering leaves performance on the table (SURVEY §7 "Pallas kernels
only where XLA underperforms"). Each kernel ships with an XLA composite
fallback so every op runs on any backend; the Pallas path is selected on
TPU."""
from .flash_attention import flash_attention  # noqa: F401
from .paged_attention import (  # noqa: F401
    dequantize_kv, paged_attention, quantize_kv,
)
