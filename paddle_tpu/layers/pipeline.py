"""Pipeline stage builder (user surface for the "pipeline" op).

Reference counterpart: PipelineOptimizer's cut_list/place_list program
sections (/root/reference/python/paddle/fluid/optimizer.py:3554) executed by
SectionWorker threads with scope queues (framework/pipeline_trainer.cc:122).
TPU-native shape: one UNIFORM stage sub-block replicated across the "pp"
mesh axis; every parameter created inside the stage is re-stacked to a
leading [num_stages] dim (sharded over "pp") so each pipeline rank holds its
own stage weights, and the op lowers to the shard_map+ppermute GPipe
schedule in ops/pipeline_ops.py.

    pipe = layers.Pipeline(num_stages=4, num_microbatches=8)
    with pipe.stage():
        h = pipe.stage_input(x)           # x: [B, ...], B % M == 0
        y = layers.fc(h, d, act="relu")   # stage params auto-stacked
        pipe.stage_output(y)              # same shape/dtype as input
    out = pipe()                          # [B, ...]
"""
import contextlib

from ..framework import unique_name
from ..framework.core import Parameter, default_startup_program
from .control_flow import _outer_reads
from .layer_helper import LayerHelper


class Pipeline:
    def __init__(self, num_stages, num_microbatches, name=None):
        assert num_stages >= 1 and num_microbatches >= 1
        self.num_stages = int(num_stages)
        self.num_microbatches = int(num_microbatches)
        self.helper = LayerHelper("pipeline", name=name)
        self._block = None
        self._input = None       # (outer var, inner var)
        self._out_inner = None
        self._out_var = None

    @contextlib.contextmanager
    def stage(self):
        program = self.helper.main_program
        self._parent = program.current_block()
        params_before = set(program.global_block().vars)
        self._block = program._create_block()
        try:
            yield
        except BaseException:
            program._rollback()
            raise
        else:
            program._rollback()
            self._complete(params_before)

    def stage_input(self, x):
        assert self._block is not None, "call inside `with pipe.stage():`"
        assert x.shape and x.shape[0] not in (None, -1), \
            "pipeline needs a static batch dim"
        assert x.shape[0] % self.num_microbatches == 0, \
            f"batch {x.shape[0]} % num_microbatches " \
            f"{self.num_microbatches} != 0"
        assert self._input is None, "pipeline takes ONE stage_input"
        mb = x.shape[0] // self.num_microbatches
        iv = self._block.create_var(
            name=unique_name.generate(f"{self.helper.name}.stage_in"),
            shape=(mb,) + tuple(x.shape[1:]), dtype=x.dtype)
        self._input = (x, iv)
        return iv

    def stage_output(self, o):
        assert self._block is not None, "call inside `with pipe.stage():`"
        assert self._out_inner is None, "pipeline takes ONE stage_output"
        self._out_inner = o

    def _stack_param(self, program, param):
        """Give a stage-created param a leading [S] dim sharded over pp and
        patch its startup init ops (bounds were computed from the per-stage
        shape, so each stage slice keeps the right fan-in/out init)."""
        S = self.num_stages
        old_shape = tuple(param.shape)
        param.shape = (S,) + old_shape
        param.dist_attr = ("pp",)
        startup = default_startup_program().global_block()
        sv = startup.vars.get(param.name)
        if sv is not None:
            sv.shape = (S,) + old_shape
            sv.dist_attr = ("pp",)
        for op in startup.ops:
            if param.name in op.output_arg_names and "shape" in op.attrs:
                op.attrs["shape"] = [S] + list(old_shape)

    def _complete(self, params_before):
        program = self.helper.main_program
        parent = self._parent
        assert self._input is not None, "pipeline needs stage_input(x)"
        assert self._out_inner is not None, "pipeline needs stage_output(y)"
        x, iv = self._input
        out_inner = self._out_inner
        if tuple(out_inner.shape or ()) != tuple(iv.shape or ()) or \
                out_inner.dtype != iv.dtype:
            raise ValueError(
                f"pipeline stage must preserve shape/dtype (uniform chain): "
                f"in {iv.shape}/{iv.dtype} vs out "
                f"{out_inner.shape}/{out_inner.dtype}")

        gblock = program.global_block()
        new_params = [v for n, v in gblock.vars.items()
                      if n not in params_before and isinstance(v, Parameter)]
        reads = _outer_reads(program, self._block.idx,
                             exclude=[iv.name])
        p_names = [p.name for p in new_params]
        r_names = [n for n in reads if n not in p_names]
        for p in new_params:
            self._stack_param(program, p)

        out = parent.create_var(
            name=unique_name.generate(f"{self.helper.name}.out"),
            shape=x.shape, dtype=x.dtype)
        parent.append_op(
            type="pipeline",
            inputs={"X": [x], "P": p_names, "R": r_names},
            outputs={"Out": [out]},
            attrs={"sub_block": self._block.idx,
                   "num_stages": self.num_stages,
                   "num_microbatches": self.num_microbatches,
                   "x_name": iv.name, "out_name": out_inner.name,
                   "p_names": p_names, "r_names": r_names},
            infer_shape=False)
        self._out_var = out

    def __call__(self):
        assert self._out_var is not None, "finish `with pipe.stage():` first"
        return self._out_var
