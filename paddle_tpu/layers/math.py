"""Elementwise / reduction layer wrappers + Variable operator overloading
(reference: python/paddle/fluid/layers/nn.py reduce_*,
python/paddle/fluid/layers/math_op_patch.py)."""
import numpy as np

from ..framework.core import Variable
from .layer_helper import LayerHelper
from . import tensor as tensor_layers


def _binary(op_type, x, y, axis=-1, act=None, name=None):
    helper = LayerHelper(op_type, name=name, act=act)
    if np.isscalar(y):
        y = tensor_layers.fill_constant([1], x.dtype, float(y))
    if np.isscalar(x):
        x = tensor_layers.fill_constant([1], y.dtype, float(x))
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return helper.append_activation(out, act)


def elementwise_add(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_add", x, y, axis, act, name)


def elementwise_sub(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_sub", x, y, axis, act, name)


def elementwise_mul(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_mul", x, y, axis, act, name)


def elementwise_div(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_div", x, y, axis, act, name)


def elementwise_min(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_min", x, y, axis, act, name)


def elementwise_max(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_max", x, y, axis, act, name)


def elementwise_pow(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_pow", x, y, axis, act, name)


def elementwise_mod(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_mod", x, y, axis, act, name)


def elementwise_floordiv(x, y, axis=-1, act=None, name=None):
    return _binary("elementwise_floordiv", x, y, axis, act, name)


def _reduce(op_type, input, dim=None, keep_dim=False, name=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    if dim is None:
        attrs = {"dim": [0], "keep_dim": keep_dim, "reduce_all": True}
    else:
        if isinstance(dim, int):
            dim = [dim]
        attrs = {"dim": list(dim), "keep_dim": keep_dim,
                 "reduce_all": False}
    helper.append_op(type=op_type, inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs=attrs)
    return out


def reduce_sum(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_sum", input, dim, keep_dim, name)


def reduce_mean(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_mean", input, dim, keep_dim, name)


def reduce_max(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_max", input, dim, keep_dim, name)


def reduce_min(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_min", input, dim, keep_dim, name)


def reduce_prod(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_prod", input, dim, keep_dim, name)


def reduce_all(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_all", input, dim, keep_dim, name)


def reduce_any(input, dim=None, keep_dim=False, name=None):
    return _reduce("reduce_any", input, dim, keep_dim, name)


def mean(x, name=None):
    helper = LayerHelper("mean", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="mean", inputs={"X": [x]}, outputs={"Out": [out]})
    return out


def sum(x):
    helper = LayerHelper("sum")
    xs = x if isinstance(x, (list, tuple)) else [x]
    out = helper.create_variable_for_type_inference(dtype=xs[0].dtype)
    helper.append_op(type="sum", inputs={"X": list(xs)},
                     outputs={"Out": [out]})
    return out


def sums(input, out=None):
    helper = LayerHelper("sum")
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype=input[0].dtype)
    helper.append_op(type="sum", inputs={"X": list(input)},
                     outputs={"Out": [out]})
    return out


def scale(x, scale=1.0, bias=0.0, bias_after_scale=True, act=None,
          name=None):
    helper = LayerHelper("scale", name=name, act=act)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="scale", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"scale": float(scale), "bias": float(bias),
                            "bias_after_scale": bias_after_scale})
    return helper.append_activation(out, act)


def _cmp(op_type, x, y, cond=None):
    helper = LayerHelper(op_type)
    if np.isscalar(y):
        y = tensor_layers.fill_constant([1], x.dtype, float(y))
    if cond is None:
        cond = helper.create_variable_for_type_inference(
            dtype="bool", stop_gradient=True)
    helper.append_op(type=op_type, inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [cond]})
    return cond


def less_than(x, y, cond=None):
    return _cmp("less_than", x, y, cond)


def less_equal(x, y, cond=None):
    return _cmp("less_equal", x, y, cond)


def greater_than(x, y, cond=None):
    return _cmp("greater_than", x, y, cond)


def greater_equal(x, y, cond=None):
    return _cmp("greater_equal", x, y, cond)


def equal(x, y, cond=None):
    return _cmp("equal", x, y, cond)


def not_equal(x, y, cond=None):
    return _cmp("not_equal", x, y, cond)


def logical_and(x, y, out=None, name=None):
    return _cmp("logical_and", x, y, out)


def logical_or(x, y, out=None, name=None):
    return _cmp("logical_or", x, y, out)


def logical_xor(x, y, out=None, name=None):
    return _cmp("logical_xor", x, y, out)


def logical_not(x, out=None, name=None):
    helper = LayerHelper("logical_not", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(
            dtype="bool", stop_gradient=True)
    helper.append_op(type="logical_not", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


# ---- operator overloading (math_op_patch parity, one impl for both
# static Variables and eager VarBases — the layer wrappers dispatch through
# the dygraph-aware LayerHelper) ----

def _install_op_overloads(cls):
    def _make_binop(op_type, reverse=False):
        def impl(self, other):
            if reverse:
                return _binary(op_type, other, self)
            return _binary(op_type, self, other)
        return impl

    cls.__add__ = _make_binop("elementwise_add")
    cls.__radd__ = _make_binop("elementwise_add", reverse=True)
    cls.__sub__ = _make_binop("elementwise_sub")
    cls.__rsub__ = _make_binop("elementwise_sub", reverse=True)
    cls.__mul__ = _make_binop("elementwise_mul")
    cls.__rmul__ = _make_binop("elementwise_mul", reverse=True)
    cls.__truediv__ = _make_binop("elementwise_div")
    cls.__rtruediv__ = _make_binop("elementwise_div", reverse=True)
    cls.__pow__ = _make_binop("elementwise_pow")
    cls.__mod__ = _make_binop("elementwise_mod")
    cls.__floordiv__ = _make_binop("elementwise_floordiv")
    cls.__neg__ = lambda self: scale(self, scale=-1.0)
    cls.__lt__ = lambda self, o: _cmp("less_than", self, o)
    cls.__le__ = lambda self, o: _cmp("less_equal", self, o)
    cls.__gt__ = lambda self, o: _cmp("greater_than", self, o)
    cls.__ge__ = lambda self, o: _cmp("greater_equal", self, o)
    # reference math_op_patch.py:278 patches __eq__/__ne__ to equal/
    # not_equal ops on both static and dygraph vars. Defining __eq__
    # would drop the inherited __hash__ — restore identity hashing
    # (Variables are dict keys, e.g. executor feed dicts).
    cls.__eq__ = lambda self, o: _cmp("equal", self, o)
    cls.__ne__ = lambda self, o: _cmp("not_equal", self, o)
    cls.__hash__ = object.__hash__


_install_op_overloads(Variable)


def _patch_varbase():
    from ..dygraph.base import VarBase
    _install_op_overloads(VarBase)


_patch_varbase()


def einsum(equation, *operands, name=None):
    """paddle.einsum (2.x API): Einstein summation over operands."""
    from .layer_helper import LayerHelper
    helper = LayerHelper("einsum", name=name)
    out = helper.create_variable_for_type_inference(operands[0].dtype)
    helper.append_op(type="einsum",
                     inputs={"Operands": list(operands)},
                     outputs={"Out": [out]},
                     attrs={"equation": equation})
    return out
