"""fluid.layers-compatible namespace (reference: python/paddle/fluid/layers/)."""
from .. import ops  # noqa: F401  (registers op lowerings)
from .nn import *          # noqa: F401,F403
from .tensor import *      # noqa: F401,F403
from .loss import *        # noqa: F401,F403
from .math import *        # noqa: F401,F403
from .control_flow import (  # noqa: F401
    While, Switch, StaticRNN, DynamicRNN, IfElse, Print, case,
    switch_case, cond, create_array, array_read, array_write,
    array_length,
)
from .sequence_lod import (  # noqa: F401
    sequence_pool, sequence_first_step, sequence_last_step,
    sequence_expand, sequence_scatter, lod_reset, lod_append,
    sequence_softmax, sequence_reverse, sequence_expand_as, sequence_pad,
    sequence_unpad, sequence_concat, sequence_slice, sequence_erase,
    sequence_enumerate, sequence_reshape, sequence_mask, sequence_conv,
)
from .pipeline import Pipeline  # noqa: F401
from .rnn_api import (  # noqa: F401
    RNNCell, LSTMCell, GRUCell, rnn, Decoder, BasicDecoder,
    BeamSearchDecoder, dynamic_decode, DecodeHelper,
    TrainingHelper, GreedyEmbeddingHelper, SampleEmbeddingHelper)
from . import rnn_api  # noqa: F401
from .distributions import (  # noqa: F401
    Uniform, Normal, Categorical, MultivariateNormalDiag)
from . import distributions  # noqa: F401
from . import nn, tensor, loss, math, control_flow, sequence_lod  # noqa: F401
from . import pipeline  # noqa: F401
from .collective import _allreduce, _allgather, _broadcast, shard  # noqa: F401
from .more import *       # noqa: F401,F403
from . import more         # noqa: F401
from .detection import *   # noqa: F401,F403
from . import detection    # noqa: F401
from .learning_rate_scheduler import (  # noqa: F401
    noam_decay, exponential_decay, natural_exp_decay, inverse_time_decay,
    polynomial_decay, piecewise_decay, cosine_decay, linear_lr_warmup,
    autoincreased_step_counter,
)
from . import learning_rate_scheduler  # noqa: F401
