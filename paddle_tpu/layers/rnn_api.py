"""fluid.layers RNN cell / decoder API (reference
python/paddle/fluid/layers/rnn.py — RNNCell :38, LSTMCell :159,
GRUCell :262, rnn() :356, Decoder :565, BeamSearchDecoder :636,
dynamic_decode :1110, DecodeHelper/TrainingHelper/GreedyEmbeddingHelper/
SampleEmbeddingHelper :1330-1600, BasicDecoder :1680).

TPU-first design: `rnn()` and `dynamic_decode()` unroll over the STATIC
time bound (XLA requires static shapes; the reference's while_op loop
becomes a bounded unroll whose per-step writes are masked by
finished/sequence-length state — same results, one compiled program).
Batch-major [B, T, ...] tensors, like the rest of the masked-dense
design."""
import numpy as np

from ..framework.core import Variable
from . import math as M
from . import tensor as T
from .layer_helper import LayerHelper

__all__ = [
    "RNNCell", "LSTMCell", "GRUCell", "rnn", "Decoder", "BasicDecoder",
    "BeamSearchDecoder", "dynamic_decode", "DecodeHelper",
    "TrainingHelper", "GreedyEmbeddingHelper", "SampleEmbeddingHelper",
]


def _L():
    from .. import layers
    return layers


class RNNCell:
    """Base cell: call(inputs, states) -> (outputs, new_states)
    (reference rnn.py:38)."""

    def call(self, inputs, states, **kwargs):
        raise NotImplementedError

    def __call__(self, inputs, states, **kwargs):
        return self.call(inputs, states, **kwargs)

    def get_initial_states(self, batch_ref, shape=None, dtype="float32",
                           init_value=0.0, batch_dim_idx=0):
        B = int(batch_ref.shape[batch_dim_idx])
        shape = list(shape or [self.hidden_size])
        return T.fill_constant([B] + shape, dtype, init_value)


class LSTMCell(RNNCell):
    """reference rnn.py:159 (lstm_cell_fused lowering; gate order
    i,f,c,o with forget_bias)."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 forget_bias=1.0, dtype="float32", name="lstm_cell"):
        self.hidden_size = int(hidden_size)
        self.param_attr = param_attr
        self.bias_attr = bias_attr
        self.forget_bias = float(forget_bias)
        self.dtype = dtype
        self.name = name
        self._w = None
        self._b = None

    def call(self, inputs, states):
        h_prev, c_prev = states
        helper = LayerHelper(self.name, param_attr=self.param_attr,
                             bias_attr=self.bias_attr)
        H = self.hidden_size
        if self._w is None:
            # later calls may see inference-opaque input shapes (e.g.
            # argmax-fed embeddings); weights fix D after the first call
            D = int(inputs.shape[-1])
            self._w = helper.create_parameter(
                helper.param_attr, shape=[D + H, 4 * H], dtype=self.dtype)
            from ..framework import initializer as init_mod
            self._b = helper.create_parameter(
                helper.bias_attr, shape=[4 * H], dtype=self.dtype,
                default_initializer=init_mod.ConstantInitializer(0.0))
        h = helper.create_variable_for_type_inference(dtype=self.dtype)
        c = helper.create_variable_for_type_inference(dtype=self.dtype)
        B = (inputs.shape or h_prev.shape or (None,))[0]
        if B is not None:
            h.shape = c.shape = (B, H)
        helper.append_op(
            type="lstm_cell_fused",
            inputs={"X": [inputs], "HPrev": [h_prev], "CPrev": [c_prev],
                    "W": [self._w], "B": [self._b]},
            outputs={"H": [h], "C": [c]},
            attrs={"forget_bias": self.forget_bias},
            infer_shape=False)
        return h, [h, c]

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        B = int(batch_ref.shape[batch_dim_idx])
        mk = lambda: T.fill_constant([B, self.hidden_size],
                                     dtype or self.dtype, init_value)
        return [mk(), mk()]


class GRUCell(RNNCell):
    """reference rnn.py:262 (gru_cell_fused lowering)."""

    def __init__(self, hidden_size, param_attr=None, bias_attr=None,
                 dtype="float32", name="gru_cell", origin_mode=False):
        self.hidden_size = int(hidden_size)
        self.param_attr = param_attr
        self.bias_attr = bias_attr
        self.dtype = dtype
        self.name = name
        self.origin_mode = bool(origin_mode)
        self._wg = self._bg = self._wc = self._bc = None

    def call(self, inputs, states):
        h_prev = states[0] if isinstance(states, (list, tuple)) else states
        helper = LayerHelper(self.name, param_attr=self.param_attr,
                             bias_attr=self.bias_attr)
        H = self.hidden_size
        if self._wg is None:
            D = int(inputs.shape[-1])
            from ..framework import initializer as init_mod
            self._wg = helper.create_parameter(
                helper.param_attr, shape=[D + H, 2 * H], dtype=self.dtype)
            self._bg = helper.create_parameter(
                helper.bias_attr, shape=[2 * H], dtype=self.dtype,
                default_initializer=init_mod.ConstantInitializer(0.0))
            self._wc = helper.create_parameter(
                helper.param_attr, shape=[D + H, H], dtype=self.dtype)
            self._bc = helper.create_parameter(
                helper.bias_attr, shape=[H], dtype=self.dtype,
                default_initializer=init_mod.ConstantInitializer(0.0))
        h = helper.create_variable_for_type_inference(dtype=self.dtype)
        B = (inputs.shape or h_prev.shape or (None,))[0]
        if B is not None:
            h.shape = (B, H)
        helper.append_op(
            type="gru_cell_fused",
            inputs={"X": [inputs], "HPrev": [h_prev],
                    "WGate": [self._wg], "BGate": [self._bg],
                    "WCand": [self._wc], "BCand": [self._bc]},
            outputs={"H": [h]},
            attrs={"origin_mode": self.origin_mode},
            infer_shape=False)
        return h, [h]

    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        B = int(batch_ref.shape[batch_dim_idx])
        return [T.fill_constant([B, self.hidden_size],
                                dtype or self.dtype, init_value)]


def _mask_state(new, old, mask_col):
    """new where mask else old; mask_col [B, 1] float."""
    return M.elementwise_add(
        old, M.elementwise_mul(M.elementwise_sub(new, old), mask_col))


def rnn(cell, inputs, initial_states=None, sequence_length=None,
        time_major=False, is_reverse=False, **kwargs):
    """Run a cell over a sequence (reference rnn.py:356). inputs
    [B, T, D] (or [T, B, D] with time_major); returns (outputs
    [B, T, H], final_states). Static unroll with per-step masking by
    sequence_length — the TPU analog of the reference's while loop."""
    if time_major:
        nd = len(inputs.shape)
        inputs = T.transpose(inputs, [1, 0] + list(range(2, nd)))
    B, T_len = int(inputs.shape[0]), int(inputs.shape[1])
    states = initial_states
    if states is None:
        states = cell.get_initial_states(inputs)
    if isinstance(states, Variable):
        states = [states]
    mask = None
    if sequence_length is not None:
        from .sequence_lod import sequence_mask
        mask = sequence_mask(sequence_length, maxlen=T_len,
                             dtype="float32")          # [B, T]
    step_outs = []
    order = range(T_len - 1, -1, -1) if is_reverse else range(T_len)
    for t in order:
        x_t = T.reshape(
            T.slice(inputs, axes=[1], starts=[t], ends=[t + 1]),
            [B] + [int(s) for s in inputs.shape[2:]])
        out, new_states = cell(x_t, states if len(states) > 1
                               else states[0], **kwargs) \
            if not isinstance(cell, RNNCell) \
            else cell.call(x_t, states, **kwargs)
        if not isinstance(new_states, (list, tuple)):
            new_states = [new_states]
        if mask is not None:
            m_t = T.reshape(
                T.slice(mask, axes=[1], starts=[t], ends=[t + 1]),
                [B, 1])
            new_states = [_mask_state(ns, s, m_t)
                          for ns, s in zip(new_states, states)]
            out = M.elementwise_mul(out, m_t)
        states = list(new_states)
        step_outs.append(out)
    if is_reverse:
        step_outs = step_outs[::-1]
    outputs = T.stack(step_outs, axis=1)               # [B, T, H]
    if time_major:
        nd = len(outputs.shape)
        outputs = T.transpose(outputs, [1, 0] + list(range(2, nd)))
    final = states if len(states) > 1 else states[0]
    return outputs, final


# ---------------------------------------------------------------- decoding

class DecodeHelper:
    """initialize() -> (initial_inputs, initial_finished);
    sample(time, outputs, states) -> sample_ids;
    next_inputs(time, outputs, states, sample_ids)
    -> (finished, next_inputs, next_states) (reference rnn.py:1330)."""

    def initialize(self):
        raise NotImplementedError

    def sample(self, time, outputs, states):
        raise NotImplementedError

    def next_inputs(self, time, outputs, states, sample_ids):
        raise NotImplementedError


class TrainingHelper(DecodeHelper):
    """Teacher forcing from ground-truth inputs [B, T, D]
    (reference rnn.py:1378)."""

    def __init__(self, inputs, sequence_length=None, time_major=False):
        if time_major:
            nd = len(inputs.shape)
            inputs = T.transpose(inputs, [1, 0] + list(range(2, nd)))
        self.inputs = inputs
        self.sequence_length = sequence_length
        self.B = int(inputs.shape[0])
        self.T = int(inputs.shape[1])

    def _step_input(self, t):
        return T.reshape(
            T.slice(self.inputs, axes=[1], starts=[t], ends=[t + 1]),
            [self.B] + [int(s) for s in self.inputs.shape[2:]])

    def initialize(self):
        finished = T.fill_constant([self.B], "bool", False)
        if self.sequence_length is not None:
            finished = M.less_than(
                self.sequence_length,
                T.fill_constant(list(self.sequence_length.shape),
                                self.sequence_length.dtype, 1))
        return self._step_input(0), finished

    def sample(self, time, outputs, states):
        return T.argmax(outputs, axis=-1)

    def next_inputs(self, time, outputs, states, sample_ids):
        t_next = time + 1
        if t_next >= self.T:
            nxt = self._step_input(self.T - 1)   # past end: repeat last
            finished = T.fill_constant([self.B], "bool", True)
        else:
            nxt = self._step_input(t_next)
            if self.sequence_length is not None:
                finished = M.less_equal(
                    self.sequence_length,
                    T.fill_constant(list(self.sequence_length.shape),
                                    self.sequence_length.dtype,
                                    t_next))
            else:
                finished = T.fill_constant([self.B], "bool", False)
        return finished, nxt, states


class GreedyEmbeddingHelper(DecodeHelper):
    """Argmax feedback through an embedding fn (reference rnn.py:1480)."""

    def __init__(self, embedding_fn, start_tokens, end_token):
        self.embedding_fn = embedding_fn
        self.start_tokens = start_tokens          # [B] int64
        self.end_token = int(end_token)

    def initialize(self):
        B = int(self.start_tokens.shape[0])
        finished = T.fill_constant([B], "bool", False)
        return self.embedding_fn(self.start_tokens), finished

    def sample(self, time, outputs, states):
        return T.argmax(outputs, axis=-1)

    def next_inputs(self, time, outputs, states, sample_ids):
        finished = M.equal(
            T.cast(sample_ids, "int64"),
            T.fill_constant([1], "int64", self.end_token))
        return finished, self.embedding_fn(sample_ids), states


class SampleEmbeddingHelper(GreedyEmbeddingHelper):
    """Categorical sampling feedback (reference rnn.py:1550) via the
    sampling_id op over softmax(outputs)."""

    def __init__(self, embedding_fn, start_tokens, end_token,
                 softmax_temperature=None, seed=None):
        super().__init__(embedding_fn, start_tokens, end_token)
        self.temperature = softmax_temperature
        self.seed = seed or 0

    def sample(self, time, outputs, states):
        from .nn import softmax
        logits = outputs
        if self.temperature is not None:
            logits = M.scale(logits, 1.0 / float(self.temperature))
        probs = softmax(logits)
        return _L().sampling_id(probs, seed=self.seed)


class Decoder:
    """initialize(inits) -> (inputs, states, finished);
    step(time, inputs, states) -> (outputs, states, inputs, finished)
    (reference rnn.py:565)."""

    def initialize(self, inits):
        raise NotImplementedError

    def step(self, time, inputs, states, **kwargs):
        raise NotImplementedError

    def finalize(self, outputs, final_states, sequence_lengths):
        return outputs, final_states


class BasicDecoder(Decoder):
    """cell + helper (+ output layer fn) (reference rnn.py:1680).
    step outputs are (cell_outputs, sample_ids) pairs."""

    def __init__(self, cell, helper, output_fn=None):
        self.cell = cell
        self.helper = helper
        self.output_fn = output_fn

    def initialize(self, initial_cell_states):
        inputs, finished = self.helper.initialize()
        return inputs, initial_cell_states, finished

    def step(self, time, inputs, states, **kwargs):
        cell_outputs, cell_states = self.cell.call(inputs, states)
        if self.output_fn is not None:
            cell_outputs = self.output_fn(cell_outputs)
        sample_ids = self.helper.sample(time, cell_outputs, cell_states)
        finished, next_inputs, next_states = self.helper.next_inputs(
            time, cell_outputs, cell_states, sample_ids)
        return ((cell_outputs, sample_ids), next_states, next_inputs,
                finished)


def dynamic_decode(decoder, inits=None, max_step_num=None,
                   output_time_major=False, impute_finished=False,
                   is_test=False, return_length=False, **kwargs):
    """Run a Decoder to completion (reference rnn.py:1110). On TPU the
    loop is a bounded static unroll over max_step_num with
    finished-masked state updates — identical results to the
    reference's dynamic while loop for any decode that fits the bound."""
    assert max_step_num is not None, \
        "dynamic_decode on TPU needs max_step_num (static bound)"
    inputs, states, finished = decoder.initialize(inits)
    if isinstance(states, Variable):
        states = [states]
    outputs_ta = []
    ids_ta = []
    lengths = None
    for t in range(int(max_step_num)):
        step_out, next_states, next_inputs, next_finished = decoder.step(
            t, inputs, states if len(states) > 1 else states[0], **kwargs)
        if not isinstance(next_states, (list, tuple)):
            next_states = [next_states]
        cell_out, sample_ids = step_out if isinstance(step_out, tuple) \
            else (step_out, None)
        not_fin = T.cast(_L().logical_not(finished), "float32")
        m_col = T.reshape(not_fin, [-1, 1])
        tracks_own = getattr(decoder, "tracks_own_finished_state", False)
        if not tracks_own:
            cell_out = M.elementwise_mul(cell_out, m_col)
        outputs_ta.append(cell_out)
        if sample_ids is not None:
            ids_ta.append(sample_ids)
        if lengths is None:
            lengths = T.cast(not_fin, "int64")
        else:
            lengths = M.elementwise_add(lengths, T.cast(not_fin, "int64"))
        if tracks_own:
            # the decoder's step already carried finished rows (e.g.
            # beam parent-gather); masking here would blend PRE-reorder
            # slots into the post-reorder layout
            states = list(next_states)
        else:
            states = [_mask_state(ns, s, m_col)
                      for ns, s in zip(next_states, states)]
        inputs = next_inputs
        finished = _L().logical_or(finished, next_finished)
    outputs = T.stack(outputs_ta, axis=1)          # [B, T, ...]
    ids = T.stack(ids_ta, axis=1) if ids_ta else None
    final = states if len(states) > 1 else states[0]
    outputs, final = decoder.finalize((outputs, ids), final, lengths)
    if output_time_major:
        o0 = outputs[0] if isinstance(outputs, tuple) else outputs
        nd = len(o0.shape)
        perm = [1, 0] + list(range(2, nd))
        if isinstance(outputs, tuple):
            outputs = tuple(T.transpose(o, perm[:len(o.shape)])
                            if o is not None else None for o in outputs)
        else:
            outputs = T.transpose(outputs, perm)
    if return_length:
        return outputs, final, lengths
    return outputs, final


class BeamSearchDecoder(Decoder):
    """Beam search over a cell (reference rnn.py:636): states tile to
    [B*beam, ...]; each step scores V continuations per beam with the
    beam_search op and re-gathers states by parent; finalize back-traces
    with gather_tree. tracks_own_state: the parent-gather already
    carries finished beams, and dynamic_decode's generic finished-mask
    would blend PRE-reorder slots into the post-reorder layout
    (reference BeamSearchDecoder.tracks_own_finished_state)."""

    tracks_own_finished_state = True

    def __init__(self, cell, start_token, end_token, beam_size,
                 embedding_fn=None, output_fn=None):
        self.cell = cell
        self.start_token = int(start_token)
        self.end_token = int(end_token)
        self.beam_size = int(beam_size)
        self.embedding_fn = embedding_fn
        self.output_fn = output_fn

    def _tile(self, x):
        """[B, ...] -> [B*beam, ...] (repeat each row beam times)."""
        B = int(x.shape[0])
        nd = len(x.shape)
        e = _L().unsqueeze(x, [1])                         # [B, 1, ...]
        reps = [1, self.beam_size] + [1] * (nd - 1)
        e = T.expand(e, reps)
        return T.reshape(e, [B * self.beam_size] +
                         [int(s) for s in x.shape[1:]])

    def initialize(self, initial_cell_states):
        states = initial_cell_states
        if isinstance(states, Variable):
            states = [states]
        B = int(states[0].shape[0])
        self.B = B
        states = [self._tile(s) for s in states]
        ids0 = T.fill_constant([B, self.beam_size], "int64",
                               self.start_token)
        # only beam 0 live at start: others -inf so the first expansion
        # draws from a single beam
        np_init = np.full((1, self.beam_size), -1e30, np.float32)
        np_init[0, 0] = 0.0
        scores0 = _L().expand(T.assign(np_init), [B, 1])
        self._pre_ids = ids0
        self._pre_scores = scores0
        self._ids_ta = []
        self._parents_ta = []
        inputs = self.embedding_fn(T.reshape(ids0, [-1]))
        finished = T.fill_constant([B * self.beam_size], "bool", False)
        return inputs, states, finished

    def step(self, time, inputs, states, **kwargs):
        return _beam_step(self, time, inputs, states, **kwargs)

    def finalize(self, outputs, final_states, sequence_lengths):
        return _beam_finalize(self, outputs, final_states,
                              sequence_lengths)


def _beam_step(self, time, inputs, states, **kwargs):
    from .nn import softmax
    cell_outputs, cell_states = self.cell.call(inputs, states)
    if self.output_fn is not None:
        cell_outputs = self.output_fn(cell_outputs)
    if not isinstance(cell_states, (list, tuple)):
        cell_states = [cell_states]
    probs = softmax(cell_outputs)                   # [B*beam, V]
    logp = _L().log(probs)
    sel_ids, sel_scores, parent = _L().beam_search(
        self._pre_ids, self._pre_scores, logp, self.beam_size,
        end_id=self.end_token)
    self._ids_ta.append(sel_ids)
    self._parents_ta.append(parent)
    self._pre_ids = sel_ids
    self._pre_scores = sel_scores
    # re-gather states by parent beam: flat index = b*beam + parent
    offs = T.assign(
        (np.arange(self.B, dtype=np.int64) * self.beam_size
         ).reshape(self.B, 1))
    flat_parent = T.reshape(
        M.elementwise_add(T.cast(parent, "int64"),
                          _L().expand(offs, [1, self.beam_size])), [-1])
    next_states = [T.gather(s, flat_parent) for s in cell_states]
    next_inputs = self.embedding_fn(T.reshape(sel_ids, [-1]))
    finished = T.reshape(
        M.equal(T.cast(sel_ids, "int64"),
                T.fill_constant([1], "int64", self.end_token)), [-1])
    return ((cell_outputs, sel_ids), next_states, next_inputs, finished)


def _beam_finalize(self, outputs, final_states, sequence_lengths):
    ids = T.stack(self._ids_ta, axis=0)         # [T, B, beam]
    parents = T.stack(self._parents_ta, axis=0)
    seqs = _L().gather_tree(ids, parents)
    return (seqs, self._pre_scores), final_states
