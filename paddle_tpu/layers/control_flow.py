"""Control-flow layer builders (reference:
python/paddle/fluid/layers/control_flow.py — While :1035, cond :1884,
Switch :2442, StaticRNN :431, array ops :1280-1420).

The builders create nested sub-blocks exactly like the reference; the ops
they emit lower to lax.while_loop / lax.cond / lax.scan (see
ops/control_flow_ops.py) instead of nested Executor runs.
"""
import contextlib

import numpy as np

from ..framework import unique_name
from ..framework.core import Variable, VarType
from ..framework.lowering import analyze_block_io
from .layer_helper import LayerHelper


def _outer_reads(program, block_idx, exclude=()):
    reads, _ = analyze_block_io(program, block_idx, list(exclude))
    parent = program.blocks[block_idx].parent_block
    return [n for n in reads if parent is not None and parent.has_var(n)]


def _defining_op(block, name, stop_op=None):
    """Last op in `block` (or an ancestor) writing `name`, looking only
    at ops BEFORE `stop_op` when given (the while op itself rewrites its
    loop state, so post-hoc re-derivation must not see it); returns
    (op, block) or (None, None)."""
    b = block
    while b is not None:
        found = None
        for op in b.ops:
            if stop_op is not None and op is stop_op:
                break
            if any(name in ns for ns in op.outputs.values()):
                found = op
        if found is not None:
            return found, b
        b = b.parent_block
    return None, None


def _const_scalar(block, name, stop_op=None):
    op, _ = _defining_op(block, name, stop_op)
    if op is not None and op.type == "fill_constant":
        try:
            return float(op.attrs.get("value", 0.0))
        except (TypeError, ValueError):
            return None
    return None


def _other_writers(block, name, keep_op, skip_op=None):
    """Any op (in `block` or an ancestor) besides keep_op/skip_op that
    writes `name` — an outer loop body mutating a bound constant after
    the inner loop makes the derived trip count unsound."""
    b = block
    while b is not None:
        for op in b.ops:
            if op is keep_op or op is skip_op:
                continue
            if any(name in ns for ns in op.outputs.values()):
                return True
        b = b.parent_block
    return False


def _counter_step(sub, parent, ivar):
    """Constant positive per-iteration increment of `ivar` inside the
    loop body, or None. Recognizes increment(i) and i = i + const."""
    writers = [op for op in sub.ops
               if any(ivar in ns for ns in op.outputs.values())]
    if len(writers) != 1:
        return None
    op = writers[0]
    if op.type == "increment":
        step = float(op.attrs.get("step", 1.0))
        return step if step > 0 else None
    if op.type == "elementwise_add":
        xs = op.inputs.get("X", [])
        ys = op.inputs.get("Y", [])
        for a, b in ((xs, ys), (ys, xs)):
            if a and a[0] == ivar and b:
                c = _const_scalar(sub, b[0])
                if c is None:
                    c = _const_scalar(parent, b[0])
                if c is not None and c > 0:
                    return c
    return None


def _infer_max_trip(program, parent, sub, cond_name, stop_op=None):
    """Static trip bound for the reference decoder idiom: the rebound
    loop condition is less_than/less_equal(i, n) (possibly under
    logical_and, e.g. dygraph_to_static's synthesized `and not brk`)
    with n a build-time constant and i a constant-initialized counter
    incremented by a constant step in the body. Returns int or None.
    The bound stays valid when other conjuncts end the loop earlier —
    the masked-scan lowering handles early exit exactly
    (reference while_op.cc needs no bound; this recovers its
    differentiability on TPU's static-shape terms)."""
    import math

    def bound_of(name, depth):
        if depth > 4:
            return None
        op, _ = _defining_op(sub, name)
        if op is None:
            op, _ = _defining_op(parent, name, stop_op)
        if op is None:
            return None
        if op.type in ("logical_and", "assign"):
            cands = [bound_of(ns[0], depth + 1)
                     for s, ns in op.inputs.items() if ns]
            cands = [c for c in cands if c is not None]
            return min(cands) if cands else None
        if op.type not in ("less_than", "less_equal"):
            return None
        xs, ys = op.inputs.get("X", []), op.inputs.get("Y", [])
        if not xs or not ys:
            return None
        ivar, nvar = xs[0], ys[0]
        n_op, n_blk = _defining_op(sub, nvar)
        if n_op is None:
            n_op, n_blk = _defining_op(parent, nvar, stop_op)
        if n_op is None or n_op.type != "fill_constant":
            return None
        try:
            n_val = float(n_op.attrs.get("value", 0.0))
        except (TypeError, ValueError):
            return None
        # the bound must be a true constant: no OTHER writer anywhere in
        # the loop body or the enclosing block chain (an outer loop
        # mutating it after this loop would re-execute that write)
        if _other_writers(sub, nvar, n_op) or \
                _other_writers(parent, nvar, n_op, skip_op=stop_op):
            return None
        i0_op, i0_blk = _defining_op(parent, ivar, stop_op)
        if i0_op is None or i0_op.type != "fill_constant":
            return None
        i0 = float(i0_op.attrs.get("value", 0.0))
        step = _counter_step(sub, parent, ivar)
        if step is None:
            return None
        span = n_val - i0 + (1.0 if op.type == "less_equal" else 0.0)
        if span <= 0:
            return 0
        return int(math.ceil(span / step))

    return bound_of(cond_name, 0)


class While:
    """fluid.layers.While loop builder.

    i = fill_constant([1], 'int64', 0)
    cond = less_than(i, n)
    w = While(cond)
    with w.block():
        ...
        increment(i)
        less_than(i, n, cond=cond)   # rebind the condition var
    """

    def __init__(self, cond, is_test=False, name=None, max_trip_count=None):
        """`max_trip_count` (TPU extension, not in the reference signature):
        a static upper bound on iterations. Setting it makes the loop
        reverse-mode differentiable (bounded masked-scan lowering, see
        ops/control_flow_ops.py while_op); without it the bound is
        AUTO-DERIVED from counter-vs-constant loop conditions
        (_infer_max_trip) — reference-style decoder loops differentiate
        with no extra kwarg, matching while_op.cc's boundless grad.
        Underivable loops lower to lax.while_loop (forward-only)."""
        self.cond_var = cond
        self.max_trip_count = max_trip_count
        self.helper = LayerHelper("while", name=name)

    @contextlib.contextmanager
    def block(self):
        program = self.helper.main_program
        parent = program.current_block()
        sub = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        from ..ops.control_flow_ops import block_writes
        for op in program.blocks[sub.idx].ops:
            if op.type == "write_to_array":
                raise ValueError(
                    "array_write inside a While body is not supported "
                    "(trace-time arrays cannot be loop state); collect "
                    "per-step values with StaticRNN step outputs instead")
        writes = [n for n in block_writes(program, sub.idx)
                  if parent.has_var(n)]
        reads = _outer_reads(program, sub.idx)
        # loop-state writes must also be op inputs: the carry is initialized
        # from them, and grads of the initial values flow out through X@GRAD
        x_names = list(reads)
        for n in writes:
            if n not in x_names and n != self.cond_var.name:
                x_names.append(n)
        max_trip = self.max_trip_count
        auto = False
        if max_trip is None:
            max_trip = _infer_max_trip(program, parent,
                                       program.blocks[sub.idx],
                                       self.cond_var.name)
            auto = max_trip is not None
        attrs = {"sub_block": sub.idx, "cond_name": self.cond_var.name,
                 "x_names": x_names, "out_names": writes}
        if max_trip is not None:
            attrs["max_trip_count"] = int(max_trip)
            if auto:
                # re-validated at lowering time when the program is
                # FINAL: ops appended after this point (e.g. an outer
                # loop mutating the bound) could invalidate the
                # derivation (ops/control_flow_ops.py while_op)
                attrs["max_trip_count_auto"] = True
        parent.append_op(
            type="while",
            inputs={"Condition": [self.cond_var], "X": x_names},
            outputs={"Out": writes},
            attrs=attrs,
            infer_shape=False)


def cond(pred, true_fn=None, false_fn=None, name=None):
    """fluid.layers.cond — returns merged branch outputs (single Variable or
    flat list/tuple of Variables; both branches must match)."""
    helper = LayerHelper("cond", name=name)
    program = helper.main_program
    parent = program.current_block()

    def build(fn):
        blk = program._create_block()
        try:
            out = fn() if fn is not None else None
        finally:
            program._rollback()
        if out is None:
            outs = []
        elif isinstance(out, (list, tuple)):
            outs = list(out)
        else:
            outs = [out]
        return blk, outs

    t_blk, t_outs = build(true_fn)
    f_blk, f_outs = build(false_fn)
    if len(t_outs) != len(f_outs):
        raise ValueError(
            f"cond branches must return the same number of outputs "
            f"({len(t_outs)} vs {len(f_outs)})")

    reads = sorted(set(_outer_reads(program, t_blk.idx)) |
                   set(_outer_reads(program, f_blk.idx)))
    outs = []
    for tv in t_outs:
        outs.append(parent.create_var(
            name=unique_name.generate(f"{helper.name}.out"),
            shape=tv.shape, dtype=tv.dtype))
    parent.append_op(
        type="cond",
        inputs={"Cond": [pred], "X": reads},
        outputs={"Out": outs},
        attrs={"sub_block_true": t_blk.idx, "sub_block_false": f_blk.idx,
               "x_names": reads,
               "true_outs": [v.name for v in t_outs],
               "false_outs": [v.name for v in f_outs]},
        infer_shape=False)
    if not outs:
        return None
    return outs[0] if len(outs) == 1 else outs


class Switch:
    """fluid.layers.Switch — first-true-case semantics via a chain of cond
    ops. Cases communicate by assigning to pre-existing outer variables."""

    def __init__(self, name=None):
        self.helper = LayerHelper("switch", name=name)
        self.cases = []          # [(pred_var or None, block)]
        self.inside = False

    def __enter__(self):
        self.inside = True
        return self

    @contextlib.contextmanager
    def case(self, condition):
        program = self.helper.main_program
        blk = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        self.cases.append((condition, blk))

    @contextlib.contextmanager
    def default(self):
        program = self.helper.main_program
        blk = program._create_block()
        try:
            yield
        finally:
            program._rollback()
        self.cases.append((None, blk))

    def __exit__(self, exc_type, exc_val, exc_tb):
        self.inside = False
        if exc_type is not None:
            return False
        program = self.helper.main_program
        parent = program.current_block()
        from ..ops.control_flow_ops import block_writes

        preds = [(p, b) for p, b in self.cases if p is not None]
        defaults = [b for p, b in self.cases if p is None]
        writes = []
        for _, b in self.cases:
            for n in block_writes(program, b.idx):
                if parent.has_var(n) and n not in writes:
                    writes.append(n)
        reads = sorted({n for _, b in self.cases
                        for n in _outer_reads(program, b.idx)} |
                       set(writes))

        def empty_block():
            blk = program._create_block()
            program._rollback()
            return blk

        # fold right: else-branch of case i is a wrapper block holding the
        # cond op for cases i+1...
        rest = defaults[0] if defaults else empty_block()
        if not preds:
            # default-only Switch: run it unconditionally
            from . import tensor as T
            always = T.fill_constant([1], "bool", 1.0)
            parent.append_op(
                type="cond",
                inputs={"Cond": [always], "X": list(reads)},
                outputs={"Out": list(writes)},
                attrs={"sub_block_true": rest.idx,
                       "sub_block_false": empty_block().idx,
                       "x_names": list(reads),
                       "true_outs": list(writes),
                       "false_outs": list(writes)},
                infer_shape=False)
            return False
        for i in reversed(range(len(preds))):
            pred, blk = preds[i]
            if i == 0:
                # outermost: emit into the parent block
                target = parent
            else:
                target = program._create_block()
                program._rollback()
            target.append_op(
                type="cond",
                inputs={"Cond": [pred], "X": list(reads)},
                outputs={"Out": list(writes)},
                attrs={"sub_block_true": blk.idx,
                       "sub_block_false": rest.idx,
                       "x_names": list(reads),
                       "true_outs": list(writes),
                       "false_outs": list(writes)},
                infer_shape=False)
            rest = target
        return False


class StaticRNN:
    """fluid.layers.StaticRNN — fixed-length recurrence, lowered to ONE
    lax.scan (reference recurrent_op.cc ran the step block T times through
    a nested executor with step scopes).

    rnn = StaticRNN()
    with rnn.step():
        w = rnn.step_input(x)         # x time-major [T, B, D]
        h_prev = rnn.memory(init=h0)  # [B, H]
        h = layers.fc(concat([w, h_prev]), H, act='tanh')
        rnn.update_memory(h_prev, h)
        rnn.step_output(h)
    out = rnn()                        # [T, B, H]
    """

    def __init__(self, name=None):
        self.helper = LayerHelper("static_rnn", name=name)
        self._block = None
        self._step_inputs = []    # (outer var, inner var)
        self._memories = []       # [pre_var, post_var|None, boot_var]
        self._step_outputs = []   # inner vars
        self._outputs = None
        self._final_states = None

    @contextlib.contextmanager
    def step(self):
        program = self.helper.main_program
        self._parent = program.current_block()
        self._block = program._create_block()
        try:
            yield
        except BaseException:
            program._rollback()
            raise
        else:
            program._rollback()
            self._complete()

    def _in_step(self):
        assert self._block is not None and \
            self.helper.main_program.current_block() is self._block, \
            "call inside `with rnn.step():`"

    def step_input(self, x):
        self._in_step()
        assert x.shape is not None and len(x.shape) >= 1, \
            "step_input needs a time-major var with known rank"
        iv = self._block.create_var(
            name=unique_name.generate(f"{self.helper.name}.step_in"),
            shape=x.shape[1:], dtype=x.dtype)
        self._step_inputs.append((x, iv))
        return iv

    def memory(self, init=None, shape=None, batch_ref=None, init_value=0.0,
               init_batch_dim_idx=0, ref_batch_dim_idx=1):
        self._in_step()
        if init is None:
            assert shape is not None and batch_ref is not None, \
                "memory() needs init= or (shape=, batch_ref=)"
            batch = (batch_ref.shape[0]
                     if batch_ref.block is self._block
                     else batch_ref.shape[ref_batch_dim_idx])
            full = [batch] + [int(s) for s in shape[1:]] \
                if len(shape) > 1 else [batch]
            from . import tensor as T
            # boot var lives in the parent block, before the recurrent op
            program = self.helper.main_program
            cur = program.current_block_idx
            program.current_block_idx = self._parent.idx
            try:
                init = T.fill_constant(full, batch_ref.dtype, init_value)
            finally:
                program.current_block_idx = cur
        pre = self._block.create_var(
            name=unique_name.generate(f"{self.helper.name}.mem"),
            shape=init.shape, dtype=init.dtype)
        self._memories.append([pre, None, init])
        return pre

    def update_memory(self, mem, var):
        self._in_step()
        for rec in self._memories:
            if rec[0] is mem:
                rec[1] = var
                return
        raise ValueError("update_memory: unknown memory var")

    def step_output(self, o):
        self._in_step()
        self._step_outputs.append(o)

    def output(self, *outputs):
        for o in outputs:
            self.step_output(o)

    def _complete(self):
        program = self.helper.main_program
        parent = self._parent
        assert self._step_inputs, "StaticRNN needs at least one step_input"
        assert all(rec[1] is not None for rec in self._memories), \
            "every memory() needs an update_memory()"
        seq_len = self._step_inputs[0][0].shape[0]

        exclude = [iv.name for _, iv in self._step_inputs] + \
                  [rec[0].name for rec in self._memories]
        reads = _outer_reads(program, self._block.idx, exclude)

        outs = []
        for o in self._step_outputs:
            outs.append(parent.create_var(
                name=unique_name.generate(f"{self.helper.name}.out"),
                shape=(seq_len,) + tuple(o.shape or ()), dtype=o.dtype))
        finals = []
        for rec in self._memories:
            finals.append(parent.create_var(
                name=unique_name.generate(f"{self.helper.name}.final"),
                shape=rec[2].shape, dtype=rec[2].dtype))

        parent.append_op(
            type="recurrent",
            inputs={"X": [x for x, _ in self._step_inputs],
                    "Boot": [rec[2] for rec in self._memories],
                    "P": reads},
            outputs={"Out": outs, "FinalStates": finals},
            attrs={"sub_block": self._block.idx,
                   "step_input_vars": [iv.name
                                       for _, iv in self._step_inputs],
                   "memories": [(rec[0].name, rec[1].name)
                                for rec in self._memories],
                   "p_names": reads,
                   "step_outputs": [o.name for o in self._step_outputs],
                   "is_reverse": False},
            infer_shape=False)
        self._outputs = outs
        self._final_states = finals

    def __call__(self):
        assert self._outputs is not None, "finish `with rnn.step():` first"
        return self._outputs[0] if len(self._outputs) == 1 \
            else list(self._outputs)


# ---- LoDTensorArray helpers (reference layers/control_flow.py:1280) ----

def _const_index(block, i, _upto=None):
    """Resolve an array index to a build-time int. Everything inside jit is
    staged (no trace-time concretes), so the index subgraph (fill_constant /
    increment / assign chains) is folded here at build time."""
    if isinstance(i, (int, np.integer)):
        return int(i)
    ops = block.ops if _upto is None else block.ops[:_upto]
    for idx in range(len(ops) - 1, -1, -1):
        op = ops[idx]
        if i.name not in op.output_arg_names:
            continue
        if op.type == "fill_constant":
            return int(op.attrs["value"])
        if op.type == "assign":
            src = block.var(op.input("X")[0])
            return _const_index(block, src, _upto=idx)
        if op.type == "increment":
            return _const_index(block, i, _upto=idx) + \
                int(op.attrs.get("step", 1))
        break
    raise ValueError(
        f"tensor-array index {i.name!r} is not a build-time constant "
        f"(only fill_constant/increment/assign chains fold); inside loops "
        f"use StaticRNN step outputs instead of arrays")


def create_array(dtype="float32"):
    helper = LayerHelper("array")
    var = helper.block.create_var(
        name=unique_name.generate("array"), dtype=dtype,
        type=VarType.LOD_TENSOR_ARRAY)
    return var


def array_write(x, i, array=None):
    helper = LayerHelper("array_write")
    if array is None:
        array = create_array(x.dtype)
    idx = _const_index(helper.block, i)
    helper.append_op(type="write_to_array",
                     inputs={"X": [x]},
                     outputs={},
                     attrs={"array_name": array.name, "index": idx},
                     infer_shape=False)
    return array


def array_read(array, i):
    helper = LayerHelper("array_read")
    idx = _const_index(helper.block, i)
    out = helper.create_variable_for_type_inference(dtype=array.dtype)
    helper.append_op(type="read_from_array",
                     inputs={}, outputs={"Out": [out]},
                     attrs={"array_name": array.name, "index": idx},
                     infer_shape=False)
    return out


def array_length(array):
    helper = LayerHelper("array_length")
    out = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(type="lod_array_length",
                     inputs={}, outputs={"Out": [out]},
                     attrs={"array_name": array.name}, infer_shape=False)
    return out


class DynamicRNN:
    """fluid.layers.DynamicRNN (reference layers/control_flow.py:2768) in
    masked-dense form. The reference sorts sequences by length
    (lod_rank_table), shrinks the live batch every step, and re-scatters
    outputs; on TPU the batch stays static and a per-step validity mask
    freezes finished rows' memories and zeros their outputs — identical
    results, one lax.scan.

        drnn = layers.DynamicRNN()
        with drnn.block():
            x_t = drnn.step_input(x, lengths)   # x [B, T, D] padded
            h = drnn.memory(shape=[H], value=0.0)
            nh = layers.fc(layers.concat([x_t, h], 1), H, act="tanh")
            drnn.update_memory(h, nh)
            drnn.output(nh)
        out = drnn()                             # [B, T, H] (zeros padded)
    """

    def __init__(self, name=None):
        self._rnn = StaticRNN(name=name)
        self._mask_t = None          # [B, 1] float validity, per step
        self._lengths = None
        self._batch = None

    def block(self):
        return self._rnn.step()

    def step_input(self, x, lengths=None, level=0):
        """x: [B, T, ...] padded batch-major + lengths [B] (the
        masked-dense stand-in for the reference's LoD input; `level` is
        accepted for API parity). Additional step inputs share the first
        one's lengths — passing a different lengths var raises."""
        from . import tensor as T
        from .sequence_lod import sequence_mask
        assert x.shape is not None and len(x.shape) >= 2, \
            "step_input needs [B, T, ...] with known rank"
        if self._mask_t is not None and lengths is not None \
                and lengths is not self._lengths:
            raise ValueError(
                "DynamicRNN: every step_input shares the FIRST one's "
                "lengths; a second lengths= would be silently wrong")
        ndim = len(x.shape)
        # the transpose/mask prep must run BEFORE the recurrent op:
        # emit into the parent block (same trick StaticRNN.memory uses
        # for boot vars)
        program = self._rnn.helper.main_program
        cur = program.current_block_idx
        program.current_block_idx = self._rnn._parent.idx
        try:
            # time-major for the scan: [T, B, ...]
            xt = T.transpose(x, [1, 0] + list(range(2, ndim)))
            mask_in = None
            if self._mask_t is None:
                if lengths is None:
                    raise ValueError(
                        "the FIRST DynamicRNN.step_input needs lengths= "
                        "([B] int sequence lengths; masked-dense design)")
                self._lengths = lengths
                self._batch = int(x.shape[0])
                maxlen = int(x.shape[1])
                mask = sequence_mask(lengths, maxlen=maxlen,
                                     dtype="float32")       # [B, T]
                mask_tm = T.transpose(mask, [1, 0])          # [T, B]
                mask_in = T.reshape(mask_tm, [maxlen, -1, 1])
        finally:
            program.current_block_idx = cur
        iv = self._rnn.step_input(xt)
        if mask_in is not None:
            self._mask_t = self._rnn.step_input(mask_in)     # [B, 1]
        return iv

    def static_input(self, x):
        """Whole-sequence (non-stepped) input: visible unchanged every
        step (the recurrent lowering threads outer reads through)."""
        return x

    def memory(self, init=None, shape=None, value=0.0,
               need_reorder=False, dtype="float32"):
        """Reference signature (layers/control_flow.py:3184): `shape`
        EXCLUDES the batch dim; `value`/`dtype` set the boot constant.
        need_reorder is a no-op — masked-dense never sorts the batch."""
        assert self._mask_t is not None, \
            "call step_input() before memory() (the mask drives updates)"
        if init is None:
            assert shape is not None, "memory() needs init= or shape="
            from . import tensor as T
            program = self._rnn.helper.main_program
            cur = program.current_block_idx
            program.current_block_idx = self._rnn._parent.idx
            try:
                init = T.fill_constant(
                    [self._batch] + [int(s) for s in shape], dtype,
                    value)
            finally:
                program.current_block_idx = cur
        return self._rnn.memory(init=init)

    def _mask_like(self, var):
        """[B, 1] mask broadcast-shaped for `var`'s rank."""
        rank = len(var.shape)
        if rank <= 2:
            return self._mask_t
        from . import tensor as T
        return T.reshape(self._mask_t, [-1] + [1] * (rank - 1))

    def update_memory(self, ex_mem, new_mem):
        """Finished rows (mask 0) keep their memory — the reference
        achieves this by shrinking the live batch instead."""
        from . import math as M
        masked = M.elementwise_add(
            ex_mem,
            M.elementwise_mul(M.elementwise_sub(new_mem, ex_mem),
                              self._mask_like(new_mem)))
        self._rnn.update_memory(ex_mem, masked)

    def output(self, *outputs):
        from . import math as M
        for o in outputs:
            self._rnn.step_output(
                M.elementwise_mul(o, self._mask_like(o)))

    def __call__(self):
        from . import tensor as T
        outs = self._rnn()
        if not isinstance(outs, (list, tuple)):
            outs = [outs]
        back = []
        for o in outs:
            nd = len(o.shape)
            back.append(T.transpose(o, [1, 0] + list(range(2, nd))))
        return back[0] if len(back) == 1 else back


def Print(input, first_n=-1, message=None, summarize=20,
          print_tensor_name=True, print_tensor_type=True,
          print_tensor_shape=True, print_tensor_lod=True,
          print_phase="both"):
    """fluid.layers.Print (reference control_flow.py Print /
    print_op.cc): records a print op; the value flows through."""
    helper = LayerHelper("print")
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="print", inputs={"In": [input]},
                     outputs={"Out": [out]},
                     attrs={"message": message or ""},
                     infer_shape=False)
    return out


def case(pred_fn_pairs, default=None, name=None):
    """fluid.layers.case (reference control_flow.py:3204): first-true
    semantics via a chain of conds."""
    assert pred_fn_pairs, "case needs at least one (pred, fn) pair"

    def chain(pairs):
        (pred, fn) = pairs[0]
        rest = pairs[1:]
        if not rest:
            if default is None:
                # reference: with no default the last fn runs
                # unconditionally — trace it ONCE (two cond branches
                # would duplicate any parameters it creates)
                return fn()
            return cond(pred, fn, default)
        return cond(pred, fn, lambda: chain(rest))

    return chain(list(pred_fn_pairs))


def switch_case(branch_index, branch_fns, default=None, name=None):
    """fluid.layers.switch_case (reference control_flow.py:3073):
    dispatch on an integer index."""
    from . import math as M
    from . import tensor as T
    if isinstance(branch_fns, dict):
        items = sorted(branch_fns.items())
    else:
        items = list(enumerate(branch_fns))
    pairs = []
    for idx, fn in items:
        idx_c = T.fill_constant([1], "int64", int(idx))
        pairs.append((M.equal(T.cast(branch_index, "int64"), idx_c), fn))
    if default is None:
        default = items[-1][1]    # reference: last branch is default
    return case(pairs, default=default, name=name)


class IfElse:
    """Old-style fluid.layers.IfElse (reference control_flow.py:1851).
    The reference gathers true/false rows into sub-scopes and merges;
    masked-dense TPU form: both branches compute on the FULL batch and
    outputs merge per-row by the condition mask.

        ie = layers.IfElse(cond_rows)        # cond_rows: [B, 1] bool
        with ie.true_block():
            ie.output(f(x))
        with ie.false_block():
            ie.output(g(x))
        out, = ie()
    """

    def __init__(self, cond, name=None):
        self._cond = cond
        self._outs = {True: [], False: []}
        self._in_branch = None

    @contextlib.contextmanager
    def true_block(self):
        self._in_branch = True
        try:
            yield
        finally:
            self._in_branch = None

    @contextlib.contextmanager
    def false_block(self):
        self._in_branch = False
        try:
            yield
        finally:
            self._in_branch = None

    def input(self, x):
        """The reference slices x to the branch's rows; masked-dense
        keeps the full batch (outputs merge by mask)."""
        assert self._in_branch is not None, \
            "IfElse.input() must be called inside a branch block"
        return x

    def output(self, *outs):
        assert self._in_branch is not None, \
            "IfElse.output() must be called inside a branch block"
        self._outs[self._in_branch].extend(outs)

    def __call__(self):
        from . import tensor as T
        t_outs = self._outs[True]
        f_outs = self._outs[False]
        assert len(t_outs) == len(f_outs), \
            "both IfElse branches must output the same number of vars"
        cond_b = T.cast(self._cond, "bool")
        merged = []
        for tv, fv in zip(t_outs, f_outs):
            merged.append(T.where(cond_b, tv, fv))
        return merged
