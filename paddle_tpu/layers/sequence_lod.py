"""fluid.layers sequence surface (reference:
python/paddle/fluid/layers/sequence_lod.py).

API shape follows the reference, with one masked-dense difference: the
reference reads sequence boundaries off the input tensor's LoD; the TPU
build passes them as an explicit `length` Variable ([B] ints) because XLA
programs are static-shape (see ops/sequence_ops.py). Layers that change
lengths return (out, out_length).
"""
from .layer_helper import LayerHelper


def _seq_op(op_type, inputs, attrs, dtype, helper=None, n_outs=1,
            out_dtypes=None, name=None):
    helper = helper or LayerHelper(op_type, name=name)
    out_dtypes = out_dtypes or [dtype] * n_outs
    outs = [helper.create_variable_for_type_inference(dtype=dt)
            for dt in out_dtypes]
    out_slots = {"Out": [outs[0]]}
    if n_outs > 1:
        out_slots["OutLength"] = [outs[1]]
    helper.append_op(type=op_type, inputs=inputs, outputs=out_slots,
                     attrs=attrs or {})
    return outs[0] if n_outs == 1 else tuple(outs)


def sequence_pool(input, pool_type, length=None, is_test=False, pad_value=0.0):
    """reference sequence_lod.py sequence_pool; pad_value fills the result
    rows of zero-length sequences."""
    return _seq_op("sequence_pool",
                   {"X": [input], "Length": [length]},
                   {"pooltype": pool_type.upper(),
                    "pad_value": float(pad_value)}, input.dtype)


def sequence_first_step(input, length=None):
    return sequence_pool(input, "FIRST", length=length)


def sequence_last_step(input, length=None):
    return sequence_pool(input, "LAST", length=length)


def sequence_softmax(input, length=None, use_cudnn=False, name=None):
    return _seq_op("sequence_softmax",
                   {"X": [input], "Length": [length]}, {}, input.dtype,
                   name=name)


def sequence_reverse(x, length=None, name=None):
    return _seq_op("sequence_reverse",
                   {"X": [x], "Length": [length]}, {}, x.dtype, name=name)


def sequence_expand_as(x, y=None, length=None, maxlen=None, name=None):
    """x row i broadcast over the i-th target length. `length`+`maxlen`
    replace the reference's `y` LoD donor; passing a padded `y` Variable
    infers maxlen from its shape."""
    if maxlen is None:
        if y is None or y.shape is None or len(y.shape) < 2:
            raise ValueError("sequence_expand_as needs maxlen= or a padded "
                             "y with a static time dim")
        maxlen = int(y.shape[1])
    return _seq_op("sequence_expand_as",
                   {"X": [x], "Length": [length]},
                   {"maxlen": int(maxlen)}, x.dtype, name=name)


def sequence_pad(x, pad_value=0.0, maxlen=None, length=None, name=None):
    """Packed [total, ...] -> padded [B, maxlen, ...]
    (reference sequence_pad; pad_value here is a float, not a Variable)."""
    if maxlen is None:
        raise ValueError(
            "sequence_pad needs a static maxlen= on TPU (the reference "
            "derives the padded length from LoD — a dynamic output shape)")
    out = _seq_op("sequence_pad",
                  {"X": [x], "Length": [length]},
                  {"padded_length": int(maxlen),
                   "pad_value": float(pad_value)}, x.dtype, name=name)
    return out, length


def sequence_unpad(x, length=None, name=None):
    return _seq_op("sequence_unpad",
                   {"X": [x], "Length": [length]}, {}, x.dtype, name=name)


def sequence_concat(input, length=None, name=None):
    """input: list of padded [B, Ti, ...]; length: parallel list of [B]
    length Variables. Returns (out, out_length)."""
    if length is None or len(length) != len(input):
        raise ValueError(
            "sequence_concat needs length=[len1, len2, ...] (one [B] int "
            "Variable per input); the reference reads LoD off the inputs, "
            "the TPU build passes lengths explicitly")
    return _seq_op("sequence_concat",
                   {"X": list(input), "Length": list(length)}, {},
                   input[0].dtype, n_outs=2,
                   out_dtypes=[input[0].dtype, "int32"], name=name)


def sequence_slice(input, offset, length, name=None, seq_length=None):
    """Per-row [offset, offset+length) slice; `seq_length` (the input's
    valid-length vector) is optional — the kernel slices by Offset and
    SliceLength alone."""
    ins = {"X": [input], "Offset": [offset], "SliceLength": [length]}
    if seq_length is not None:
        ins["Length"] = [seq_length]
    return _seq_op("sequence_slice", ins, {}, input.dtype, n_outs=2,
                   out_dtypes=[input.dtype, "int32"], name=name)


def sequence_erase(input, tokens, length=None, name=None):
    return _seq_op("sequence_erase",
                   {"X": [input], "Length": [length]},
                   {"tokens": [int(t) for t in tokens]}, input.dtype,
                   n_outs=2, out_dtypes=[input.dtype, "int32"], name=name)


def sequence_enumerate(input, win_size, pad_value=0, length=None, name=None):
    return _seq_op("sequence_enumerate",
                   {"X": [input], "Length": [length]},
                   {"win_size": int(win_size), "pad_value": pad_value},
                   input.dtype, name=name)


def sequence_reshape(input, new_dim, length=None, name=None):
    return _seq_op("sequence_reshape",
                   {"X": [input], "Length": [length]},
                   {"new_dim": int(new_dim)}, input.dtype, n_outs=2,
                   out_dtypes=[input.dtype, "int32"], name=name)


def sequence_mask(x, maxlen=None, dtype="int64", name=None):
    if maxlen is None:
        raise ValueError(
            "sequence_mask needs a static maxlen= on TPU (the reference's "
            "default derives it from max(x) — a dynamic output shape)")
    return _seq_op("sequence_mask", {"X": [x]},
                   {"maxlen": int(maxlen), "out_dtype": dtype}, dtype,
                   name=name)


def sequence_conv(input, num_filters, filter_size=3, filter_stride=1,
                  padding=True, padding_start=None, bias_attr=None,
                  param_attr=None, act=None, length=None, name=None):
    """reference sequence_lod.py sequence_conv: context window (im2col over
    time) + one projection matmul."""
    assert filter_stride == 1, "sequence_conv supports stride 1"
    helper = LayerHelper("sequence_conv", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    D = int(input.shape[-1])
    w = helper.create_parameter(helper.param_attr,
                                shape=[filter_size * D, num_filters],
                                dtype=input.dtype)
    if padding_start is None:
        padding_start = -(filter_size // 2)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="sequence_conv",
        inputs={"X": [input], "Filter": [w], "Length": [length]},
        outputs={"Out": [out]},
        attrs={"contextStart": int(padding_start),
               "contextLength": int(filter_size), "contextStride": 1})
    out = helper.append_bias_op(out, dim_start=2)
    return helper.append_activation(out, act)


def sequence_expand(x, y=None, ref_level=-1, length=None,
                    repeat_times=None, out_rows=None, name=None):
    """Masked-dense sequence_expand (reference sequence_expand_op.h):
    row i of x repeats repeat_times[i] times into a static out_rows
    buffer (padded; OutLength carries per-row lengths)."""
    helper = LayerHelper("sequence_expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out_len = helper.create_variable_for_type_inference(dtype="int32")
    if repeat_times is None or out_rows is None or length is None:
        raise ValueError(
            "masked-dense sequence_expand needs length= ([B] int row "
            "lengths), repeat_times= ([B] int), and out_rows= (static "
            "output capacity); the reference derives these from LoD")
    ins = {"X": [x], "RepeatTimes": [repeat_times]}
    if length is not None:
        ins["Length"] = [length]
    helper.append_op(type="sequence_expand", inputs=ins,
                     outputs={"Out": [out], "OutLength": [out_len]},
                     attrs={"out_rows": int(out_rows)},
                     infer_shape=False)
    return out


def sequence_scatter(input, index, updates, upd_length=None, name=None):
    helper = LayerHelper("sequence_scatter", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    ins = {"X": [input], "Ids": [index], "Updates": [updates]}
    if upd_length is not None:
        ins["UpdLength"] = [upd_length]
    helper.append_op(type="sequence_scatter", inputs=ins,
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def lod_reset(x, y=None, target_lod=None):
    """Masked-dense lod_reset (reference lod_reset_op.h): re-mask x by
    new lengths (y: [B] lengths tensor, or target_lod: static list)."""
    helper = LayerHelper("lod_reset")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    out_len = helper.create_variable_for_type_inference(dtype="int32")
    if y is None:
        if target_lod is None:
            raise ValueError("lod_reset needs y= or target_lod=")
        from . import tensor as T
        import numpy as _np
        y = T.assign(_np.asarray(target_lod, _np.int32))
    helper.append_op(type="lod_reset", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out], "OutLength": [out_len]},
                     infer_shape=False)
    return out


def lod_append(x, level):
    """reference lod_append (layers/nn.py): append a lod level. The
    masked-dense design carries ONE explicit length vector, so
    appending a level == re-masking by it (lod_reset)."""
    return lod_reset(x, y=level if not isinstance(level, (list, tuple))
                     else None,
                     target_lod=level if isinstance(level, (list, tuple))
                     else None)
