"""In-graph learning-rate schedulers.

Capability parity with
/root/reference/python/paddle/fluid/layers/learning_rate_scheduler.py
(noam_decay :63, exponential_decay :113, natural_exp_decay :171,
inverse_time_decay :229, polynomial_decay :288, piecewise_decay :358,
cosine_decay :410, linear_lr_warmup :446). Each scheduler appends
LRSched-role ops that read an auto-incremented persistable step counter and
compute the LR as part of the same compiled step — one XLA module, no host
round-trip per step, and clone(for_test) drops the whole scheduler with the
other non-Forward roles.
"""
import math

from ..framework.core import OpRole, op_role_guard, default_main_program
from ..framework.initializer import ConstantInitializer
from .layer_helper import LayerHelper
from . import tensor
from . import nn as nn_layers
from .math import less_than, elementwise_min, elementwise_max

LR_COUNTER_NAME = "@LR_DECAY_COUNTER@"


def autoincreased_step_counter(counter_name=None, begin=1, step=1):
    """Persistable step counter, +`step` on every executor run of the
    program (reference layers/nn.py autoincreased_step_counter)."""
    helper = LayerHelper("global_step_counter")
    name = counter_name or LR_COUNTER_NAME
    gblock = default_main_program().global_block()
    if name in gblock.vars:
        return gblock.vars[name]
    counter = gblock.create_var(
        name=name, shape=[1], dtype="float32", persistable=True,
        stop_gradient=True)
    ConstantInitializer(float(begin - step))(counter)
    helper.append_op(type="increment", inputs={"X": [counter]},
                     outputs={"Out": [counter]},
                     attrs={"step": float(step)})
    return counter


def _decay_step_counter(begin=0):
    """First executor run observes `begin`, then begin+1, ... (reference
    semantics: counter initialized to begin-1, incremented before use)."""
    with op_role_guard(OpRole.LRSched):
        return autoincreased_step_counter(begin=begin, step=1)


def noam_decay(d_model, warmup_steps, learning_rate=1.0):
    """lr = learning_rate * d_model^-0.5 * min(step^-0.5,
    step * warmup_steps^-1.5) — reference :63."""
    with op_role_guard(OpRole.LRSched):
        step = _decay_step_counter(begin=1)
        a = nn_layers.rsqrt(step)
        b = step * (float(warmup_steps) ** -1.5)
        lr = (float(learning_rate) * float(d_model) ** -0.5) * \
            elementwise_min(a, b)
        return lr


def exponential_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    with op_role_guard(OpRole.LRSched):
        step = _decay_step_counter()
        div = step / float(decay_steps)
        if staircase:
            div = nn_layers.floor(div)
        rate = tensor.fill_constant([1], "float32", float(decay_rate))
        return float(learning_rate) * (rate ** div)


def natural_exp_decay(learning_rate, decay_steps, decay_rate,
                      staircase=False):
    with op_role_guard(OpRole.LRSched):
        step = _decay_step_counter()
        div = step / float(decay_steps)
        if staircase:
            div = nn_layers.floor(div)
        return float(learning_rate) * nn_layers.exp(
            div * (-float(decay_rate)))


def inverse_time_decay(learning_rate, decay_steps, decay_rate,
                       staircase=False):
    with op_role_guard(OpRole.LRSched):
        step = _decay_step_counter()
        div = step / float(decay_steps)
        if staircase:
            div = nn_layers.floor(div)
        denom = div * float(decay_rate) + 1.0
        return float(learning_rate) / denom


def polynomial_decay(learning_rate, decay_steps, end_learning_rate=0.0001,
                     power=1.0, cycle=False):
    with op_role_guard(OpRole.LRSched):
        step = _decay_step_counter()
        if cycle:
            div = nn_layers.ceil(step / float(decay_steps))
            # at step 0, divisor must be 1 not 0
            one = tensor.fill_constant([1], "float32", 1.0)
            div = elementwise_max(div, one)
            decay_var = div * float(decay_steps)
        else:
            decay_var = tensor.fill_constant([1], "float32",
                                             float(decay_steps))
            step = elementwise_min(step, decay_var)
        one = tensor.fill_constant([1], "float32", 1.0)
        frac = nn_layers.pow(one - step / decay_var, float(power))
        return (float(learning_rate) - float(end_learning_rate)) * frac + \
            float(end_learning_rate)


def piecewise_decay(boundaries, values):
    """values[i] while step < boundaries[i]; values[-1] after — :358."""
    assert len(values) == len(boundaries) + 1
    with op_role_guard(OpRole.LRSched):
        step = _decay_step_counter()
        lr = tensor.fill_constant([1], "float32", float(values[-1]))
        for b, v in reversed(list(zip(boundaries, values[:-1]))):
            bvar = tensor.fill_constant([1], "float32", float(b))
            below = tensor.cast(less_than(step, bvar), "float32")
            lr = below * float(v) + (1.0 - below) * lr
        return lr


def cosine_decay(learning_rate, step_each_epoch, epochs):
    """lr/2 * (cos(epoch * pi / epochs) + 1) — :410."""
    with op_role_guard(OpRole.LRSched):
        step = _decay_step_counter()
        epoch = nn_layers.floor(step / float(step_each_epoch))
        return 0.5 * float(learning_rate) * (
            nn_layers.cos(epoch * (math.pi / float(epochs))) + 1.0)


def linear_lr_warmup(learning_rate, warmup_steps, start_lr, end_lr):
    """Linear ramp start_lr -> end_lr over warmup_steps, then the wrapped
    schedule (a float or an LR Variable) — :446."""
    with op_role_guard(OpRole.LRSched):
        step = _decay_step_counter()
        wsteps = tensor.fill_constant([1], "float32", float(warmup_steps))
        in_warmup = tensor.cast(less_than(step, wsteps),
                                "float32")
        warm = float(start_lr) + (float(end_lr) - float(start_lr)) * \
            (step / float(warmup_steps))
        if not isinstance(learning_rate, float):
            base = learning_rate
        else:
            base = tensor.fill_constant([1], "float32",
                                        float(learning_rate))
        return in_warmup * warm + (1.0 - in_warmup) * base
