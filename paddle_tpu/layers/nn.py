"""fluid.layers NN surface (reference: python/paddle/fluid/layers/nn.py —
153 layer functions emitting ops via LayerHelper.append_op)."""
import numpy as np

from ..framework.core import Variable
from ..framework import initializer as init_mod
from .layer_helper import LayerHelper
from ..param_attr import ParamAttr


def fc(input, size, num_flatten_dims=1, param_attr=None, bias_attr=None,
       act=None, name=None):
    """Fully-connected (reference layers/nn.py fc -> mul + elementwise_add)."""
    helper = LayerHelper("fc", param_attr=param_attr, bias_attr=bias_attr,
                         act=act, name=name)
    input_shape = input.shape
    in_features = int(np.prod(input_shape[num_flatten_dims:]))
    w = helper.create_parameter(helper.param_attr,
                                shape=[in_features, size],
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="mul", inputs={"X": [input], "Y": [w]},
        outputs={"Out": [out]},
        attrs={"x_num_col_dims": num_flatten_dims, "y_num_col_dims": 1})
    out = helper.append_bias_op(out, dim_start=num_flatten_dims)
    return helper.append_activation(out, act)


def _emit_embedding(op_type, input, size, is_sparse, is_distributed,
                    padding_idx, param_attr, dtype, name=None):
    """Shared body of layers.embedding (lookup_table, v1 trailing-[.,1]
    ids) and fluid.embedding (lookup_table_v2, any-rank ids). A
    negative padding_idx normalizes to size[0]+padding_idx (reference
    input.py / layers/nn.py both do this); -1 stays the kernel's
    no-padding sentinel only when the user passed None."""
    helper = LayerHelper("embedding", param_attr=param_attr, name=name)
    if padding_idx is None:
        padding_idx = -1
    elif padding_idx < 0:
        padding_idx = int(size[0]) + int(padding_idx)
    w = helper.create_parameter(helper.param_attr, shape=list(size),
                                dtype=dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type=op_type, inputs={"W": [w], "Ids": [input]},
        outputs={"Out": [out]},
        attrs={"padding_idx": padding_idx,
               "is_sparse": is_sparse, "is_distributed": is_distributed})
    return out


def embedding(input, size, is_sparse=False, is_distributed=False,
              padding_idx=None, param_attr=None, dtype="float32", name=None):
    return _emit_embedding("lookup_table", input, size, is_sparse,
                           is_distributed, padding_idx, param_attr,
                           dtype, name=name)


def distributed_embedding(input, size, table_name, endpoint, name=None):
    """Sparse embedding served from a host parameter-server table
    (reference distributed_lookup_table_op.cc + parameter_prefetch.cc;
    the table lives on the pserver, only touched rows cross the host
    boundary, and sparse grads are applied server-side on push). `size` is
    (vocab, dim); the table must be hosted via ParameterServer.
    host_sparse_table(table_name, ...)."""
    from .tensor import fill_constant
    helper = LayerHelper("distributed_embedding", name=name)
    stub = fill_constant([1], "float32", 0.0)
    stub.stop_gradient = False      # gives autodiff a path to the push
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(
        type="distributed_lookup_table",
        inputs={"Ids": [input], "W": [stub]},
        outputs={"Out": [out]},
        attrs={"table_name": table_name, "endpoint": endpoint,
               "emb_dim": int(size[1])},
        infer_shape=False)
    out.shape = tuple(input.shape or ()) + (int(size[1]),)
    out.dtype = "float32"
    return out


def conv2d(input, num_filters, filter_size, stride=1, padding=0, dilation=1,
           groups=1, param_attr=None, bias_attr=None, act=None,
           use_cudnn=True, name=None, data_format="NCHW"):
    helper = LayerHelper("conv2d", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    filter_size = _pair(filter_size)
    stride = _pair(stride)
    dilation = _pair(dilation)
    padding, algo = _conv_padding(padding)
    filter_shape = [num_filters, num_channels // groups] + list(filter_size)
    std = (2.0 / (filter_size[0] * filter_size[1] * num_channels)) ** 0.5
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=input.dtype,
        default_initializer=init_mod.NormalInitializer(0.0, std))
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="conv2d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": list(stride), "paddings": list(padding),
               "dilations": list(dilation), "groups": groups,
               "padding_algorithm": algo, "data_format": data_format})
    out = _append_channel_bias(helper, out)
    return helper.append_activation(out, act)


def conv2d_transpose(input, num_filters, output_size=None, filter_size=None,
                     stride=1, padding=0, dilation=1, groups=1,
                     param_attr=None, bias_attr=None, act=None, name=None):
    helper = LayerHelper("conv2d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    stride = _pair(stride)
    dilation = _pair(dilation)
    padding, algo = _conv_padding(padding)
    if filter_size is None:
        raise ValueError("filter_size required (output_size-only inference "
                         "not supported)")
    filter_size = _pair(filter_size)
    filter_shape = [num_channels, num_filters // groups] + list(filter_size)
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="conv2d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": list(stride), "paddings": list(padding),
               "dilations": list(dilation), "groups": groups,
               "padding_algorithm": algo})
    out = _append_channel_bias(helper, out)
    return helper.append_activation(out, act)


def pool2d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, use_cudnn=True,
           ceil_mode=False, exclusive=True, name=None):
    helper = LayerHelper("pool2d", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": list(_pair(pool_size)),
               "strides": list(_pair(pool_stride)),
               "paddings": list(_pair(pool_padding)),
               "global_pooling": global_pooling, "ceil_mode": ceil_mode,
               "exclusive": exclusive})
    return out


def adaptive_pool2d(input, pool_size, pool_type="max", name=None):
    helper = LayerHelper("adaptive_pool2d", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="pool2d", inputs={"X": [input]}, outputs={"Out": [out]},
        attrs={"pooling_type": pool_type, "ksize": list(_pair(pool_size)),
               "adaptive": True})
    return out


def batch_norm(input, act=None, is_test=False, momentum=0.9, epsilon=1e-5,
               param_attr=None, bias_attr=None, data_layout="NCHW",
               name=None, moving_mean_name=None, moving_variance_name=None,
               use_global_stats=False, sync=False):
    helper = LayerHelper("batch_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    caxis = 1 if data_layout == "NCHW" else len(input.shape) - 1
    c = input.shape[caxis]
    dtype = input.dtype if input.dtype != "float16" else "float32"
    scale = helper.create_parameter(
        helper.param_attr, shape=[c], dtype=dtype,
        default_initializer=init_mod.ConstantInitializer(1.0))
    bias = helper.create_parameter(helper.bias_attr, shape=[c], dtype=dtype,
                                   is_bias=True)
    mean = helper.create_global_variable(
        shape=[c], dtype=dtype, name=moving_mean_name,
        initializer=init_mod.ConstantInitializer(0.0))
    variance = helper.create_global_variable(
        shape=[c], dtype=dtype, name=moving_variance_name,
        initializer=init_mod.ConstantInitializer(1.0))
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    saved_m = helper.create_variable_for_type_inference(dtype=dtype,
                                                        stop_gradient=True)
    saved_v = helper.create_variable_for_type_inference(dtype=dtype,
                                                        stop_gradient=True)
    helper.append_op(
        type="sync_batch_norm" if sync else "batch_norm",
        inputs={"X": [input], "Scale": [scale], "Bias": [bias],
                "Mean": [mean], "Variance": [variance]},
        outputs={"Y": [out], "MeanOut": [mean], "VarianceOut": [variance],
                 "SavedMean": [saved_m], "SavedVariance": [saved_v]},
        attrs={"momentum": momentum, "epsilon": epsilon, "is_test": is_test,
               "data_layout": data_layout,
               "use_global_stats": use_global_stats})
    return helper.append_activation(out, act)


def layer_norm(input, scale=True, shift=True, begin_norm_axis=1,
               epsilon=1e-5, param_attr=None, bias_attr=None, act=None,
               name=None):
    helper = LayerHelper("layer_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    norm_shape = [int(np.prod(input.shape[begin_norm_axis:]))]
    inputs = {"X": [input]}
    if scale:
        s = helper.create_parameter(
            helper.param_attr, shape=norm_shape, dtype=input.dtype,
            default_initializer=init_mod.ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if shift:
        b = helper.create_parameter(helper.bias_attr, shape=norm_shape,
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    mean = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                     stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                    stop_gradient=True)
    helper.append_op(
        type="layer_norm", inputs=inputs,
        outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
        attrs={"begin_norm_axis": begin_norm_axis, "epsilon": epsilon})
    return helper.append_activation(out, act)


def group_norm(input, groups, epsilon=1e-5, param_attr=None, bias_attr=None,
               act=None, name=None):
    helper = LayerHelper("group_norm", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    c = input.shape[1]
    inputs = {"X": [input]}
    if helper.param_attr is not False:
        s = helper.create_parameter(
            helper.param_attr, shape=[c], dtype=input.dtype,
            default_initializer=init_mod.ConstantInitializer(1.0))
        inputs["Scale"] = [s]
    if helper.bias_attr is not False:
        b = helper.create_parameter(helper.bias_attr, shape=[c],
                                    dtype=input.dtype, is_bias=True)
        inputs["Bias"] = [b]
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    mean = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                     stop_gradient=True)
    var = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                    stop_gradient=True)
    helper.append_op(type="group_norm", inputs=inputs,
                     outputs={"Y": [out], "Mean": [mean], "Variance": [var]},
                     attrs={"groups": groups, "epsilon": epsilon})
    return helper.append_activation(out, act)


def dropout(x, dropout_prob, is_test=False, seed=None, name=None,
            dropout_implementation="downgrade_in_infer"):
    helper = LayerHelper("dropout", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    mask = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                     stop_gradient=True)
    helper.append_op(
        type="dropout", inputs={"X": [x]},
        outputs={"Out": [out], "Mask": [mask]},
        attrs={"dropout_prob": dropout_prob, "is_test": is_test,
               "seed": seed or 0,
               "dropout_implementation": dropout_implementation})
    return out


def softmax(input, axis=-1, use_cudnn=False, name=None):
    helper = LayerHelper("softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="softmax", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def log_softmax(input, axis=-1, name=None):
    helper = LayerHelper("log_softmax", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="log_softmax", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def relu(x, name=None):
    return _unary("relu", x, name)


def sigmoid(x, name=None):
    return _unary("sigmoid", x, name)


def tanh(x, name=None):
    return _unary("tanh", x, name)


def gelu(x, approximate=False, name=None):
    return _unary("gelu", x, name, {"approximate": approximate})


def leaky_relu(x, alpha=0.02, name=None):
    return _unary("leaky_relu", x, name, {"alpha": alpha})


def relu6(x, threshold=6.0, name=None):
    return _unary("relu6", x, name, {"threshold": threshold})


def elu(x, alpha=1.0, name=None):
    return _unary("elu", x, name, {"alpha": alpha})


def swish(x, beta=1.0, name=None):
    return _unary("swish", x, name, {"beta": beta})


def hard_swish(x, name=None):
    return _unary("hard_swish", x, name)


def hard_sigmoid(x, slope=0.2, offset=0.5, name=None):
    return _unary("hard_sigmoid", x, name, {"slope": slope,
                                            "offset": offset})


def exp(x, name=None):
    return _unary("exp", x, name)


def log(x, name=None):
    return _unary("log", x, name)


def sqrt(x, name=None):
    return _unary("sqrt", x, name)


def rsqrt(x, name=None):
    return _unary("rsqrt", x, name)


def square(x, name=None):
    return _unary("square", x, name)


def abs(x, name=None):
    return _unary("abs", x, name)


def floor(x, name=None):
    return _unary("floor", x, name)


def ceil(x, name=None):
    return _unary("ceil", x, name)


def round(x, name=None):
    return _unary("round", x, name)


def sign(x, name=None):
    return _unary("sign", x, name)


def sin(x, name=None):
    return _unary("sin", x, name)


def cos(x, name=None):
    return _unary("cos", x, name)


def erf(x, name=None):
    return _unary("erf", x, name)


def softplus(x, name=None):
    return _unary("softplus", x, name)


def softsign(x, name=None):
    return _unary("softsign", x, name)


def logsigmoid(x, name=None):
    return _unary("logsigmoid", x, name)


def pow(x, factor=1.0, name=None):
    return _unary("pow", x, name, {"factor": factor})


def prelu(x, mode="all", param_attr=None, name=None):
    helper = LayerHelper("prelu", param_attr=param_attr, name=name)
    if mode == "all":
        alpha_shape = [1]
    elif mode == "channel":
        alpha_shape = [x.shape[1]]
    else:
        alpha_shape = list(x.shape[1:])
    alpha = helper.create_parameter(
        helper.param_attr, shape=alpha_shape, dtype=x.dtype,
        default_initializer=init_mod.ConstantInitializer(0.25))
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="prelu", inputs={"X": [x], "Alpha": [alpha]},
                     outputs={"Out": [out]}, attrs={"mode": mode})
    return out


def matmul(x, y, transpose_x=False, transpose_y=False, alpha=1.0, name=None):
    helper = LayerHelper("matmul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="matmul", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
        attrs={"transpose_X": transpose_x, "transpose_Y": transpose_y,
               "alpha": float(alpha)})
    return out


def mul(x, y, x_num_col_dims=1, y_num_col_dims=1, name=None):
    helper = LayerHelper("mul", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="mul", inputs={"X": [x], "Y": [y]}, outputs={"Out": [out]},
        attrs={"x_num_col_dims": x_num_col_dims,
               "y_num_col_dims": y_num_col_dims})
    return out


def bmm(x, y, name=None):
    helper = LayerHelper("bmm", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="bmm", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def topk(input, k, name=None):
    helper = LayerHelper("top_k", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    idx = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    helper.append_op(type="top_k", inputs={"X": [input]},
                     outputs={"Out": [out], "Indices": [idx]},
                     attrs={"k": k})
    return out, idx


def accuracy(input, label, k=1, correct=None, total=None):
    helper = LayerHelper("accuracy")
    _, idx = topk(input, k)
    acc = helper.create_variable_for_type_inference(dtype="float32",
                                                    stop_gradient=True)
    correct = correct or helper.create_variable_for_type_inference(
        dtype="int32", stop_gradient=True)
    total = total or helper.create_variable_for_type_inference(
        dtype="int32", stop_gradient=True)
    helper.append_op(
        type="accuracy",
        inputs={"Out": [input], "Indices": [idx], "Label": [label]},
        outputs={"Accuracy": [acc], "Correct": [correct], "Total": [total]})
    return acc


def auc(input, label, curve="ROC", num_thresholds=4095, topk=1,
        slide_steps=1):
    helper = LayerHelper("auc")
    stat_pos = helper.create_global_variable(
        shape=[num_thresholds + 1], dtype="int64",
        initializer=init_mod.ConstantInitializer(0))
    stat_neg = helper.create_global_variable(
        shape=[num_thresholds + 1], dtype="int64",
        initializer=init_mod.ConstantInitializer(0))
    auc_out = helper.create_variable_for_type_inference(dtype="float32",
                                                        stop_gradient=True)
    helper.append_op(
        type="auc",
        inputs={"Predict": [input], "Label": [label],
                "StatPos": [stat_pos], "StatNeg": [stat_neg]},
        outputs={"AUC": [auc_out], "StatPosOut": [stat_pos],
                 "StatNegOut": [stat_neg]},
        attrs={"num_thresholds": num_thresholds, "curve": curve})
    return auc_out, auc_out, [stat_pos, stat_neg]


def l2_normalize(x, axis, epsilon=1e-12, name=None):
    helper = LayerHelper("l2_normalize", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    norm = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                     stop_gradient=True)
    helper.append_op(type="norm", inputs={"X": [x]},
                     outputs={"Out": [out], "Norm": [norm]},
                     attrs={"axis": axis, "epsilon": epsilon})
    return out


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    helper = LayerHelper("label_smooth", name=name)
    out = helper.create_variable_for_type_inference(dtype=label.dtype)
    inputs = {"X": [label]}
    if prior_dist is not None:
        inputs["PriorDist"] = [prior_dist]
    helper.append_op(type="label_smooth", inputs=inputs,
                     outputs={"Out": [out]}, attrs={"epsilon": epsilon})
    return out


def clip(x, min, max, name=None):
    return _unary("clip", x, name, {"min": min, "max": max})


def clip_by_norm(x, max_norm, name=None):
    return _unary("clip_by_norm", x, name, {"max_norm": max_norm})


def image_resize(input, out_shape, resample="BILINEAR", name=None):
    helper = LayerHelper("image_resize", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    op = "bilinear_interp" if resample.upper() == "BILINEAR" \
        else "nearest_interp"
    helper.append_op(type=op, inputs={"X": [input]}, outputs={"Out": [out]},
                     attrs={"out_h": out_shape[0], "out_w": out_shape[1]})
    return out


def pad(x, paddings, pad_value=0.0, name=None):
    return _unary("pad", x, name, {"paddings": list(paddings),
                                   "pad_value": pad_value})


def pad2d(x, paddings, mode="constant", pad_value=0.0, name=None):
    return _unary("pad2d", x, name, {"paddings": list(paddings),
                                     "mode": mode, "pad_value": pad_value})


def unsqueeze(input, axes, name=None):
    helper = LayerHelper("unsqueeze", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    xshape = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                       stop_gradient=True)
    helper.append_op(type="unsqueeze2", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": list(axes)})
    return out


def squeeze(input, axes=None, name=None):
    helper = LayerHelper("squeeze", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    xshape = helper.create_variable_for_type_inference(dtype=input.dtype,
                                                       stop_gradient=True)
    helper.append_op(type="squeeze2", inputs={"X": [input]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axes": list(axes or [])})
    return out


def flatten(x, axis=1, name=None):
    helper = LayerHelper("flatten", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    xshape = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                       stop_gradient=True)
    helper.append_op(type="flatten2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": axis})
    return out


# ---- helpers ----

def _unary(op_type, x, name=None, attrs=None):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type=op_type, inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs=attrs or {})
    return out


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v)


def _conv_padding(padding):
    if isinstance(padding, str):
        return [0, 0], padding.upper()
    return list(_pair(padding)), "EXPLICIT"


def _append_channel_bias(helper, out):
    bias_attr = helper.bias_attr
    if bias_attr is False:
        return out
    bias = helper.create_parameter(bias_attr, shape=[out.shape[1]],
                                   dtype=out.dtype, is_bias=True)
    tmp = helper.create_variable_for_type_inference(dtype=out.dtype)
    helper.append_op(type="elementwise_add",
                     inputs={"X": [out], "Y": [bias]},
                     outputs={"Out": [tmp]}, attrs={"axis": 1})
    return tmp


def switch_moe(input, num_experts, d_hidden, capacity_factor=1.25,
               param_attr=None, name=None):
    """Switch-style Mixture-of-Experts FFN block (north-star extra; no
    reference counterpart — see ops/moe_ops.py). Expert weights are
    stacked [E, ...] and sharded over the "ep" mesh axis; returns
    (out, aux_loss) where aux_loss is the load-balance term to add to the
    training loss."""
    helper = LayerHelper("switch_moe", param_attr=param_attr, name=name)
    d = int(input.shape[-1])
    E, H = int(num_experts), int(d_hidden)
    gate_w = helper.create_parameter(helper.param_attr, shape=[d, E],
                                     dtype=input.dtype)
    std1 = (2.0 / (d + H)) ** 0.5
    w1 = helper.create_parameter(
        helper.param_attr, shape=[E, d, H], dtype=input.dtype,
        default_initializer=init_mod.NormalInitializer(0.0, std1),
        dist_attr=("ep",))
    b1 = helper.create_parameter(helper.param_attr, shape=[E, H],
                                 dtype=input.dtype, is_bias=True,
                                 dist_attr=("ep",))
    w2 = helper.create_parameter(
        helper.param_attr, shape=[E, H, d], dtype=input.dtype,
        default_initializer=init_mod.NormalInitializer(0.0, std1),
        dist_attr=("ep",))
    b2 = helper.create_parameter(helper.param_attr, shape=[E, d],
                                 dtype=input.dtype, is_bias=True,
                                 dist_attr=("ep",))
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    aux = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="switch_moe",
        inputs={"X": [input], "GateW": [gate_w], "W1": [w1], "B1": [b1],
                "W2": [w2], "B2": [b2]},
        outputs={"Out": [out], "AuxLoss": [aux]},
        attrs={"capacity_factor": float(capacity_factor)},
        infer_shape=False)
    out.shape = tuple(input.shape or ())
    out.dtype = input.dtype
    aux.shape = ()
    aux.dtype = input.dtype
    return out, aux


def lstm_unit(x_t, hidden_t_prev, cell_t_prev, forget_bias=0.0,
              param_attr=None, bias_attr=None, name=None):
    """One LSTM step for use inside StaticRNN (reference layers/nn.py
    lstm_unit -> operators/lstm_unit_op.h; here the x/h projections and
    gate math are one fused MXU-friendly op). Returns (hidden_t, cell_t)."""
    helper = LayerHelper("lstm_unit", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    D = int(x_t.shape[-1])
    H = int(hidden_t_prev.shape[-1])
    w = helper.create_parameter(helper.param_attr, shape=[D + H, 4 * H],
                                dtype=x_t.dtype)
    b = helper.create_parameter(helper.bias_attr, shape=[4 * H],
                                dtype=x_t.dtype, is_bias=True)
    h = helper.create_variable_for_type_inference(dtype=x_t.dtype)
    c = helper.create_variable_for_type_inference(dtype=x_t.dtype)
    helper.append_op(
        type="lstm_cell_fused",
        inputs={"X": [x_t], "HPrev": [hidden_t_prev],
                "CPrev": [cell_t_prev], "W": [w], "B": [b]},
        outputs={"H": [h], "C": [c]},
        attrs={"forget_bias": float(forget_bias)})
    return h, c


def gru_unit(input, hidden, size=None, param_attr=None, bias_attr=None,
             name=None):
    """One GRU step for use inside StaticRNN (reference layers/nn.py
    gru_unit -> operators/gru_unit_op.h, fused). Returns hidden_t."""
    helper = LayerHelper("gru_unit", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    D = int(input.shape[-1])
    H = int(hidden.shape[-1])

    def _suffixed(attr, suffix):
        # gru_unit owns TWO weight/bias pairs; a user-fixed attr name must
        # not collide between them
        from ..param_attr import ParamAttr
        attr = ParamAttr._to_attr(attr)
        if attr and attr.name:
            import copy as _copy
            attr = _copy.copy(attr)
            attr.name = attr.name + suffix
        return attr

    wg = helper.create_parameter(_suffixed(helper.param_attr, ".gate"),
                                 shape=[D + H, 2 * H], dtype=input.dtype)
    bg = helper.create_parameter(_suffixed(helper.bias_attr, ".gate"),
                                 shape=[2 * H], dtype=input.dtype,
                                 is_bias=True)
    wc = helper.create_parameter(_suffixed(helper.param_attr, ".cand"),
                                 shape=[D + H, H], dtype=input.dtype)
    bc = helper.create_parameter(_suffixed(helper.bias_attr, ".cand"),
                                 shape=[H], dtype=input.dtype,
                                 is_bias=True)
    h = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="gru_cell_fused",
        inputs={"X": [input], "HPrev": [hidden], "WGate": [wg],
                "BGate": [bg], "WCand": [wc], "BCand": [bc]},
        outputs={"H": [h]}, attrs={})
    return h


def ring_attention(q, k, v, attn_bias=None, scale=0.0, mechanism="ring",
                   causal=False, name=None):
    """Sequence-parallel attention for long contexts (north-star extra;
    the reference's sequences are single-device — SURVEY §5.7). q/k/v:
    [B, n_head, S, d_head] with S sharded over the "sp" mesh axis.
    mechanism="ring" rotates K/V blocks around the sp ring with online
    softmax (no full K/V on any chip); "ulysses" all-to-alls the shard
    dim from sequence to heads. `causal` masks from block/iota indices
    (the RING never materializes an [S, S] mask and skips fully-dead
    blocks — a FLOP/energy saving, not a latency one, since the ring
    synchronizes every hop; ulysses scores are dense per device either
    way). Exact math either way; identical to plain attention without
    an sp axis."""
    assert mechanism in ("ring", "ulysses")
    helper = LayerHelper(f"{mechanism}_attention", name=name)
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    ins = {"Q": [q], "K": [k], "V": [v]}
    if attn_bias is not None:
        ins["Bias"] = [attn_bias]
    helper.append_op(
        type=f"{mechanism}_attention", inputs=ins,
        outputs={"Out": [out]},
        attrs={"scale": float(scale), "causal": bool(causal)},
        infer_shape=False)
    out.shape = tuple(q.shape or ())
    out.dtype = q.dtype
    return out


def flash_attention(q, k, v, attn_bias=None, scale=0.0, causal=False,
                    impl=None, block_q=None, block_k=None, name=None):
    """Fused blockwise attention (Pallas kernel on TPU; exact XLA composite
    elsewhere). q/k/v: [B, n_head, S, d_head]; attn_bias: optional additive
    key mask [B, 1, 1, S] (constant — no gradient flows to it). Never
    materializes the [S, S] score matrix in HBM on the Pallas path."""
    helper = LayerHelper("flash_attention", name=name)
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    ins = {"Q": [q], "K": [k], "V": [v]}
    if attn_bias is not None:
        ins["Bias"] = [attn_bias]
    helper.append_op(
        type="flash_attention", inputs=ins,
        outputs={"Out": [out]},
        attrs={"scale": float(scale), "causal": bool(causal),
               "impl": impl or "",
               "block_q": int(block_q or 0), "block_k": int(block_k or 0)},
        infer_shape=False)
    out.shape = tuple(q.shape or ())
    out.dtype = q.dtype
    return out


def kv_cache_write(cache, kv, pos, name=None):
    """Append ``kv`` [B, H, S, D] into the preallocated KV ``cache``
    [B, H, max_len, D] at each row's own ``pos`` [B] int32 (vmapped
    position-indexed ``dynamic_update_slice``). Returns the updated
    cache; the incremental-decoding append (see models/gpt.py)."""
    helper = LayerHelper("kv_cache_write", name=name)
    out = helper.create_variable_for_type_inference(dtype=cache.dtype)
    helper.append_op(
        type="kv_cache_write",
        inputs={"Cache": [cache], "KV": [kv], "Pos": [pos]},
        outputs={"Out": [out]}, attrs={}, infer_shape=False)
    out.shape = tuple(cache.shape or ())
    out.dtype = cache.dtype
    return out


def kv_cached_attention(q, k_cache, v_cache, pos, scale=0.0, name=None):
    """Causal attention of fresh queries ``q`` [B, H, S, D] over KV
    caches [B, H, max_len, D], masked by per-row position counters
    ``pos`` [B] int32 (key slot j visible to query i iff
    j <= pos[b] + i). Rows at different positions share one executable —
    the decode-batch fast path of autoregressive generation."""
    helper = LayerHelper("kv_cached_attention", name=name)
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    helper.append_op(
        type="kv_cached_attention",
        inputs={"Q": [q], "K": [k_cache], "V": [v_cache], "Pos": [pos]},
        outputs={"Out": [out]}, attrs={"scale": float(scale)},
        infer_shape=False)
    out.shape = tuple(q.shape or ())
    out.dtype = q.dtype
    return out


def paged_kv_cache_write(cache, kv, tables, pos, scale=None, limit=None,
                         name=None):
    """Append S new ``kv`` vectors [B, H, S, D] into the block-paged
    pool ``cache`` [num_blocks, H, block_size, D] at each row's own
    ``pos`` [B] int32, routed through the per-row block ``tables``
    [B, nblk] int32. Optional ``limit`` [B] int32 marks how many of the
    S vectors are real per row (chunked prefill's ragged tail; the rest
    route to the trash block). For an int8 pool pass its ``scale``
    array [num_blocks, H, block_size]; the op quantizes and returns
    ``(updated_pool, updated_scale)``, else just the updated pool."""
    helper = LayerHelper("paged_kv_cache_write", name=name)
    out = helper.create_variable_for_type_inference(dtype=cache.dtype)
    ins = {"Cache": [cache], "KV": [kv], "Tables": [tables],
           "Pos": [pos]}
    if limit is not None:
        ins["Limit"] = [limit]
    outs = {"Out": [out]}
    out_scale = None
    if scale is not None:
        ins["Scale"] = [scale]
        out_scale = helper.create_variable_for_type_inference(
            dtype=scale.dtype)
        outs["OutScale"] = [out_scale]
    helper.append_op(
        type="paged_kv_cache_write", inputs=ins, outputs=outs,
        attrs={}, infer_shape=False)
    out.shape = tuple(cache.shape or ())
    out.dtype = cache.dtype
    if out_scale is not None:
        out_scale.shape = tuple(scale.shape or ())
        out_scale.dtype = scale.dtype
        return out, out_scale
    return out


def paged_attention(q, k_cache, v_cache, tables, pos, k_scale=None,
                    v_scale=None, scale=0.0, impl=None, name=None):
    """Decode attention of S queries per row (``q`` [B, H, S, D] —
    S=1 decode, S>1 chunked prefill) over the block-paged KV pool
    ([num_blocks, H, block_size, D], int8 pools with their
    [num_blocks, H, block_size] scales), gathered through the per-row
    block ``tables`` and masked by per-row ``pos`` counters — the paged
    analogue of :func:`kv_cached_attention`. Fused Pallas gather+attend
    on TPU for S=1; ``jnp.take`` reference elsewhere and for S>1."""
    helper = LayerHelper("paged_attention", name=name)
    out = helper.create_variable_for_type_inference(dtype=q.dtype)
    ins = {"Q": [q], "K": [k_cache], "V": [v_cache],
           "Tables": [tables], "Pos": [pos]}
    if k_scale is not None:
        ins["KScale"] = [k_scale]
        ins["VScale"] = [v_scale]
    helper.append_op(
        type="paged_attention", inputs=ins, outputs={"Out": [out]},
        attrs={"scale": float(scale), "impl": impl or ""},
        infer_shape=False)
    out.shape = tuple(q.shape or ())
    out.dtype = q.dtype
    return out


def row_gather(x, index, name=None):
    """Out[b] = x[b, index[b]] — per-row gather along axis 1 (e.g. the
    last real token's position of a right-padded batch)."""
    helper = LayerHelper("row_gather", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(
        type="row_gather", inputs={"X": [x], "Index": [index]},
        outputs={"Out": [out]}, attrs={}, infer_shape=False)
    out.shape = tuple(x.shape[:1] or ()) + tuple(x.shape[2:] or ())
    out.dtype = x.dtype
    return out


def sample_tokens(logits, temperature, top_k=None, seed=0, name=None):
    """Next-token selection over ``logits`` [B, V] with per-row sampling
    config: ``temperature`` [B] float32 (<= 0 -> greedy argmax), optional
    ``top_k`` [B] int32 (> 0 -> restrict sampling to the k highest
    logits). Draws from the framework RNG stream — fixed executor seed
    gives bitwise-reproducible sequences. Returns sampled ids [B] int32."""
    helper = LayerHelper("sample_tokens", name=name)
    out = helper.create_variable_for_type_inference(dtype="int32")
    ins = {"X": [logits], "Temperature": [temperature]}
    if top_k is not None:
        ins["TopK"] = [top_k]
    helper.append_op(
        type="sample_tokens", inputs=ins, outputs={"Out": [out]},
        attrs={"seed": int(seed)}, infer_shape=False)
    out.shape = tuple(logits.shape[:1] or ())
    out.dtype = "int32"
    return out


def spec_accept(logits, draft, temperature, num_draft, top_k=None,
                seed=0, name=None):
    """Speculative-decoding acceptance over a verified span: ``logits``
    [B, S, V] (the verify step's per-position distributions), ``draft``
    [B, K] int32 proposals (K = S-1), per-row ``temperature`` [B] /
    optional ``top_k`` [B] sampling config (matching
    :func:`sample_tokens` exactly), ``num_draft`` [B] int32 real draft
    counts. Returns ``(tokens [B, S] int32, accepted [B] int32)`` —
    row b emits ``tokens[b, :accepted[b] + 1]``. Greedy rows are
    bitwise-identical to sequential decode; stochastic rows preserve
    the sampler's output distribution via rejection sampling."""
    helper = LayerHelper("spec_accept", name=name)
    out = helper.create_variable_for_type_inference(dtype="int32")
    acc = helper.create_variable_for_type_inference(dtype="int32")
    ins = {"X": [logits], "Draft": [draft],
           "Temperature": [temperature], "NumDraft": [num_draft]}
    if top_k is not None:
        ins["TopK"] = [top_k]
    helper.append_op(
        type="spec_accept", inputs=ins,
        outputs={"Out": [out], "Accepted": [acc]},
        attrs={"seed": int(seed)}, infer_shape=False)
    out.shape = tuple(logits.shape[:2] or ())
    out.dtype = "int32"
    acc.shape = tuple(logits.shape[:1] or ())
    acc.dtype = "int32"
    return out, acc


def beam_search(pre_ids, pre_scores, scores, beam_size, end_id=0,
                name=None):
    """One beam expansion step (reference layers/rnn.py beam_search ->
    beam_search_op). Returns (selected_ids [B, beam] int32,
    selected_scores [B, beam], parent_idx [B, beam] int32)."""
    helper = LayerHelper("beam_search", name=name)
    B = pre_ids.shape[0] if pre_ids.shape else -1
    outs = []
    for suffix, dtype in (("ids", "int32"), ("scores", "float32"),
                          ("parents", "int32")):
        outs.append(helper.block.create_var(
            name=f"{helper.name}.{suffix}", dtype=dtype,
            shape=(B, beam_size)))
    helper.append_op(
        type="beam_search",
        inputs={"pre_ids": [pre_ids], "pre_scores": [pre_scores],
                "scores": [scores]},
        outputs={"selected_ids": [outs[0]],
                 "selected_scores": [outs[1]],
                 "parent_idx": [outs[2]]},
        attrs={"beam_size": int(beam_size), "end_id": int(end_id)},
        infer_shape=False)
    return tuple(outs)


def gather_tree(ids, parents, name=None):
    """Back-trace beam parents into sequences (reference
    layers gather_tree -> gather_tree_op). ids/parents [T, B, beam]."""
    helper = LayerHelper("gather_tree", name=name)
    out = helper.block.create_var(name=f"{helper.name}.out",
                                  dtype="int32",
                                  shape=tuple(ids.shape or ()))
    helper.append_op(type="gather_tree",
                     inputs={"Ids": [ids], "Parents": [parents]},
                     outputs={"Out": [out]}, attrs={}, infer_shape=False)
    return out


def py_func(func, x, out, backward_func=None,
            skip_vars_in_backward_input=None, name=None):
    """Run user Python inside the program (reference layers/nn.py:12799
    py_func + py_func_op.cc). `func(*numpy_inputs)` fills `out` (pre-made
    Variable(s) carrying the static shape/dtype the TPU program needs);
    `backward_func(*inputs, *outputs, *out_grads)` returns per-input
    grads (None allowed). Both must be PURE — the compiled program may
    re-invoke them (jax.pure_callback semantics).
    `skip_vars_in_backward_input` is accepted for API parity; the
    backward here always receives the full (inputs, outputs, grads)
    tuple and may ignore entries."""
    from ..framework.core import Variable
    from ..ops.extra_ops import register_py_func
    helper = LayerHelper("py_func", name=name)
    xs = [x] if isinstance(x, Variable) else list(x)
    outs = [out] if isinstance(out, Variable) else list(out)
    for v in outs:
        if v.shape is None or any(s is None or s < 0 for s in v.shape):
            raise ValueError(
                f"py_func out {v.name!r} needs a fully static shape "
                f"(got {v.shape}) — XLA compiles the callback's result "
                f"buffer ahead of time")
    attrs = {"func_id": register_py_func(func),
             "out_shapes": [list(v.shape) for v in outs],
             "out_dtypes": [str(v.dtype) for v in outs]}
    if backward_func is not None:
        attrs["bwd_func_id"] = register_py_func(backward_func)
    helper.append_op(type="py_func", inputs={"X": xs},
                     outputs={"Out": outs}, attrs=attrs,
                     infer_shape=False)
    return out


# ---- round-4 layer-surface wrappers over existing op lowerings ----

def scatter_nd_add(ref, index, updates, name=None):
    helper = LayerHelper("scatter_nd_add", name=name)
    out = helper.create_variable_for_type_inference(dtype=ref.dtype)
    helper.append_op(type="scatter_nd_add",
                     inputs={"X": [ref], "Index": [index],
                             "Updates": [updates]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def strided_slice(input, axes, starts, ends, strides, name=None):
    helper = LayerHelper("strided_slice", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="strided_slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends),
                            "strides": list(strides)},
                     infer_shape=False)
    return out


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    helper = LayerHelper("unfold", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)

    def _pair2(v):
        return [v, v] if isinstance(v, int) else list(v)
    helper.append_op(type="unfold", inputs={"X": [x]},
                     outputs={"Y": [out]},
                     attrs={"kernel_sizes": _pair2(kernel_sizes),
                            "strides": _pair2(strides),
                            "paddings": (list(paddings)
                                         if isinstance(paddings,
                                                       (list, tuple))
                                         else [paddings] * 4),
                            "dilations": _pair2(dilations)},
                     infer_shape=False)
    return out


def pixel_shuffle(x, upscale_factor, name=None):
    return _unary("pixel_shuffle", x, name=name,
                  attrs={"upscale_factor": int(upscale_factor)})


def shuffle_channel(x, group, name=None):
    return _unary("shuffle_channel", x, name=name,
                  attrs={"group": int(group)})


def temporal_shift(x, seg_num, shift_ratio=0.25, name=None):
    return _unary("temporal_shift", x, name=name,
                  attrs={"seg_num": int(seg_num),
                         "shift_ratio": float(shift_ratio)})


def pad_constant_like(x, y, pad_value=0.0, name=None):
    helper = LayerHelper("pad_constant_like", name=name)
    out = helper.create_variable_for_type_inference(dtype=y.dtype)
    helper.append_op(type="pad_constant_like",
                     inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]},
                     attrs={"pad_value": float(pad_value)},
                     infer_shape=False)
    return out


def _crop_impl(op_type, x, shape, offsets, name):
    from ..framework.core import Variable
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    ins = {"X": [x]}
    attrs = {}
    if isinstance(shape, Variable):
        raise ValueError(
            f"{op_type}: a tensor `shape` is a dynamic output shape — "
            f"pass a static list on TPU (offsets MAY be a tensor)")
    if shape is not None:
        attrs["shape"] = list(shape)
    if isinstance(offsets, Variable):
        ins["Offsets"] = [offsets]      # runtime offsets: dynamic_slice
    elif offsets is not None:
        attrs["offsets"] = list(offsets)
    helper.append_op(type=op_type, inputs=ins, outputs={"Out": [out]},
                     attrs=attrs, infer_shape=False)
    return out


def crop(x, shape=None, offsets=None, name=None):
    return _crop_impl("crop", x, shape, offsets, name)


def crop_tensor(x, shape=None, offsets=None, name=None):
    return _crop_impl("crop_tensor", x, shape, offsets, name)


def expand_as(x, target_tensor, name=None):
    helper = LayerHelper("expand_as", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="expand_as",
                     inputs={"X": [x], "target_tensor": [target_tensor]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def gaussian_random(shape, mean=0.0, std=1.0, seed=0, dtype="float32",
                    name=None):
    helper = LayerHelper("gaussian_random", name=name)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(type="gaussian_random", inputs={},
                     outputs={"Out": [out]},
                     attrs={"shape": list(shape), "mean": float(mean),
                            "std": float(std), "seed": int(seed),
                            "dtype": dtype},
                     infer_shape=False)
    return out


def maxout(x, groups, name=None, axis=1):
    return _unary("maxout", x, name=name,
                  attrs={"groups": int(groups), "axis": int(axis)})


def space_to_depth(x, blocksize, name=None):
    return _unary("space_to_depth", x, name=name,
                  attrs={"blocksize": int(blocksize)})


def affine_channel(x, scale=None, bias=None, data_layout="NCHW",
                   name=None, act=None):
    helper = LayerHelper("affine_channel", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    ins = {"X": [x]}
    if scale is not None:
        ins["Scale"] = [scale]
    if bias is not None:
        ins["Bias"] = [bias]
    helper.append_op(type="affine_channel", inputs=ins,
                     outputs={"Out": [out]},
                     attrs={"data_layout": data_layout},
                     infer_shape=False)
    return helper.append_activation(out, act)


def unique_with_counts(x, dtype="int32"):
    helper = LayerHelper("unique_with_counts")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    index = helper.create_variable_for_type_inference(dtype=dtype)
    count = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(type="unique_with_counts", inputs={"X": [x]},
                     outputs={"Out": [out], "Index": [index],
                              "Count": [count]},
                     attrs={"dtype": dtype}, infer_shape=False)
    return out, index, count


def fsp_matrix(x, y, name=None):
    """FSP matrix for distillation (reference layers/nn.py fsp_matrix /
    fsp_op.h)."""
    helper = LayerHelper("fsp", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="fsp", inputs={"X": [x], "Y": [y]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def continuous_value_model(input, cvm, use_cvm=True, name=None):
    """CVM op for CTR (reference layers/nn.py continuous_value_model /
    cvm_op.h)."""
    helper = LayerHelper("cvm", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="cvm",
                     inputs={"X": [input], "CVM": [cvm]},
                     outputs={"Y": [out]},
                     attrs={"use_cvm": bool(use_cvm)},
                     infer_shape=False)
    return out


# ---- round-4 batch 2: remaining fluid.layers surface ----

def brelu(x, t_min=0.0, t_max=24.0, name=None):
    return _unary("brelu", x, name=name,
                  attrs={"t_min": float(t_min), "t_max": float(t_max)})


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772,
         name=None):
    return _unary("selu", x, name=name,
                  attrs={"scale": float(scale), "alpha": float(alpha)})


def stanh(x, scale_a=0.67, scale_b=1.7159, name=None):
    return _unary("stanh", x, name=name,
                  attrs={"scale_a": float(scale_a),
                         "scale_b": float(scale_b)})


def _triple(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v, v)


def conv3d(input, num_filters, filter_size, stride=1, padding=0,
           dilation=1, groups=1, param_attr=None, bias_attr=None,
           act=None, use_cudnn=True, name=None, data_format="NCDHW"):
    helper = LayerHelper("conv3d", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    filter_size = _triple(filter_size)
    stride = _triple(stride)
    dilation = _triple(dilation)
    if isinstance(padding, str):
        paddings, algo = [0, 0, 0], padding.upper()
    else:
        paddings, algo = list(_triple(padding)), "EXPLICIT"
    filter_shape = [num_filters, num_channels // groups] + \
        list(filter_size)
    fan = filter_size[0] * filter_size[1] * filter_size[2] * num_channels
    w = helper.create_parameter(
        helper.param_attr, shape=filter_shape, dtype=input.dtype,
        default_initializer=init_mod.NormalInitializer(
            0.0, (2.0 / fan) ** 0.5))
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="conv3d",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": list(stride), "paddings": paddings,
               "dilations": list(dilation), "groups": groups,
               "padding_algorithm": algo, "data_format": data_format})
    out = _append_channel_bias(helper, out)
    return helper.append_activation(out, act)


def conv3d_transpose(input, num_filters, output_size=None,
                     filter_size=None, stride=1, padding=0, dilation=1,
                     groups=1, param_attr=None, bias_attr=None, act=None,
                     name=None):
    helper = LayerHelper("conv3d_transpose", param_attr=param_attr,
                         bias_attr=bias_attr, act=act, name=name)
    num_channels = input.shape[1]
    if filter_size is None:
        raise ValueError("filter_size required")
    filter_size = _triple(filter_size)
    stride = _triple(stride)
    dilation = _triple(dilation)
    if isinstance(padding, str):
        paddings, algo = [0, 0, 0], padding.upper()
    else:
        paddings, algo = list(_triple(padding)), "EXPLICIT"
    filter_shape = [num_channels, num_filters // groups] + \
        list(filter_size)
    w = helper.create_parameter(helper.param_attr, shape=filter_shape,
                                dtype=input.dtype)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="conv3d_transpose",
        inputs={"Input": [input], "Filter": [w]},
        outputs={"Output": [out]},
        attrs={"strides": list(stride), "paddings": paddings,
               "dilations": list(dilation), "groups": groups,
               "padding_algorithm": algo})
    out = _append_channel_bias(helper, out)
    return helper.append_activation(out, act)


def lrn(input, n=5, k=1.0, alpha=1e-4, beta=0.75, name=None,
        data_format="NCHW"):
    return _unary("lrn", input, name=name,
                  attrs={"n": int(n), "k": float(k),
                         "alpha": float(alpha), "beta": float(beta)})


def instance_norm(input, epsilon=1e-5, param_attr=None, bias_attr=None,
                  name=None):
    helper = LayerHelper("instance_norm", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    C = input.shape[1]
    ins = {"X": [input]}
    if param_attr is not False:
        scale = helper.create_parameter(
            helper.param_attr, shape=[C], dtype=input.dtype,
            default_initializer=init_mod.ConstantInitializer(1.0))
        ins["Scale"] = [scale]
    if bias_attr is not False:
        bias = helper.create_parameter(
            helper.bias_attr, shape=[C], dtype=input.dtype,
            default_initializer=init_mod.ConstantInitializer(0.0))
        ins["Bias"] = [bias]
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="instance_norm", inputs=ins,
                     outputs={"Y": [out]},
                     attrs={"epsilon": float(epsilon)},
                     infer_shape=False)
    return out


def data_norm(input, act=None, epsilon=1e-5, param_attr=None,
              data_layout="NCHW", in_place=False, name=None,
              moving_mean_name=None, moving_variance_name=None,
              do_model_average_for_mean_and_var=True, slot_dim=-1,
              sync_stats=False, summary_decay_rate=0.9999999,
              enable_scale_and_shift=False):
    """Streaming feature normalization (reference layers/nn.py data_norm
    / data_norm_op.h): batch-count/sum/square-sum accumulators are
    persistable parameters updated functionally every step."""
    helper = LayerHelper("data_norm", param_attr=param_attr, name=name)
    D = input.shape[-1]
    dtype = input.dtype
    # reference contract (layers/nn.py:3245): param_attr keys
    # batch_size/batch_sum/batch_square hold the accumulators' INITIAL
    # VALUES
    pa = param_attr if isinstance(param_attr, dict) else {}
    size = helper.create_parameter(
        ParamAttr(), shape=[D], dtype=dtype,
        default_initializer=init_mod.ConstantInitializer(
            float(pa.get("batch_size", 1e4))))
    bsum = helper.create_parameter(
        ParamAttr(), shape=[D], dtype=dtype,
        default_initializer=init_mod.ConstantInitializer(
            float(pa.get("batch_sum", 0.0))))
    sqsum = helper.create_parameter(
        ParamAttr(), shape=[D], dtype=dtype,
        default_initializer=init_mod.ConstantInitializer(
            float(pa.get("batch_square", 1e4))))
    out = helper.create_variable_for_type_inference(dtype=dtype)
    means = helper.create_variable_for_type_inference(dtype=dtype)
    scales = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(
        type="data_norm",
        inputs={"X": [input], "BatchSize": [size], "BatchSum": [bsum],
                "BatchSquareSum": [sqsum]},
        outputs={"Y": [out], "Means": [means], "Scales": [scales],
                 "BatchSizeOut": [size], "BatchSumOut": [bsum],
                 "BatchSquareSumOut": [sqsum]},
        attrs={"epsilon": float(epsilon)},
        infer_shape=False)
    return helper.append_activation(out, act)


def spectral_norm(weight, dim=0, power_iters=1, eps=1e-12, name=None):
    helper = LayerHelper("spectral_norm", name=name)
    w_shape = list(weight.shape)
    h = w_shape[dim]
    wdim = 1
    for i, s in enumerate(w_shape):
        if i != dim:
            wdim *= s
    u = helper.create_parameter(
        ParamAttr(name=None, trainable=False), shape=[h],
        dtype=weight.dtype,
        default_initializer=init_mod.NormalInitializer(0.0, 1.0))
    v = helper.create_parameter(
        ParamAttr(name=None, trainable=False), shape=[wdim],
        dtype=weight.dtype,
        default_initializer=init_mod.NormalInitializer(0.0, 1.0))
    out = helper.create_variable_for_type_inference(dtype=weight.dtype)
    helper.append_op(
        type="spectral_norm",
        inputs={"Weight": [weight], "U": [u], "V": [v]},
        outputs={"Out": [out], "UOut": [u], "VOut": [v]},
        attrs={"dim": int(dim), "power_iters": int(power_iters),
               "eps": float(eps)},
        infer_shape=False)
    return out


def multiplex(inputs, index, name=None):
    helper = LayerHelper("multiplex", name=name)
    out = helper.create_variable_for_type_inference(
        dtype=inputs[0].dtype)
    helper.append_op(type="multiplex",
                     inputs={"X": list(inputs), "Ids": [index]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def reverse(x, axis, name=None):
    if isinstance(axis, int):
        axis = [axis]
    return _unary("reverse", x, name=name, attrs={"axis": list(axis)})


def is_empty(x, cond=None, name=None):
    helper = LayerHelper("is_empty", name=name)
    out = cond or helper.create_variable_for_type_inference(dtype="bool")
    helper.append_op(type="is_empty", inputs={"X": [x]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def chunk_eval(input, label, chunk_scheme, num_chunk_types,
               excluded_chunk_types=None, seq_length=None):
    helper = LayerHelper("chunk_eval")
    outs = [helper.create_variable_for_type_inference(dtype=d)
            for d in ("float32", "float32", "float32", "int64", "int64",
                      "int64")]
    ins = {"Inference": [input], "Label": [label]}
    if seq_length is not None:
        ins["SeqLength"] = [seq_length]
    helper.append_op(
        type="chunk_eval", inputs=ins,
        outputs={"Precision": [outs[0]], "Recall": [outs[1]],
                 "F1-Score": [outs[2]], "NumInferChunks": [outs[3]],
                 "NumLabelChunks": [outs[4]],
                 "NumCorrectChunks": [outs[5]]},
        attrs={"num_chunk_types": int(num_chunk_types),
               "chunk_scheme": chunk_scheme,
               "excluded_chunk_types": list(excluded_chunk_types or [])},
        infer_shape=False)
    return tuple(outs)


def roi_align(input, rois, pooled_height=1, pooled_width=1,
              spatial_scale=1.0, sampling_ratio=-1, name=None,
              rois_num=None):
    helper = LayerHelper("roi_align", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    ins = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    helper.append_op(type="roi_align", inputs=ins,
                     outputs={"Out": [out]},
                     attrs={"pooled_height": int(pooled_height),
                            "pooled_width": int(pooled_width),
                            "spatial_scale": float(spatial_scale),
                            "sampling_ratio": int(sampling_ratio)},
                     infer_shape=False)
    return out


def roi_pool(input, rois, pooled_height=1, pooled_width=1,
             spatial_scale=1.0, rois_num=None, name=None):
    helper = LayerHelper("roi_pool", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    argmax = helper.create_variable_for_type_inference(dtype="int32")
    ins = {"X": [input], "ROIs": [rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    helper.append_op(type="roi_pool", inputs=ins,
                     outputs={"Out": [out], "Argmax": [argmax]},
                     attrs={"pooled_height": int(pooled_height),
                            "pooled_width": int(pooled_width),
                            "spatial_scale": float(spatial_scale)},
                     infer_shape=False)
    return out


def resize_bilinear(input, out_shape=None, scale=None, name=None,
                    actual_shape=None, align_corners=True, align_mode=1,
                    data_format="NCHW"):
    if out_shape is None and scale is not None:
        out_shape = [int(input.shape[2] * scale),
                     int(input.shape[3] * scale)]
    return image_resize(input, out_shape, resample="BILINEAR", name=name)


def resize_nearest(input, out_shape=None, scale=None, name=None,
                   actual_shape=None, align_corners=True,
                   data_format="NCHW"):
    if out_shape is None and scale is not None:
        out_shape = [int(input.shape[2] * scale),
                     int(input.shape[3] * scale)]
    return image_resize(input, out_shape, resample="NEAREST", name=name)


def resize_trilinear(input, out_shape=None, scale=None, name=None,
                     actual_shape=None, align_corners=True, align_mode=1,
                     data_format="NCDHW"):
    if out_shape is None and scale is not None:
        out_shape = [int(s * scale) for s in input.shape[2:]]
    d, h, w = [int(v) for v in out_shape]
    helper = LayerHelper("trilinear_interp", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="trilinear_interp", inputs={"X": [input]},
                     outputs={"Out": [out]},
                     attrs={"out_d": d, "out_h": h, "out_w": w,
                            "align_corners": bool(align_corners),
                            "align_mode": int(align_mode)},
                     infer_shape=False)
    return out


def image_resize_short(input, out_short_len, resample="BILINEAR"):
    """Resize so the SHORT side equals out_short_len, keeping aspect
    (reference layers/nn.py image_resize_short)."""
    h, w = int(input.shape[2]), int(input.shape[3])
    short, long_ = (h, w) if h < w else (w, h)
    ratio = out_short_len / float(short)
    out_shape = ([out_short_len, int(w * ratio)] if h < w
                 else [int(h * ratio), out_short_len])
    return image_resize(input, out_shape, resample=resample)


def warpctc(input, label, blank=0, norm_by_times=False,
            input_length=None, label_length=None):
    """CTC loss (reference warpctc_op.h). Masked-dense layout: Logits
    [B, T, V] batch-major padded + input_length/label_length (the
    reference's LoD form is time-major packed)."""
    helper = LayerHelper("warpctc")
    loss = helper.create_variable_for_type_inference(dtype=input.dtype)
    ins = {"Logits": [input], "Label": [label]}
    if input_length is not None:
        ins["LogitsLength"] = [input_length]
    if label_length is not None:
        ins["LabelLength"] = [label_length]
    helper.append_op(type="warpctc", inputs=ins,
                     outputs={"Loss": [loss]},
                     attrs={"blank": int(blank),
                            "norm_by_times": bool(norm_by_times)},
                     infer_shape=False)
    return loss


def nce(input, label, num_total_classes, sample_weight=None,
        param_attr=None, bias_attr=None, num_neg_samples=None,
        name=None, sampler="uniform", custom_dist=None, seed=0,
        is_sparse=False):
    helper = LayerHelper("nce", param_attr=param_attr,
                         bias_attr=bias_attr, name=name)
    D = input.shape[-1]
    w = helper.create_parameter(helper.param_attr,
                                shape=[num_total_classes, D],
                                dtype=input.dtype)
    ins = {"Input": [input], "Label": [label], "Weight": [w]}
    if bias_attr is not False:
        b = helper.create_parameter(
            helper.bias_attr, shape=[num_total_classes],
            dtype=input.dtype,
            default_initializer=init_mod.ConstantInitializer(0.0))
        ins["Bias"] = [b]
    cost = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="nce", inputs=ins,
                     outputs={"Cost": [cost]},
                     attrs={"num_total_classes": int(num_total_classes),
                            "num_neg_samples": int(num_neg_samples or 10),
                            "seed": int(seed)},
                     infer_shape=False)
    return cost


def similarity_focus(input, axis, indexes, name=None):
    return _unary("similarity_focus", input, name=name,
                  attrs={"axis": int(axis),
                         "indexes": [int(i) for i in indexes]})


def filter_by_instag(ins, ins_tag, filter_tag, is_lod=True,
                     out_val_if_empty=0):
    helper = LayerHelper("filter_by_instag")
    out = helper.create_variable_for_type_inference(dtype=ins.dtype)
    loss_weight = helper.create_variable_for_type_inference(
        dtype="float32")
    index_map = helper.create_variable_for_type_inference(dtype="int32")
    out_count = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(
        type="filter_by_instag",
        inputs={"Ins": [ins], "Ins_tag": [ins_tag],
                "Filter_tag": [filter_tag]},
        outputs={"Out": [out], "LossWeight": [loss_weight],
                 "IndexMap": [index_map], "OutCount": [out_count]},
        attrs={"is_lod": bool(is_lod)},
        infer_shape=False)
    return out, loss_weight, index_map


def uniform_random(shape, dtype="float32", min=-1.0, max=1.0, seed=0,
                   name=None):
    helper = LayerHelper("uniform_random", name=name)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(type="uniform_random", inputs={},
                     outputs={"Out": [out]},
                     attrs={"shape": list(shape), "min": float(min),
                            "max": float(max), "seed": int(seed),
                            "dtype": dtype},
                     infer_shape=False)
    return out


def _random_batch_size_like(op_type, input, shape, input_dim_idx,
                            output_dim_idx, dtype, extra):
    helper = LayerHelper(op_type)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(type=op_type, inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs=dict(extra, shape=list(shape),
                                input_dim_idx=int(input_dim_idx),
                                output_dim_idx=int(output_dim_idx),
                                dtype=dtype),
                     infer_shape=False)
    return out


def uniform_random_batch_size_like(input, shape, dtype="float32",
                                   input_dim_idx=0, output_dim_idx=0,
                                   min=-1.0, max=1.0, seed=0):
    return _random_batch_size_like(
        "uniform_random_batch_size_like", input, shape, input_dim_idx,
        output_dim_idx, dtype,
        {"min": float(min), "max": float(max), "seed": int(seed)})


def gaussian_random_batch_size_like(input, shape, input_dim_idx=0,
                                    output_dim_idx=0, mean=0.0, std=1.0,
                                    seed=0, dtype="float32"):
    return _random_batch_size_like(
        "gaussian_random_batch_size_like", input, shape, input_dim_idx,
        output_dim_idx, dtype,
        {"mean": float(mean), "std": float(std), "seed": int(seed)})


def inplace_abn(input, act=None, is_test=False, momentum=0.9,
                epsilon=1e-5, param_attr=None, bias_attr=None,
                data_layout="NCHW", name=None, **kwargs):
    """Inplace activated batch norm (reference inplace_abn_op.cc) — on
    TPU 'inplace' is XLA's buffer planning; this is batch_norm + act."""
    return batch_norm(input, act=act, is_test=is_test, momentum=momentum,
                      epsilon=epsilon, param_attr=param_attr,
                      bias_attr=bias_attr, data_layout=data_layout,
                      name=name)


def deformable_roi_pooling(input, rois, trans, no_trans=False,
                           spatial_scale=1.0, group_size=(1, 1),
                           pooled_height=1, pooled_width=1,
                           part_size=None, sample_per_part=1,
                           trans_std=0.1, position_sensitive=False,
                           name=None):
    helper = LayerHelper("deformable_psroi_pooling", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    top_count = helper.create_variable_for_type_inference(dtype="int32")
    part = part_size or (pooled_height, pooled_width)
    helper.append_op(
        type="deformable_psroi_pooling",
        inputs={"Input": [input], "ROIs": [rois], "Trans": [trans]},
        outputs={"Output": [out], "TopCount": [top_count]},
        attrs={"no_trans": bool(no_trans),
               "spatial_scale": float(spatial_scale),
               "output_dim": int(input.shape[1]) // (
                   int(group_size[0]) * int(group_size[1]))
               if position_sensitive else int(input.shape[1]),
               "group_size": [int(g) for g in group_size],
               "pooled_height": int(pooled_height),
               "pooled_width": int(pooled_width),
               "part_size": [int(p) for p in part],
               "sample_per_part": int(sample_per_part),
               "trans_std": float(trans_std)},
        infer_shape=False)
    return out


def unique(x, dtype="int32"):
    """TPU divergence (PARITY.md): `unique` has a data-dependent output
    shape; use unique_with_counts (padded + count)."""
    raise NotImplementedError(
        "unique has a data-dependent output shape on TPU; use "
        "layers.unique_with_counts (first-occurrence order, padded "
        "with a Count output) instead")


# ---- layer_function_generator parity (reference
# python/paddle/fluid/layers/layer_function_generator.py) ----

def templatedoc(op_type=None):
    """Doc-templating decorator (reference layer_function_generator.py
    templatedoc): docs are authored directly here, so it is identity."""
    def deco(fn):
        return fn
    return deco


def autodoc(comment=""):
    def deco(fn):
        fn.__doc__ = comment + (fn.__doc__ or "")
        return fn
    return deco


def deprecated(since=None, instead=None, reason=""):
    """Mark a layer deprecated (reference annotations): warns on call."""
    def deco(fn):
        import functools
        import warnings

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            warnings.warn(
                f"{fn.__name__} is deprecated"
                + (f" since {since}" if since else "")
                + (f"; use {instead}" if instead else "")
                + (f" ({reason})" if reason else ""),
                DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def generate_layer_fn(op_type):
    """Build a layer fn for a registered op type (reference
    layer_function_generator.py generate_layer_fn): inputs by slot
    kwargs, single Out."""
    def fn(*args, **kwargs):
        helper = LayerHelper(op_type, name=kwargs.pop("name", None))
        ins = {}
        first = None
        for slot in list(kwargs):
            v = kwargs[slot]
            if isinstance(v, Variable):
                ins[slot] = [kwargs.pop(slot)]
                first = first or v
            elif isinstance(v, (list, tuple)) and v and \
                    all(isinstance(e, Variable) for e in v):
                ins[slot] = list(kwargs.pop(slot))
                first = first or v[0]
        if len(args) == 1:
            ins["X"] = [args[0]]
        elif len(args) == 2:
            ins["X"], ins["Y"] = [args[0]], [args[1]]
        elif len(args) > 2:
            ins["X"] = list(args)       # variadic ops (sum/concat style)
        if args:
            first = first or args[0]
        out = helper.create_variable_for_type_inference(
            dtype=first.dtype if first is not None else "float32")
        helper.append_op(type=op_type, inputs=ins,
                         outputs={"Out": [out]}, attrs=dict(kwargs),
                         infer_shape=False)
        return out
    fn.__name__ = op_type
    return fn


def generate_activation_fn(op_type):
    def fn(x, name=None):
        return _unary(op_type, x, name=name)
    fn.__name__ = op_type
    return fn


# ---- reader plumbing (by-design divergence, PARITY.md: the host
# DataLoader owns async feeding; these names guide users there) ----

def py_reader(capacity, shapes, dtypes, lod_levels=None, name=None,
              use_double_buffer=True):
    raise NotImplementedError(
        "py_reader's feed-queue ops are replaced by the host DataLoader "
        "on TPU (by-design, PARITY.md): use "
        "fluid.io.PyReader(feed_list=..., capacity=...) or "
        "fluid.io.DataLoader.from_generator(...) — same capability, "
        "host-side double buffering")


def create_py_reader_by_data(capacity, feed_list, name=None,
                             use_double_buffer=True):
    from ..dataio.reader import PyReader as _PyReader
    return _PyReader(feed_list=feed_list, capacity=capacity,
                     use_double_buffer=use_double_buffer)


def double_buffer(reader, place=None, name=None):
    """Identity: the DataLoader double-buffers host-side (by design)."""
    return reader


def read_file(reader):
    raise NotImplementedError(
        "read_file consumes py_reader's queue vars; on TPU feed through "
        "the DataLoader's batch dicts instead (PARITY.md reader-ops row)")


def load(out, file_path, load_as_fp16=None):
    """reference layers/io.py load / load_op.cc: fill `out` from a saved
    .npy file at EXECUTION time (host callback). When `out` carries no
    static shape (create_tensor), the shape/dtype come from the file
    HEADER at build time (mmap — no data read)."""
    import numpy as _np
    helper = LayerHelper("load")
    shape, dtype = out.shape, out.dtype
    if shape is None or any(s is None or s < 0 for s in shape):
        probe = _np.load(file_path, mmap_mode="r", allow_pickle=False)
        shape = probe.shape
        dtype = str(probe.dtype)
        out.shape = tuple(shape)
        out.dtype = dtype
    if load_as_fp16:
        dtype = "float16"
        out.dtype = dtype

    def _read():
        arr = _np.load(file_path, allow_pickle=False)
        return arr.astype(_np.float16) if load_as_fp16 else arr

    from ..ops.extra_ops import register_py_func
    helper.append_op(
        type="py_func", inputs={"X": []}, outputs={"Out": [out]},
        attrs={"func_id": register_py_func(_read),
               "out_shapes": [list(shape)],
               "out_dtypes": [str(dtype)]},
        infer_shape=False)
    return out


def sampled_softmax_with_cross_entropy(logits, label, num_samples,
                                       num_true=1, remove_accidental_hits=True,
                                       use_customized_samples=False,
                                       customized_samples=None,
                                       customized_probabilities=None,
                                       seed=0):
    """reference layers/nn.py sampled_softmax_with_cross_entropy /
    sample_logits_op.cc (uniform sampler). Unsupported parity args
    raise rather than silently change semantics."""
    if use_customized_samples or customized_samples is not None:
        raise NotImplementedError(
            "sampled_softmax_with_cross_entropy: customized samplers "
            "are not supported on TPU (uniform sampler only); pass "
            "use_customized_samples=False")
    if num_true != 1:
        raise NotImplementedError(
            "sampled_softmax_with_cross_entropy: num_true must be 1")
    helper = LayerHelper("sampled_softmax_with_cross_entropy")
    loss = helper.create_variable_for_type_inference(dtype=logits.dtype)
    helper.append_op(
        type="sampled_softmax_with_cross_entropy",
        inputs={"Logits": [logits], "Label": [label]},
        outputs={"Loss": [loss]},
        attrs={"num_samples": int(num_samples), "seed": int(seed),
               "remove_accidental_hits": bool(remove_accidental_hits)},
        infer_shape=False)
    return loss


def tensor_array_to_tensor(input, axis=1, name=None, use_stack=False):
    """reference tensor_array_to_tensor (layers/tensor.py): concat or
    stack a tensor array's entries."""
    helper = LayerHelper("tensor_array_to_tensor", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    out_index = helper.create_variable_for_type_inference(dtype="int32")
    helper.append_op(type="tensor_array_to_tensor", inputs={},
                     outputs={"Out": [out], "OutIndex": [out_index]},
                     attrs={"array_name": input.name, "axis": int(axis),
                            "use_stack": bool(use_stack)},
                     infer_shape=False)
    return out, out_index
