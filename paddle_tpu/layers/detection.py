"""fluid.layers.detection parity (reference
python/paddle/fluid/layers/detection.py). Wrappers emit the padded-form
detection ops (see ops/detection_ops.py, ops/detection_rcnn_ops.py for
the static-shape contracts: variable-length results come back padded
with a count output instead of LoD)."""
import numpy as np

from .layer_helper import LayerHelper
from .more import _multi, _single


__all__ = [
    "prior_box", "density_prior_box", "anchor_generator", "iou_similarity",
    "box_coder", "yolo_box", "multiclass_nms", "locality_aware_nms",
    "detection_output", "detection_map", "target_assign", "ssd_loss",
    "mine_hard_examples", "multi_box_head", "rpn_target_assign",
    "retinanet_target_assign", "retinanet_detection_output",
    "generate_proposals", "generate_proposal_labels",
    "generate_mask_labels", "distribute_fpn_proposals",
    "collect_fpn_proposals", "box_decoder_and_assign",
    "roi_perspective_transform",
]


def iou_similarity(x, y, box_normalized=True, name=None):
    return _single("iou_similarity", {"X": [x], "Y": [y]},
                   {"box_normalized": box_normalized}, x.dtype, name=name)


def prior_box(input, image, min_sizes, max_sizes=None, aspect_ratios=(1.,),
              variance=(0.1, 0.1, 0.2, 0.2), flip=False, clip=False,
              steps=(0.0, 0.0), offset=0.5,
              min_max_aspect_ratios_order=False, name=None):
    attrs = {"min_sizes": list(min_sizes),
             "max_sizes": list(max_sizes or []),
             "aspect_ratios": list(aspect_ratios),
             "variances": list(variance), "flip": flip, "clip": clip,
             "step_w": steps[0], "step_h": steps[1], "offset": offset,
             "min_max_aspect_ratios_order": min_max_aspect_ratios_order}
    return _multi("prior_box", {"Input": [input], "Image": [image]}, attrs,
                  [("Boxes", input.dtype), ("Variances", input.dtype)],
                  name=name)


def density_prior_box(input, image, densities, fixed_sizes, fixed_ratios,
                      variance=(0.1, 0.1, 0.2, 0.2), clip=False,
                      steps=(0.0, 0.0), offset=0.5, flatten_to_2d=False,
                      name=None):
    attrs = {"densities": list(densities),
             "fixed_sizes": list(fixed_sizes),
             "fixed_ratios": list(fixed_ratios),
             "variances": list(variance), "clip": clip,
             "step_w": steps[0], "step_h": steps[1], "offset": offset,
             "flatten_to_2d": flatten_to_2d}
    return _multi("density_prior_box", {"Input": [input], "Image": [image]},
                  attrs, [("Boxes", input.dtype),
                          ("Variances", input.dtype)], name=name)


def anchor_generator(input, anchor_sizes, aspect_ratios,
                     variance=(0.1, 0.1, 0.2, 0.2), stride=None,
                     offset=0.5, name=None):
    attrs = {"anchor_sizes": list(anchor_sizes),
             "aspect_ratios": list(aspect_ratios),
             "variances": list(variance),
             "stride": list(stride or [16.0, 16.0]), "offset": offset}
    return _multi("anchor_generator", {"Input": [input]}, attrs,
                  [("Anchors", input.dtype), ("Variances", input.dtype)],
                  name=name)


def box_coder(prior_box, prior_box_var, target_box,
              code_type="encode_center_size", box_normalized=True,
              axis=0, name=None):
    ins = {"PriorBox": [prior_box], "TargetBox": [target_box]}
    attrs = {"code_type": code_type, "box_normalized": box_normalized,
             "axis": axis}
    if isinstance(prior_box_var, (list, tuple)):
        attrs["variance"] = [float(v) for v in prior_box_var]
    elif prior_box_var is not None:
        ins["PriorBoxVar"] = [prior_box_var]
    return _single("box_coder", ins, attrs, target_box.dtype, name=name,
                   out_slot="OutputBox")


def yolo_box(x, img_size, anchors, class_num, conf_thresh,
             downsample_ratio, clip_bbox=True, name=None):
    return _multi("yolo_box", {"X": [x], "ImgSize": [img_size]},
                  {"anchors": list(anchors), "class_num": class_num,
                   "conf_thresh": conf_thresh,
                   "downsample_ratio": downsample_ratio,
                   "clip_bbox": clip_bbox},
                  [("Boxes", x.dtype), ("Scores", x.dtype)], name=name)


def multiclass_nms(bboxes, scores, score_threshold, nms_top_k, keep_top_k,
                   nms_threshold=0.3, normalized=True, nms_eta=1.0,
                   background_label=0, name=None):
    """Padded result: Out [N, keep_top_k, 6] + NmsRoisNum counts."""
    return _multi("multiclass_nms", {"BBoxes": [bboxes], "Scores": [scores]},
                  {"score_threshold": score_threshold,
                   "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                   "nms_threshold": nms_threshold, "normalized": normalized,
                   "nms_eta": nms_eta, "background_label": background_label},
                  [("Out", bboxes.dtype), ("NmsRoisNum", "int32")],
                  name=name)[0]


def locality_aware_nms(bboxes, scores, score_threshold, nms_top_k,
                       keep_top_k, nms_threshold=0.3, normalized=True,
                       nms_eta=1.0, background_label=-1, name=None):
    return _multi("locality_aware_nms",
                  {"BBoxes": [bboxes], "Scores": [scores]},
                  {"score_threshold": score_threshold,
                   "nms_top_k": nms_top_k, "keep_top_k": keep_top_k,
                   "nms_threshold": nms_threshold, "normalized": normalized,
                   "nms_eta": nms_eta, "background_label": background_label},
                  [("Out", bboxes.dtype), ("NmsRoisNum", "int32")],
                  name=name)[0]


def detection_output(loc, scores, prior_box, prior_box_var,
                     background_label=0, nms_threshold=0.3, nms_top_k=400,
                     keep_top_k=200, score_threshold=0.01, nms_eta=1.0,
                     name=None):
    """reference detection.py detection_output: decode loc against priors
    then run multiclass NMS. loc [N, M, 4], scores [N, M, C] (post-
    softmax), priors [M, 4]."""
    from paddle_tpu import layers as L
    decoded = box_coder(prior_box, prior_box_var, loc,
                        code_type="decode_center_size")
    scores_t = L.transpose(scores, [0, 2, 1])            # [N, C, M]
    return multiclass_nms(decoded, scores_t, score_threshold, nms_top_k,
                          keep_top_k, nms_threshold=nms_threshold,
                          nms_eta=nms_eta,
                          background_label=background_label, name=name)


def detection_map(detect_res, label, class_num, background_label=0,
                  overlap_threshold=0.5, evaluate_difficult=True,
                  has_state=None, input_states=None, out_states=None,
                  ap_version="integral", gt_count=None, difficult=None):
    """Padded form: detect_res [B, D, 6]; label splits into GtLabel
    [B, G] + GtBox [B, G, 4] when passed as a tuple (gt_label, gt_box);
    streaming states are host-side (metrics.DetectionMAP)."""
    if isinstance(label, (list, tuple)):
        gt_label, gt_box = label
    else:
        raise ValueError(
            "detection_map needs label=(gt_label [B,G], gt_box [B,G,4]) "
            "in the padded design (the reference packs both in one LoD "
            "tensor)")
    ins = {"DetectRes": [detect_res], "GtLabel": [gt_label],
           "GtBox": [gt_box]}
    if gt_count is not None:
        ins["GtCount"] = [gt_count]
    if difficult is not None:
        ins["GtDifficult"] = [difficult]
    B, D = detect_res.shape[0], detect_res.shape[1]
    return _multi("detection_map", ins,
                  {"class_num": class_num,
                   "background_label": background_label,
                   "overlap_threshold": overlap_threshold,
                   "evaluate_difficult": evaluate_difficult,
                   "ap_type": ap_version},
                  [("MAP", "float32"), ("AccumPosCount", "int32"),
                   ("AccumTruePos", "float32"),
                   ("AccumFalsePos", "float32")])[0]


def target_assign(input, matched_indices, negative_indices=None,
                  mismatch_value=0, name=None):
    ins = {"X": [input], "MatchIndices": [matched_indices]}
    if negative_indices is not None:
        ins["NegIndices"] = [negative_indices]
    return _multi("target_assign", ins,
                  {"mismatch_value": mismatch_value},
                  [("Out", input.dtype), ("OutWeight", input.dtype)],
                  name=name)


def mine_hard_examples(cls_loss, loc_loss, match_indices, match_dist,
                       neg_pos_ratio=1.0, neg_dist_threshold=0.5,
                       sample_size=0, mining_type="max_negative",
                       name=None):
    ins = {"ClsLoss": [cls_loss], "MatchIndices": [match_indices],
           "MatchDist": [match_dist]}
    if loc_loss is not None:
        ins["LocLoss"] = [loc_loss]
    return _multi("mine_hard_examples", ins,
                  {"neg_pos_ratio": neg_pos_ratio,
                   "neg_dist_threshold": neg_dist_threshold,
                   "sample_size": sample_size, "mining_type": mining_type},
                  [("NegIndices", "int32"), ("NegCount", "int32"),
                   ("UpdatedMatchIndices", "int32")], name=name)


def ssd_loss(location, confidence, gt_box, gt_label, prior_box,
             prior_box_var=None, background_label=0, overlap_threshold=0.5,
             neg_pos_ratio=3.0, neg_overlap=0.5, loc_loss_weight=1.0,
             conf_loss_weight=1.0, match_type="per_prediction",
             mining_type="max_negative", normalize=True, sample_size=None,
             name=None):
    """SSD multibox loss (reference detection.py ssd_loss — the same
    composite: match -> mine -> target_assign -> smooth_l1 + softmax CE).
    Padded form: location [N, M, 4], confidence [N, M, C], gt_box
    [N, G, 4], gt_label [N, G, 1]."""
    from paddle_tpu import layers as L

    if mining_type != "max_negative":
        raise NotImplementedError("ssd_loss: only max_negative mining")
    N, M_, C = confidence.shape
    G = gt_box.shape[1]

    # 1. match priors to gts per image (bipartite_match is 2-D, so loop
    # the static batch)
    matches, dists = [], []
    for i in range(N):
        g = L.slice(gt_box, axes=[0], starts=[i], ends=[i + 1])
        g = L.reshape(g, [G, 4])
        sim = iou_similarity(g, prior_box)               # [G, M]
        m, d = _multi("bipartite_match", {"DistMat": [sim]},
                      {"match_type": match_type,
                       "dist_threshold": overlap_threshold},
                      [("ColToRowMatchIndices", "int32"),
                       ("ColToRowMatchDist", "float32")])
        matches.append(L.reshape(m, [1, M_]))
        dists.append(L.reshape(d, [1, M_]))
    match_idx = L.concat(matches, axis=0)                # [N, M]
    match_dist = L.concat(dists, axis=0)

    # 2. conf loss per prior for mining
    gt_lbl3 = L.reshape(L.cast(gt_label, "float32"), [N, G, 1])
    tgt_lbl, _ = target_assign(gt_lbl3, match_idx,
                               mismatch_value=background_label)
    tgt_lbl_i = L.cast(tgt_lbl, "int64")                 # [N, M, 1]
    conf_loss = L.softmax_with_cross_entropy(confidence, tgt_lbl_i)
    conf_loss2d = L.reshape(conf_loss, [N, M_])

    # 3. mine negatives
    neg_idx, _, upd_match = mine_hard_examples(
        conf_loss2d, None, match_idx, match_dist,
        neg_pos_ratio=neg_pos_ratio, neg_dist_threshold=neg_overlap,
        sample_size=sample_size or 0, mining_type=mining_type)

    # 4. location targets (encode gt against priors) + weights
    enc = box_coder(prior_box, prior_box_var, gt_box,
                    code_type="encode_center_size")  # [N*G, M, 4]
    enc = L.reshape(enc, [N, G, M_, 4])
    tgt_loc, tgt_loc_wt = target_assign(enc, upd_match)
    loc_diff = L.smooth_l1(L.reshape(location, [N * M_, 4]),
                           L.reshape(tgt_loc, [N * M_, 4]))
    loc_l = L.elementwise_mul(L.reshape(loc_diff, [N, M_]),
                              L.reshape(tgt_loc_wt, [N, M_]))

    # 5. conf target weights: positives + mined negatives
    _, conf_wt = target_assign(gt_lbl3, upd_match,
                               negative_indices=neg_idx,
                               mismatch_value=background_label)
    conf_l = L.elementwise_mul(conf_loss2d, L.reshape(conf_wt, [N, M_]))

    total = L.elementwise_add(L.scale(loc_l, loc_loss_weight),
                              L.scale(conf_l, conf_loss_weight))
    if normalize:
        n_pos = L.reduce_sum(L.reshape(tgt_loc_wt, [N * M_]))
        total = L.elementwise_div(
            total, L.reshape(
                L.elementwise_max(
                    n_pos, L.fill_constant([1], "float32", 1.0)), [1]))
    return total


def multi_box_head(inputs, image, base_size, num_classes, aspect_ratios,
                   min_ratio=None, max_ratio=None, min_sizes=None,
                   max_sizes=None, steps=None, step_w=None, step_h=None,
                   offset=0.5, variance=(0.1, 0.1, 0.2, 0.2), flip=True,
                   clip=False, kernel_size=1, pad=0, stride=1, name=None,
                   min_max_aspect_ratios_order=False):
    """SSD detection head (reference detection.py multi_box_head): per
    feature map emit priors + conv loc/conf predictions, concat across
    maps. Returns (mbox_locs [N, P, 4], mbox_confs [N, P, C], boxes
    [P, 4], variances [P, 4])."""
    from paddle_tpu import layers as L

    n_layer = len(inputs)
    if min_sizes is None:
        min_sizes, max_sizes = [], []
        step = int(np.floor((max_ratio - min_ratio)
                            / max(n_layer - 2, 1)))
        for ratio in range(min_ratio, max_ratio + 1, step):
            min_sizes.append(base_size * ratio / 100.0)
            max_sizes.append(base_size * (ratio + step) / 100.0)
        min_sizes = [base_size * 0.1] + min_sizes
        max_sizes = [base_size * 0.2] + max_sizes

    locs, confs, boxes_all, vars_all = [], [], [], []
    for i, x in enumerate(inputs):
        mins = min_sizes[i]
        maxs = max_sizes[i] if max_sizes else None
        ar = aspect_ratios[i]
        step_pair = (steps[i] if steps else
                     (step_w[i] if step_w else 0.0,
                      step_h[i] if step_h else 0.0))
        if not isinstance(step_pair, (list, tuple)):
            step_pair = (step_pair, step_pair)
        mins_l = [mins] if not isinstance(mins, list) else mins
        maxs_l = ([maxs] if maxs and not isinstance(maxs, list)
                  else (maxs or []))
        ar_l = list(ar) if isinstance(ar, (list, tuple)) else [ar]
        box, var = prior_box(
            x, image, mins_l, maxs_l, ar_l, list(variance), flip, clip,
            step_pair, offset, min_max_aspect_ratios_order)
        # priors per cell (mirrors the prior_box op's wh enumeration)
        n_extra = sum(2 if flip and abs(r - 1.0) > 1e-6 else
                      (0 if abs(r - 1.0) <= 1e-6 else 1) for r in ar_l)
        num_priors_per_cell = len(mins_l) * (
            1 + n_extra + (1 if maxs_l else 0))
        # conv heads
        loc = L.conv2d(x, num_priors_per_cell * 4, kernel_size,
                            padding=pad, stride=stride)
        loc = L.transpose(loc, [0, 2, 3, 1])
        loc = L.reshape(loc, [loc.shape[0], -1, 4])
        conf = L.conv2d(x, num_priors_per_cell * num_classes,
                             kernel_size, padding=pad, stride=stride)
        conf = L.transpose(conf, [0, 2, 3, 1])
        conf = L.reshape(conf, [conf.shape[0], -1, num_classes])
        locs.append(loc)
        confs.append(conf)
        boxes_all.append(L.reshape(box, [-1, 4]))
        vars_all.append(L.reshape(var, [-1, 4]))
    mbox_locs = L.concat(locs, axis=1)
    mbox_confs = L.concat(confs, axis=1)
    boxes = L.concat(boxes_all, axis=0)
    variances = L.concat(vars_all, axis=0)
    return mbox_locs, mbox_confs, boxes, variances


def rpn_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                      gt_boxes, is_crowd=None, im_info=None,
                      rpn_batch_size_per_im=256, rpn_straddle_thresh=0.0,
                      rpn_fg_fraction=0.5, rpn_positive_overlap=0.7,
                      rpn_negative_overlap=0.3, use_random=True,
                      gt_count=None):
    """Padded outputs (see ops/detection_rcnn_ops.py): score/loc index
    tensors [B, S] with counts; predicted score/loc gathers are left to
    the caller (the reference gathers here — with padded indices the
    caller masks by count)."""
    ins = {"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
           "ImInfo": [im_info]}
    if is_crowd is not None:
        ins["IsCrowd"] = [is_crowd]
    if gt_count is not None:
        ins["GtCount"] = [gt_count]
    return _multi(
        "rpn_target_assign", ins,
        {"rpn_batch_size_per_im": rpn_batch_size_per_im,
         "rpn_straddle_thresh": rpn_straddle_thresh,
         "rpn_fg_fraction": rpn_fg_fraction,
         "rpn_positive_overlap": rpn_positive_overlap,
         "rpn_negative_overlap": rpn_negative_overlap,
         "use_random": use_random},
        [("LocationIndex", "int32"), ("LocCount", "int32"),
         ("ScoreIndex", "int32"), ("ScoreCount", "int32"),
         ("TargetLabel", "int32"), ("TargetBBox", "float32"),
         ("BBoxInsideWeight", "float32")])


def retinanet_target_assign(bbox_pred, cls_logits, anchor_box, anchor_var,
                            gt_boxes, gt_labels, is_crowd=None,
                            im_info=None, num_classes=1,
                            positive_overlap=0.5, negative_overlap=0.4,
                            gt_count=None):
    ins = {"Anchor": [anchor_box], "GtBoxes": [gt_boxes],
           "GtLabels": [gt_labels], "ImInfo": [im_info]}
    if is_crowd is not None:
        ins["IsCrowd"] = [is_crowd]
    if gt_count is not None:
        ins["GtCount"] = [gt_count]
    return _multi(
        "retinanet_target_assign", ins,
        {"positive_overlap": positive_overlap,
         "negative_overlap": negative_overlap},
        [("LocationIndex", "int32"), ("LocCount", "int32"),
         ("ScoreIndex", "int32"), ("ScoreCount", "int32"),
         ("TargetLabel", "int32"), ("TargetBBox", "float32"),
         ("BBoxInsideWeight", "float32"), ("ForegroundNumber", "int32")])


def retinanet_detection_output(bboxes, scores, anchors, im_info,
                               score_threshold=0.05, nms_top_k=1000,
                               keep_top_k=100, nms_threshold=0.3,
                               nms_eta=1.0):
    """bboxes/scores/anchors are per-FPN-level lists."""
    return _multi(
        "retinanet_detection_output",
        {"BBoxes": list(bboxes), "Scores": list(scores),
         "Anchors": list(anchors), "ImInfo": [im_info]},
        {"score_threshold": score_threshold, "nms_top_k": nms_top_k,
         "keep_top_k": keep_top_k, "nms_threshold": nms_threshold,
         "nms_eta": nms_eta},
        [("Out", "float32"), ("NmsRoisNum", "int32")])[0]


def generate_proposals(scores, bbox_deltas, im_info, anchors, variances,
                       pre_nms_top_n=6000, post_nms_top_n=1000,
                       nms_thresh=0.5, min_size=0.1, eta=1.0,
                       return_rois_num=False, name=None):
    rois, probs, num = _multi(
        "generate_proposals",
        {"Scores": [scores], "BboxDeltas": [bbox_deltas],
         "ImInfo": [im_info], "Anchors": [anchors],
         "Variances": [variances]},
        {"pre_nms_topN": pre_nms_top_n, "post_nms_topN": post_nms_top_n,
         "nms_thresh": nms_thresh, "min_size": min_size, "eta": eta},
        [("RpnRois", scores.dtype), ("RpnRoiProbs", scores.dtype),
         ("RpnRoisLod", "int32")], name=name)
    if return_rois_num:
        return rois, probs, num
    return rois, probs


def generate_proposal_labels(rpn_rois, gt_classes, is_crowd, gt_boxes,
                             im_info, batch_size_per_im=256,
                             fg_fraction=0.25, fg_thresh=0.25,
                             bg_thresh_hi=0.5, bg_thresh_lo=0.0,
                             bbox_reg_weights=(0.1, 0.1, 0.2, 0.2),
                             class_nums=None, use_random=True,
                             is_cls_agnostic=False, is_cascade_rcnn=False,
                             rois_num=None, gt_count=None):
    ins = {"RpnRois": [rpn_rois], "GtClasses": [gt_classes],
           "GtBoxes": [gt_boxes], "ImInfo": [im_info]}
    if is_crowd is not None:
        ins["IsCrowd"] = [is_crowd]
    if rois_num is not None:
        ins["RpnRoisLod"] = [rois_num]
    if gt_count is not None:
        ins["GtCount"] = [gt_count]
    return _multi(
        "generate_proposal_labels", ins,
        {"batch_size_per_im": batch_size_per_im,
         "fg_fraction": fg_fraction, "fg_thresh": fg_thresh,
         "bg_thresh_hi": bg_thresh_hi, "bg_thresh_lo": bg_thresh_lo,
         "bbox_reg_weights": list(bbox_reg_weights),
         "class_nums": class_nums, "use_random": use_random,
         "is_cls_agnostic": is_cls_agnostic,
         "is_cascade_rcnn": is_cascade_rcnn},
        [("Rois", "float32"), ("LabelsInt32", "int32"),
         ("BboxTargets", "float32"), ("BboxInsideWeights", "float32"),
         ("BboxOutsideWeights", "float32"), ("RoisNum", "int32")])


def generate_mask_labels(im_info, gt_classes, is_crowd, gt_segms, rois,
                         labels_int32, num_classes, resolution,
                         gt_segm_lens=None, gt_count=None):
    ins = {"Rois": [rois], "LabelsInt32": [labels_int32],
           "GtSegms": [gt_segms], "GtClasses": [gt_classes]}
    if gt_segm_lens is not None:
        ins["GtSegmLens"] = [gt_segm_lens]
    if gt_count is not None:
        ins["GtCount"] = [gt_count]
    if is_crowd is not None:
        ins["IsCrowd"] = [is_crowd]
    return _multi(
        "generate_mask_labels", ins,
        {"num_classes": num_classes, "resolution": resolution},
        [("MaskRois", "float32"), ("RoiHasMaskInt32", "int32"),
         ("MaskInt32", "int32"), ("MaskNum", "int32")])[:3]


def distribute_fpn_proposals(fpn_rois, min_level, max_level, refer_level,
                             refer_scale, rois_num=None, name=None):
    helper = LayerHelper("distribute_fpn_proposals", name=name)
    n_level = max_level - min_level + 1
    ins = {"FpnRois": [fpn_rois]}
    if rois_num is not None:
        ins["RoisNum"] = [rois_num]
    outs = [helper.create_variable_for_type_inference(fpn_rois.dtype)
            for _ in range(n_level)]
    nums = [helper.create_variable_for_type_inference("int32")
            for _ in range(n_level)]
    restore = helper.create_variable_for_type_inference("int32")
    helper.append_op(
        type="distribute_fpn_proposals", inputs=ins,
        outputs={"MultiFpnRois": outs, "MultiLevelRoisNum": nums,
                 "RestoreIndex": [restore]},
        attrs={"min_level": min_level, "max_level": max_level,
               "refer_level": refer_level, "refer_scale": refer_scale},
        infer_shape=False)
    return outs, restore, nums


def collect_fpn_proposals(multi_rois, multi_scores, min_level, max_level,
                          post_nms_top_n, rois_num_per_level=None,
                          name=None):
    ins = {"MultiLevelRois": list(multi_rois),
           "MultiLevelScores": list(multi_scores)}
    if rois_num_per_level is not None:
        ins["MultiLevelRoisNum"] = list(rois_num_per_level)
    return _multi("collect_fpn_proposals", ins,
                  {"post_nms_topN": post_nms_top_n},
                  [("FpnRois", "float32"), ("RoisNum", "int32")],
                  name=name)[0]


def box_decoder_and_assign(prior_box, prior_box_var, target_box,
                           box_score, box_clip, name=None):
    return _multi("box_decoder_and_assign",
                  {"PriorBox": [prior_box], "PriorBoxVar": [prior_box_var],
                   "TargetBox": [target_box], "BoxScore": [box_score]},
                  {"box_clip": box_clip},
                  [("DecodeBox", target_box.dtype),
                   ("OutputAssignBox", target_box.dtype)], name=name)


def roi_perspective_transform(input, rois, transformed_height,
                              transformed_width, spatial_scale=1.0,
                              rois_batch=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if rois_batch is not None:
        ins["RoisBatch"] = [rois_batch]
    return _multi("roi_perspective_transform", ins,
                  {"transformed_height": transformed_height,
                   "transformed_width": transformed_width,
                   "spatial_scale": spatial_scale},
                  [("Out", input.dtype), ("Mask", "int32"),
                   ("TransformMatrix", input.dtype)], name=name)
