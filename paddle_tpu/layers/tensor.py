"""fluid.layers tensor surface (reference: python/paddle/fluid/layers/tensor.py)."""
import builtins

import numpy as np

from ..framework.core import Variable
from ..framework.dtype import convert_dtype
from .layer_helper import LayerHelper


def data(name, shape, dtype="float32", lod_level=0, append_batch_size=False,
         stop_gradient=True):
    """fluid.data / fluid.layers.data (reference layers/io.py data). Data vars
    default to stop_gradient=True like the reference."""
    from ..framework.core import default_main_program
    block = default_main_program().global_block()
    shape = list(shape)
    if append_batch_size:
        shape = [-1] + shape
    return block.create_var(name=name, shape=shape, dtype=dtype,
                            lod_level=lod_level, stop_gradient=stop_gradient,
                            is_data=True)


def create_tensor(dtype, name=None, persistable=False):
    helper = LayerHelper("create_tensor", name=name)
    return helper.create_variable(name=helper.name, dtype=dtype,
                                  persistable=persistable)


def create_parameter(shape, dtype, name=None, attr=None, is_bias=False,
                     default_initializer=None):
    """fluid.layers.create_parameter (reference layers/tensor.py:75)."""
    import copy as _copy
    from ..param_attr import ParamAttr
    if attr is None:
        attr = ParamAttr(name=name)
    else:
        attr = ParamAttr._to_attr(attr)
        if attr is not False and name is not None and attr.name is None:
            attr = _copy.copy(attr)  # never mutate the caller's ParamAttr
            attr.name = name
    helper = LayerHelper("create_parameter")
    return helper.create_parameter(attr, shape, dtype, is_bias=is_bias,
                                   default_initializer=default_initializer)


def create_global_var(shape, value, dtype, persistable=False,
                      force_cpu=False, name=None):
    from ..framework import initializer as init_mod
    helper = LayerHelper("global_var", name=name)
    var = helper.create_global_variable(
        shape=shape, dtype=dtype, persistable=persistable, name=name,
        initializer=init_mod.ConstantInitializer(value))
    return var


def cast(x, dtype):
    helper = LayerHelper("cast")
    dtype = convert_dtype(dtype)
    out = helper.create_variable_for_type_inference(dtype=dtype)
    helper.append_op(type="cast", inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"in_dtype": x.dtype, "out_dtype": dtype})
    return out


def concat(input, axis=0, name=None):
    helper = LayerHelper("concat", name=name)
    out = helper.create_variable_for_type_inference(dtype=input[0].dtype)
    helper.append_op(type="concat", inputs={"X": list(input)},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def split(input, num_or_sections, dim=-1, name=None):
    helper = LayerHelper("split", name=name)
    axis = dim % len(input.shape)
    if isinstance(num_or_sections, int):
        n = num_or_sections
        sections = []
    else:
        n = len(num_or_sections)
        sections = list(num_or_sections)
    outs = [helper.create_variable_for_type_inference(dtype=input.dtype)
            for _ in builtins.range(n)]  # layers.range shadows builtin
    helper.append_op(type="split", inputs={"X": [input]},
                     outputs={"Out": outs},
                     attrs={"axis": axis, "num": n, "sections": sections})
    return outs


def stack(x, axis=0, name=None):
    helper = LayerHelper("stack", name=name)
    out = helper.create_variable_for_type_inference(dtype=x[0].dtype)
    helper.append_op(type="stack", inputs={"X": list(x)},
                     outputs={"Y": [out]}, attrs={"axis": axis})
    return out


def unstack(x, axis=0, num=None, name=None):
    helper = LayerHelper("unstack", name=name)
    num = num or x.shape[axis]
    outs = [helper.create_variable_for_type_inference(dtype=x.dtype)
            for _ in builtins.range(num)]
    helper.append_op(type="unstack", inputs={"X": [x]}, outputs={"Y": outs},
                     attrs={"axis": axis, "num": num})
    return outs


def fill_constant(shape, dtype, value, force_cpu=False, out=None, name=None):
    helper = LayerHelper("fill_constant", name=name)
    dtype = convert_dtype(dtype)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=dtype)
    attr_shape, positions, tensors = _split_tensor_dims(shape)
    attrs = {"shape": attr_shape, "dtype": dtype, "value": float(value)}
    if tensors:
        attrs["shape_tensor_positions"] = positions
        helper.append_op(type="fill_constant",
                         inputs={"ShapeTensorList": tensors},
                         outputs={"Out": [out]}, attrs=attrs,
                         infer_shape=False)
        out.shape = tuple(attr_shape)
    else:
        helper.append_op(type="fill_constant", outputs={"Out": [out]},
                         attrs=attrs)
    out.stop_gradient = True
    return out


def fill_constant_batch_size_like(input, shape, dtype, value,
                                  input_dim_idx=0, output_dim_idx=0):
    helper = LayerHelper("fill_constant_batch_size_like")
    out = helper.create_variable_for_type_inference(
        dtype=convert_dtype(dtype))
    helper.append_op(
        type="fill_constant_batch_size_like", inputs={"Input": [input]},
        outputs={"Out": [out]},
        attrs={"shape": list(shape), "dtype": convert_dtype(dtype),
               "value": float(value), "input_dim_idx": input_dim_idx,
               "output_dim_idx": output_dim_idx})
    out.stop_gradient = True
    return out


def zeros(shape, dtype="float32", name=None):
    return fill_constant(shape, dtype, 0.0, name=name)


def ones(shape, dtype="float32", name=None):
    return fill_constant(shape, dtype, 1.0, name=name)


def zeros_like(x, out=None, name=None):
    helper = LayerHelper("zeros_like", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="fill_zeros_like", inputs={"X": [x]},
                     outputs={"Out": [out]})
    return out


def ones_like(x, out=None, name=None):
    helper = LayerHelper("ones_like", name=name)
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="fill_any_like", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"value": 1.0})
    return out


def assign(input, output=None):
    helper = LayerHelper("assign")
    if isinstance(input, np.ndarray) or np.isscalar(input):
        arr = np.asarray(input)
        if output is None:
            output = helper.create_variable_for_type_inference(
                dtype=str(arr.dtype))
        helper.append_op(type="assign_value", outputs={"Out": [output]},
                         attrs={"shape": list(arr.shape),
                                "dtype": str(arr.dtype), "values": arr})
        return output
    if output is None:
        output = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="assign", inputs={"X": [input]},
                     outputs={"Out": [output]})
    return output


def _split_tensor_dims(shape):
    """Split a dim list into (attr_shape, positions, tensor_vars).
    Variable entries become ShapeTensorList inputs (reference
    reshape_op.cc / fill_constant_op.cc ShapeTensor[List]): each tensor
    dim rides as a [1] int input and is concretized at lowering — sound
    under XLA because shape-op outputs are trace-time constants. In
    dygraph, tensor dims concretize immediately via VarBase.__int__."""
    from ..framework.core import Variable
    from ..dygraph import base as dy
    dims = list(shape)
    if dy.enabled():
        return [int(s) for s in dims], [], []
    attr_shape, positions, tensors = [], [], []
    for i, s in enumerate(dims):
        if isinstance(s, Variable):
            positions.append(i)
            tensors.append(s)
            attr_shape.append(-1)
        else:
            attr_shape.append(int(s))
    return attr_shape, positions, tensors


def reshape(x, shape, actual_shape=None, act=None, inplace=False, name=None):
    helper = LayerHelper("reshape", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    xshape = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                       stop_gradient=True)
    attr_shape, positions, tensors = _split_tensor_dims(shape)
    inputs = {"X": [x]}
    attrs = {"shape": attr_shape}
    if tensors:
        inputs["ShapeTensorList"] = tensors
        attrs["shape_tensor_positions"] = positions
        helper.append_op(type="reshape2", inputs=inputs,
                         outputs={"Out": [out], "XShape": [xshape]},
                         attrs=attrs, infer_shape=False)
        # manual annotation: tensor dims are unknown until lowering
        out.shape = tuple(attr_shape)
        if x.shape is not None:
            xshape.shape = (0,) + tuple(x.shape)
    else:
        helper.append_op(type="reshape2", inputs=inputs,
                         outputs={"Out": [out], "XShape": [xshape]},
                         attrs=attrs)
    return helper.append_activation(out, act)


def transpose(x, perm, name=None):
    helper = LayerHelper("transpose", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    xshape = helper.create_variable_for_type_inference(dtype=x.dtype,
                                                       stop_gradient=True)
    helper.append_op(type="transpose2", inputs={"X": [x]},
                     outputs={"Out": [out], "XShape": [xshape]},
                     attrs={"axis": list(perm)})
    return out


def slice(input, axes, starts, ends, name=None):
    helper = LayerHelper("slice", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="slice", inputs={"Input": [input]},
                     outputs={"Out": [out]},
                     attrs={"axes": list(axes), "starts": list(starts),
                            "ends": list(ends)})
    return out


def gather(input, index, axis=0, name=None):
    helper = LayerHelper("gather", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="gather",
                     inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def gather_nd(input, index, name=None):
    helper = LayerHelper("gather_nd", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(type="gather_nd",
                     inputs={"X": [input], "Index": [index]},
                     outputs={"Out": [out]})
    return out


def scatter(input, index, updates, overwrite=True, name=None):
    helper = LayerHelper("scatter", name=name)
    out = helper.create_variable_for_type_inference(dtype=input.dtype)
    helper.append_op(
        type="scatter",
        inputs={"X": [input], "Ids": [index], "Updates": [updates]},
        outputs={"Out": [out]}, attrs={"overwrite": overwrite})
    return out


def expand(x, expand_times, name=None):
    helper = LayerHelper("expand", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="expand", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"expand_times": list(expand_times)})
    return out


def one_hot(input, depth, allow_out_of_range=False):
    helper = LayerHelper("one_hot")
    out = helper.create_variable_for_type_inference(dtype="float32")
    helper.append_op(type="one_hot", inputs={"X": [input]},
                     outputs={"Out": [out]}, attrs={"depth": depth})
    return out


def range(start, end, step, dtype, name=None):
    helper = LayerHelper("range", name=name)
    out = helper.create_variable_for_type_inference(
        dtype=convert_dtype(dtype), stop_gradient=True)
    helper.append_op(type="range", outputs={"Out": [out]},
                     attrs={"start": start, "end": end, "step": step,
                            "dtype": convert_dtype(dtype)})
    return out


def arange(start=0, end=None, step=1, dtype="int64", name=None):
    if end is None:
        start, end = 0, start
    return range(start, end, step, dtype, name=name)


def argmax(x, axis=0, name=None):
    helper = LayerHelper("arg_max", name=name)
    out = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    helper.append_op(type="arg_max", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def argmin(x, axis=0, name=None):
    helper = LayerHelper("arg_min", name=name)
    out = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    helper.append_op(type="arg_min", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def argsort(x, axis=-1, descending=False, name=None):
    helper = LayerHelper("argsort", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    idx = helper.create_variable_for_type_inference(dtype="int64",
                                                    stop_gradient=True)
    helper.append_op(type="argsort", inputs={"X": [x]},
                     outputs={"Out": [out], "Indices": [idx]},
                     attrs={"axis": axis, "descending": descending})
    return out, idx


def increment(x, value=1.0, in_place=True):
    helper = LayerHelper("increment")
    out = x if in_place else helper.create_variable_for_type_inference(
        dtype=x.dtype)
    helper.append_op(type="increment", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"step": float(value)})
    return out


def cumsum(x, axis=-1, name=None):
    helper = LayerHelper("cumsum", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="cumsum", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"axis": axis})
    return out


def where(condition, x, y, name=None):
    helper = LayerHelper("where", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="where",
                     inputs={"Condition": [condition], "X": [x], "Y": [y]},
                     outputs={"Out": [out]})
    return out


def shape(input):
    helper = LayerHelper("shape")
    out = helper.create_variable_for_type_inference(dtype="int32",
                                                    stop_gradient=True)
    helper.append_op(type="shape", inputs={"Input": [input]},
                     outputs={"Out": [out]})
    return out


def linspace(start, stop, num, dtype="float32", name=None):
    arr = np.linspace(start, stop, num).astype(convert_dtype(dtype))
    return assign(arr)


def diag(diagonal, name=None):
    helper = LayerHelper("diag", name=name)
    out = helper.create_variable_for_type_inference(dtype=diagonal.dtype)
    helper.append_op(type="diag_v2", inputs={"X": [diagonal]},
                     outputs={"Out": [out]})
    return out


def tril(x, diagonal=0, name=None):
    helper = LayerHelper("tril", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="tril_triu", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"diagonal": diagonal, "lower": True})
    return out


def triu(x, diagonal=0, name=None):
    helper = LayerHelper("triu", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="tril_triu", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"diagonal": diagonal, "lower": False})
    return out


def merge_selected_rows(x, name=None):
    """reference merge_selected_rows_op.cc via layers surface."""
    helper = LayerHelper("merge_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="merge_selected_rows", inputs={"X": [x]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out


def get_tensor_from_selected_rows(x, name=None):
    helper = LayerHelper("get_tensor_from_selected_rows", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="get_tensor_from_selected_rows",
                     inputs={"X": [x]}, outputs={"Out": [out]},
                     infer_shape=False)
    return out


def beam_search_decode(ids, scores, beam_size=None, end_id=None,
                       parent_idx=None, name=None):
    """reference beam_search_decode_op.cc: walk ParentIdx back to full
    sentences. Padded form: Ids/ParentIdx [T, B, beam], Scores
    [B, beam]."""
    helper = LayerHelper("beam_search_decode", name=name)
    sent_ids = helper.create_variable_for_type_inference(dtype=ids.dtype)
    sent_scores = helper.create_variable_for_type_inference(
        dtype=scores.dtype)
    ins = {"Ids": [ids], "Scores": [scores]}
    if parent_idx is not None:
        ins["ParentIdx"] = [parent_idx]
    helper.append_op(type="beam_search_decode", inputs=ins,
                     outputs={"SentenceIds": [sent_ids],
                              "SentenceScores": [sent_scores]},
                     attrs={}, infer_shape=False)
    return sent_ids, sent_scores


def reorder_lod_tensor_by_rank(x, rank_table, name=None):
    helper = LayerHelper("reorder_lod_tensor_by_rank", name=name)
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="reorder_lod_tensor_by_rank",
                     inputs={"X": [x], "RankTable": [rank_table]},
                     outputs={"Out": [out]}, infer_shape=False)
    return out
