"""Collective layer wrappers (reference:
python/paddle/fluid/layers/collective.py — _allreduce :16, _allgather,
_broadcast; used by transpiler/collective.py and dygraph DataParallel)."""
from .layer_helper import LayerHelper


def _allreduce(x, out=None, reduce_type="sum", sync_mode=False, ring_id=0):
    helper = LayerHelper("allreduce")
    if out is None:
        out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type=f"c_allreduce_{reduce_type}",
                     inputs={"X": [x]}, outputs={"Out": [out]},
                     attrs={"ring_id": ring_id})
    return out


def _allgather(x, nranks, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("allgather")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="c_allgather", inputs={"X": [x]},
                     outputs={"Out": [out]},
                     attrs={"ring_id": ring_id, "nranks": nranks})
    return out


def shard(x, *spec):
    """Pin `x` to a mesh sharding, one axis name (or None) per dim — the
    declarative TPU replacement for the reference's per-device graph surgery.
    E.g. ``shard(h, "dp", "sp", None)`` for sequence parallelism."""
    helper = LayerHelper("sharding_constraint")
    out = helper.create_variable_for_type_inference(dtype=x.dtype)
    helper.append_op(type="sharding_constraint", inputs={"X": [x]},
                     outputs={"Out": [out]}, attrs={"spec": tuple(spec)})
    return out


def _broadcast(x, root=0, ring_id=0, use_calc_stream=False):
    helper = LayerHelper("broadcast")
    helper.append_op(type="c_broadcast", inputs={"X": [x]},
                     outputs={"Out": [x]},
                     attrs={"ring_id": ring_id, "root": root})
    return x
