"""fluid.layers.distributions (reference
python/paddle/fluid/layers/distributions.py — Uniform :115, Normal :260,
Categorical :424, MultivariateNormalDiag :530). Each method BUILDS graph
ops (static mode) exactly like the reference; math composed from the
existing layer surface."""
import math

import numpy as np

from ..framework.core import Variable
from . import math as M
from . import tensor as T
from .nn import gaussian_random_batch_size_like, uniform_random, \
    uniform_random_batch_size_like
from .layer_helper import LayerHelper


def _L():
    # activation-style fns (log/exp) live on the package
    # namespace; import lazily to avoid a circular import
    from .. import layers
    return layers

__all__ = ["Uniform", "Normal", "Categorical", "MultivariateNormalDiag"]


def _to_var(v, ref=None, dtype="float32"):
    if isinstance(v, Variable):
        return v
    arr = np.asarray(v, np.float32)
    return T.assign(arr.reshape(arr.shape or (1,)))


class Distribution:
    def sample(self, shape, seed=0):
        raise NotImplementedError

    def entropy(self):
        raise NotImplementedError

    def log_prob(self, value):
        raise NotImplementedError

    def kl_divergence(self, other):
        raise NotImplementedError


class Uniform(Distribution):
    """U(low, high) (reference distributions.py:115)."""

    def __init__(self, low, high):
        self.low = _to_var(low)
        self.high = _to_var(high)

    def sample(self, shape, seed=0):
        u = uniform_random(list(shape) + list(self.low.shape),
                           min=0.0, max=1.0, seed=seed)
        return M.elementwise_add(
            self.low, M.elementwise_mul(
                u, M.elementwise_sub(self.high, self.low)))

    def log_prob(self, value):
        span = M.elementwise_sub(self.high, self.low)
        lb = T.cast(M.less_than(self.low, value), "float32")
        ub = T.cast(M.less_than(value, self.high), "float32")
        return M.elementwise_sub(
            _L().log(M.elementwise_mul(lb, ub)), _L().log(span))

    def entropy(self):
        return _L().log(M.elementwise_sub(self.high, self.low))


class Normal(Distribution):
    """N(loc, scale) (reference distributions.py:260)."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)
        self.scale = _to_var(scale)

    def sample(self, shape, seed=0):
        from .nn import gaussian_random
        z = gaussian_random(list(shape) + list(self.loc.shape),
                            mean=0.0, std=1.0, seed=seed)
        return M.elementwise_add(
            self.loc, M.elementwise_mul(z, self.scale))

    def entropy(self):
        c = 0.5 + 0.5 * math.log(2.0 * math.pi)
        return M.elementwise_add(
            T.fill_constant(self.scale.shape or [1], "float32", c),
            _L().log(self.scale))

    def log_prob(self, value):
        var = M.elementwise_mul(self.scale, self.scale)
        d = M.elementwise_sub(value, self.loc)
        quad = M.elementwise_div(M.elementwise_mul(d, d),
                                 M.scale(var, 2.0))
        return M.elementwise_sub(
            M.scale(quad, -1.0),
            M.elementwise_add(
                _L().log(self.scale),
                T.fill_constant(self.scale.shape or [1], "float32",
                                0.5 * math.log(2.0 * math.pi))))

    def kl_divergence(self, other):
        """KL(self || other), both Normal (reference :404)."""
        var_ratio = M.elementwise_div(self.scale, other.scale)
        var_ratio = M.elementwise_mul(var_ratio, var_ratio)
        d = M.elementwise_div(M.elementwise_sub(self.loc, other.loc),
                              other.scale)
        t1 = M.elementwise_mul(d, d)
        return M.scale(
            M.elementwise_sub(
                M.elementwise_add(var_ratio, t1),
                M.elementwise_add(
                    T.ones_like(var_ratio), _L().log(var_ratio))),
            0.5)


class Categorical(Distribution):
    """Categorical over unnormalized logits (reference :424)."""

    def __init__(self, logits):
        self.logits = logits

    def _log_norm(self):
        # log softmax pieces via existing ops; keep the shifted logits so
        # log-probabilities are formed as shifted - log(z) (finite even
        # where exp underflows p to 0, matching the reference's
        # prob*(logits - log z) formulation at :521-527)
        shifted = M.elementwise_sub(
            self.logits, M.reduce_max(self.logits, dim=[-1],
                                      keep_dim=True))
        e = _L().exp(shifted)
        z = M.reduce_sum(e, dim=[-1], keep_dim=True)
        return shifted, e, z

    def entropy(self):
        # keep_dim=True matches the reference's [..., 1] output shape
        # (reference :524)
        shifted, e, z = self._log_norm()
        p = M.elementwise_div(e, z)
        logp = M.elementwise_sub(shifted, _L().log(z))
        return M.scale(M.reduce_sum(M.elementwise_mul(p, logp),
                                    dim=[-1], keep_dim=True), -1.0)

    def kl_divergence(self, other):
        shifted, e, z = self._log_norm()
        oshifted, oe, oz = other._log_norm()
        p = M.elementwise_div(e, z)
        logp = M.elementwise_sub(shifted, _L().log(z))
        ologp = M.elementwise_sub(oshifted, _L().log(oz))
        return M.reduce_sum(
            M.elementwise_mul(p, M.elementwise_sub(logp, ologp)),
            dim=[-1], keep_dim=True)


class MultivariateNormalDiag(Distribution):
    """N(loc, diag(scale)) (reference :530)."""

    def __init__(self, loc, scale):
        self.loc = _to_var(loc)        # [D]
        self.scale = _to_var(scale)    # [D, D] diagonal matrix

    def _diag(self):
        # extract the diagonal via elementwise mask (eye)
        D = int(self.scale.shape[0])
        eye = T.assign(np.eye(D, dtype=np.float32))
        return M.reduce_sum(M.elementwise_mul(self.scale, eye), dim=[-1])

    def entropy(self):
        """entropy = 0.5*(k*(1+log 2pi) + log det(scale)); scale is the
        diagonal COVARIANCE matrix (reference :635 and its documented
        examples: diag [0.4, 0.5] -> 2.033158)."""
        D = int(self.scale.shape[0])
        c = 0.5 * D * (1.0 + math.log(2.0 * math.pi))
        logdet = M.reduce_sum(_L().log(self._diag()))
        return M.elementwise_add(
            T.fill_constant([1], "float32", c),
            M.scale(logdet, 0.5))

    def kl_divergence(self, other):
        """KL between diagonal Gaussians (reference :645); the diagonal
        entries of scale are used as variances directly (the reference's
        _inv(other.scale) * self.scale trace term)."""
        s1 = self._diag()
        s2 = other._diag()
        d = M.elementwise_sub(self.loc, other.loc)
        quad = M.elementwise_div(M.elementwise_mul(d, d), s2)
        ratio = M.elementwise_div(s1, s2)
        D = int(self.scale.shape[0])
        return M.scale(
            M.elementwise_sub(
                M.reduce_sum(M.elementwise_add(ratio, quad)),
                M.elementwise_add(
                    T.fill_constant([1], "float32", float(D)),
                    M.reduce_sum(_L().log(ratio)))),
            0.5)
