"""LayerHelper: the op-emitting workhorse behind every layer function
(reference: python/paddle/fluid/layer_helper.py:42 `append_op`)."""
from ..framework import unique_name
from ..framework.core import default_main_program, default_startup_program
from ..framework import initializer as init_mod
from ..param_attr import ParamAttr


def _dygraph_io(io):
    """{slot: VarBase | [VarBase]} -> {slot: [VarBase]}, dropping Nones."""
    out = {}
    for slot, vals in (io or {}).items():
        if vals is None:
            continue
        if not isinstance(vals, (list, tuple)):
            vals = [vals]
        vals = [v for v in vals if v is not None]
        if vals:
            out[slot] = vals
    return out


class LayerHelper:
    def __init__(self, layer_type, **kwargs):
        self.kwargs = kwargs
        self.layer_type = layer_type
        name = kwargs.get("name")
        self.name = name or unique_name.generate(layer_type)

    @property
    def main_program(self):
        return default_main_program()

    @property
    def startup_program(self):
        return default_startup_program()

    @property
    def block(self):
        return self.main_program.current_block()

    @property
    def param_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("param_attr"))

    @property
    def bias_attr(self):
        return ParamAttr._to_attr(self.kwargs.get("bias_attr"))

    def create_variable_for_type_inference(self, dtype, stop_gradient=False):
        from ..dygraph import base as dy
        if dy.enabled():
            import numpy as np
            from ..framework.dtype import np_dtype, convert_dtype
            return dy.VarBase(
                np.zeros((), np_dtype(convert_dtype(dtype))),
                name=unique_name.generate(f"{self.name}.tmp"),
                stop_gradient=stop_gradient)
        return self.block.create_var(
            name=unique_name.generate(f"{self.name}.tmp"),
            dtype=dtype, stop_gradient=stop_gradient)

    def create_variable(self, *args, **kwargs):
        return self.block.create_var(*args, **kwargs)

    def create_parameter(self, attr, shape, dtype="float32", is_bias=False,
                         default_initializer=None, dist_attr=None):
        from ..dygraph import base as dy
        if dy.enabled():
            raise RuntimeError(
                f"fluid.layers.{self.layer_type} creates parameters and "
                f"cannot run in dygraph mode — use the equivalent "
                f"fluid.dygraph.nn Layer class instead")
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        name = attr.name or unique_name.generate(
            f"{self.name}.b" if is_bias else f"{self.name}.w")
        initializer = attr.initializer or default_initializer
        if initializer is None:
            initializer = (init_mod._global_bias_initializer() if is_bias
                           else init_mod._global_weight_initializer())
        param = self.block.create_parameter(
            name=name, shape=shape, dtype=dtype,
            initializer=initializer, regularizer=attr.regularizer,
            trainable=attr.trainable,
            do_model_average=attr.do_model_average,
            need_clip=attr.need_clip,
            learning_rate=attr.learning_rate)
        if dist_attr is not None:
            param.dist_attr = tuple(dist_attr)
        # emit init op into the startup program
        initializer(param)
        return param

    def create_global_variable(self, shape, dtype, persistable=True,
                               name=None, stop_gradient=True,
                               initializer=None):
        gblock = self.main_program.global_block()
        name = name or unique_name.generate(f"{self.name}.global")
        var = gblock.create_var(name=name, shape=shape, dtype=dtype,
                                persistable=persistable,
                                stop_gradient=stop_gradient)
        if initializer is not None:
            initializer(var)
        return var

    def append_op(self, **kwargs):
        from ..dygraph import base as dy
        if dy.enabled():
            tracer = dy._current_tracer()
            ins = _dygraph_io(kwargs.get("inputs"))
            outs = _dygraph_io(kwargs.get("outputs"))
            tracer.trace_op(kwargs["type"], ins, outs,
                            kwargs.get("attrs"))
            return None
        return self.block.append_op(**kwargs)

    def append_activation(self, out_var, act=None):
        act = act or self.kwargs.get("act")
        if act is None:
            return out_var
        if isinstance(act, str):
            act = {"type": act}
        act_type = act.pop("type")
        tmp = self.create_variable_for_type_inference(dtype=out_var.dtype)
        self.append_op(type=act_type, inputs={"X": [out_var]},
                       outputs={"Out": [tmp]}, attrs=act)
        return tmp

    def append_bias_op(self, input_var, dim_start=1, dim_end=None):
        bias_attr = self.bias_attr
        if bias_attr is False:
            return input_var
        size = list(input_var.shape[dim_start:dim_end])
        bias = self.create_parameter(bias_attr, shape=size,
                                     dtype=input_var.dtype, is_bias=True)
        tmp = self.create_variable_for_type_inference(dtype=input_var.dtype)
        self.append_op(type="elementwise_add",
                       inputs={"X": [input_var], "Y": [bias]},
                       outputs={"Out": [tmp]}, attrs={"axis": dim_start})
        return tmp
