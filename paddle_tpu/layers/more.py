"""Remaining fluid.layers surface: thin wrappers over registered ops plus
small composites (reference python/paddle/fluid/layers/{nn,detection,
tensor,loss}.py signatures). Everything here emits ops through
LayerHelper so both static programs and the eager tracer work."""
import numpy as np

from .layer_helper import LayerHelper
from . import math as M
from . import tensor as T
from . import loss as L


def _single(op_type, ins, attrs, dtype, out_slot="Out", name=None,
            infer_shape=False, shape=None, stop_gradient=False):
    helper = LayerHelper(op_type, name=name)
    out = helper.create_variable_for_type_inference(
        dtype, stop_gradient=stop_gradient)
    helper.append_op(type=op_type, inputs=ins, attrs=attrs or {},
                     outputs={out_slot: [out]}, infer_shape=infer_shape)
    if shape is not None and getattr(out, "shape", None) in (None, ()):
        out.shape = tuple(shape)
    return out


def _multi(op_type, ins, attrs, outs_spec, name=None, infer_shape=False):
    """outs_spec: [(slot, dtype)] -> tuple of vars in that order."""
    helper = LayerHelper(op_type, name=name)
    outs = {s: [helper.create_variable_for_type_inference(d)]
            for s, d in outs_spec}
    helper.append_op(type=op_type, inputs=ins, attrs=attrs or {},
                     outputs=outs, infer_shape=infer_shape)
    vals = tuple(outs[s][0] for s, _ in outs_spec)
    return vals if len(vals) > 1 else vals[0]


# --------------------------------------------------------------- RNN API

def dynamic_lstm(input, size, h_0=None, c_0=None, param_attr=None,
                 bias_attr=None, use_peepholes=True, is_reverse=False,
                 gate_activation="sigmoid", cell_activation="tanh",
                 candidate_activation="tanh", length=None, name=None):
    """reference layers/nn.py dynamic_lstm -> lstm op. input [B, T, 4H]
    (pre-projected); size = 4H. Returns (hidden, cell) [B, T, H]."""
    H = size // 4
    helper = LayerHelper("dynamic_lstm", name=name,
                         param_attr=param_attr, bias_attr=bias_attr)
    weight = helper.create_parameter(param_attr, [H, 4 * H], input.dtype)
    bias_w = 7 * H if use_peepholes else 4 * H
    bias = helper.create_parameter(bias_attr, [1, bias_w], input.dtype,
                                   is_bias=True)
    ins = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    if length is not None:
        ins["Length"] = [length]
    hidden, cell = _multi(
        "lstm", ins,
        {"use_peepholes": use_peepholes, "is_reverse": is_reverse,
         "gate_activation": gate_activation,
         "cell_activation": cell_activation,
         "candidate_activation": candidate_activation},
        [("Hidden", input.dtype), ("Cell", input.dtype)], name=name)
    B, Tm = input.shape[0], input.shape[1]
    for v in (hidden, cell):
        v.shape = (B, Tm, H)
    return hidden, cell


def dynamic_lstmp(input, size, proj_size, h_0=None, c_0=None,
                  param_attr=None, bias_attr=None, use_peepholes=True,
                  is_reverse=False, gate_activation="sigmoid",
                  cell_activation="tanh", candidate_activation="tanh",
                  proj_activation="tanh", length=None, name=None):
    """reference dynamic_lstmp -> lstmp op. Returns (projection, cell).
    use_peepholes=True (the reference default) sizes Bias [1, 7H] with the
    peephole diagonals in columns 4H:7H."""
    H = size // 4
    helper = LayerHelper("dynamic_lstmp", name=name,
                         param_attr=param_attr, bias_attr=bias_attr)
    weight = helper.create_parameter(param_attr, [proj_size, 4 * H],
                                     input.dtype)
    proj_w = helper.create_parameter(param_attr, [H, proj_size],
                                     input.dtype)
    bias_w = 7 * H if use_peepholes else 4 * H
    bias = helper.create_parameter(bias_attr, [1, bias_w], input.dtype,
                                   is_bias=True)
    ins = {"Input": [input], "Weight": [weight], "ProjWeight": [proj_w],
           "Bias": [bias]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    if c_0 is not None:
        ins["C0"] = [c_0]
    if length is not None:
        ins["Length"] = [length]
    proj, cell = _multi(
        "lstmp", ins,
        {"use_peepholes": use_peepholes, "is_reverse": is_reverse,
         "gate_activation": gate_activation,
         "cell_activation": cell_activation,
         "candidate_activation": candidate_activation,
         "proj_activation": proj_activation},
        [("Projection", input.dtype), ("Cell", input.dtype)], name=name)
    B, Tm = input.shape[0], input.shape[1]
    proj.shape = (B, Tm, proj_size)
    cell.shape = (B, Tm, H)
    return proj, cell


def dynamic_gru(input, size, h_0=None, param_attr=None, bias_attr=None,
                is_reverse=False, gate_activation="sigmoid",
                candidate_activation="tanh", origin_mode=False,
                length=None, name=None):
    """reference dynamic_gru -> gru op. input [B, T, 3H]; size = H."""
    helper = LayerHelper("dynamic_gru", name=name,
                         param_attr=param_attr, bias_attr=bias_attr)
    weight = helper.create_parameter(param_attr, [size, 3 * size],
                                     input.dtype)
    bias = helper.create_parameter(bias_attr, [1, 3 * size], input.dtype,
                                   is_bias=True)
    ins = {"Input": [input], "Weight": [weight], "Bias": [bias]}
    if h_0 is not None:
        ins["H0"] = [h_0]
    if length is not None:
        ins["Length"] = [length]
    out = _single("gru", ins,
                  {"is_reverse": is_reverse, "origin_mode": origin_mode,
                   "gate_activation": gate_activation,
                   "activation": candidate_activation},
                  input.dtype, out_slot="Hidden", name=name)
    out.shape = (input.shape[0], input.shape[1], size)
    return out


def lstm(input, init_h, init_c, max_len, hidden_size, num_layers=1,
         dropout_prob=0.0, is_bidirec=False, is_test=False, name=None,
         default_initializer=None, seed=-1):
    """reference layers/nn.py lstm (the cudnn_lstm front). This build maps
    it onto stacked `lstm` ops with an in-graph input projection per
    layer/direction (same capability; the cudnn packed-weight blob is a
    GPU-only artifact). Returns (out [B,T,H*dirs], last_h, last_c) where
    the last states are the FINAL layer's last valid step, shaped
    [1, B, H*dirs] (the reference stacks all layers — documented
    divergence)."""
    from . import nn as nn_mod
    x = input
    dirs = 2 if is_bidirec else 1
    Tm = input.shape[1]

    def _at(v, t):
        sl = T.slice(v, axes=[1], starts=[t], ends=[t + 1])
        return T.transpose(sl, [1, 0, 2])          # [1, B, H]

    last_h = last_c = None
    for layer in range(num_layers):
        per_dir, last_hs, last_cs = [], [], []
        for d in range(dirs):
            proj = nn_mod.fc(x, 4 * hidden_size, num_flatten_dims=2,
                             bias_attr=False)
            hidden, cell = dynamic_lstm(
                proj, 4 * hidden_size, use_peepholes=False,
                is_reverse=(d == 1))
            per_dir.append(hidden)
            # the reverse direction processes t=Tm-1 FIRST; its final
            # state lives at t=0
            t_last = 0 if d == 1 else Tm - 1
            last_hs.append(_at(hidden, t_last))
            last_cs.append(_at(cell, t_last))
        x = per_dir[0] if dirs == 1 else T.concat(per_dir, axis=2)
        last_h = last_hs[0] if dirs == 1 else T.concat(last_hs, axis=2)
        last_c = last_cs[0] if dirs == 1 else T.concat(last_cs, axis=2)
        if dropout_prob and not is_test and layer < num_layers - 1:
            # cudnn semantics: dropout BETWEEN layers, never after the top
            x = nn_mod.dropout(x, dropout_prob)
    return x, last_h, last_c


def row_conv(input, future_context_size, param_attr=None, act=None,
             length=None, name=None):
    helper = LayerHelper("row_conv", name=name, param_attr=param_attr)
    filt = helper.create_parameter(
        param_attr, [future_context_size, input.shape[-1]], input.dtype)
    ins = {"X": [input], "Filter": [filt]}
    if length is not None:
        ins["Length"] = [length]
    out = _single("row_conv", ins, {}, input.dtype, name=name,
                  shape=input.shape)
    return helper.append_activation(out, act)


# ---------------------------------------------------- vision / sampling

def affine_grid(theta, out_shape, name=None):
    return _single("affine_grid", {"Theta": [theta]},
                   {"output_shape": list(out_shape)}, theta.dtype,
                   out_slot="Output", name=name)


def grid_sampler(x, grid, name=None):
    return _single("grid_sampler", {"X": [x], "Grid": [grid]}, {},
                   x.dtype, name=name, shape=x.shape)


def deformable_conv(input, offset, mask, num_filters, filter_size,
                    stride=1, padding=0, dilation=1, groups=1,
                    deformable_groups=1, im2col_step=1, param_attr=None,
                    bias_attr=None, modulated=True, name=None):
    """reference layers/nn.py deformable_conv."""
    helper = LayerHelper("deformable_conv", name=name,
                         param_attr=param_attr, bias_attr=bias_attr)
    k = filter_size if isinstance(filter_size, (list, tuple)) else \
        (filter_size, filter_size)
    cin = input.shape[1]
    w = helper.create_parameter(
        param_attr, [num_filters, cin // groups, k[0], k[1]], input.dtype)
    ins = {"Input": [input], "Offset": [offset], "Filter": [w]}
    op = "deformable_conv" if modulated else "deformable_conv_v1"
    if modulated:
        ins["Mask"] = [mask]
    two = lambda v: list(v) if isinstance(v, (list, tuple)) else [v, v]
    out = _single(op, ins,
                  {"strides": two(stride), "paddings": two(padding),
                   "dilations": two(dilation), "groups": groups,
                   "deformable_groups": deformable_groups},
                  input.dtype, out_slot="Output", name=name)
    bias = helper.create_parameter(bias_attr, [num_filters], input.dtype,
                                   is_bias=True)
    if bias is not None:
        out = M.elementwise_add(out, T.reshape(bias, [1, -1, 1, 1]))
    return out


def psroi_pool(input, rois, output_channels, spatial_scale,
               pooled_height, pooled_width, rois_batch=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if rois_batch is not None:
        ins["RoisBatch"] = [rois_batch]
    return _single("psroi_pool", ins,
                   {"output_channels": output_channels,
                    "spatial_scale": spatial_scale,
                    "pooled_height": pooled_height,
                    "pooled_width": pooled_width}, input.dtype, name=name)


def prroi_pool(input, rois, spatial_scale=1.0, pooled_height=1,
               pooled_width=1, rois_batch=None, name=None):
    ins = {"X": [input], "ROIs": [rois]}
    if rois_batch is not None:
        ins["RoisBatch"] = [rois_batch]
    return _single("prroi_pool", ins,
                   {"spatial_scale": spatial_scale,
                    "pooled_height": pooled_height,
                    "pooled_width": pooled_width}, input.dtype, name=name)


def yolov3_loss(x, gt_box, gt_label, anchors, anchor_mask, class_num,
                ignore_thresh, downsample_ratio, gt_score=None,
                use_label_smooth=True, name=None, gt_count=None):
    ins = {"X": [x], "GTBox": [gt_box], "GTLabel": [gt_label]}
    if gt_count is not None:
        ins["GTCount"] = [gt_count]
    return _single("yolov3_loss", ins,
                   {"anchors": list(anchors),
                    "anchor_mask": list(anchor_mask),
                    "class_num": class_num,
                    "ignore_thresh": ignore_thresh,
                    "downsample_ratio": downsample_ratio},
                   x.dtype, out_slot="Loss", name=name)


def im2sequence(input, filter_size=1, stride=1, padding=0, name=None):
    two = lambda v: list(v) if isinstance(v, (list, tuple)) else [v, v]
    return _multi("im2sequence", {"X": [input]},
                  {"kernels": two(filter_size), "strides": two(stride),
                   "paddings": two(padding) * 2},
                  [("Out", input.dtype), ("OutLength", "int32")],
                  name=name)


def pool3d(input, pool_size=-1, pool_type="max", pool_stride=1,
           pool_padding=0, global_pooling=False, ceil_mode=False,
           exclusive=True, name=None):
    three = lambda v: list(v) if isinstance(v, (list, tuple)) else [v] * 3
    return _single("pool3d", {"X": [input]},
                   {"ksize": three(pool_size), "pooling_type": pool_type,
                    "strides": three(pool_stride),
                    "paddings": three(pool_padding),
                    "global_pooling": global_pooling,
                    "exclusive": exclusive}, input.dtype, name=name)


def adaptive_pool3d(input, pool_size, pool_type="max", name=None):
    three = lambda v: list(v) if isinstance(v, (list, tuple)) else [v] * 3
    return _single("pool3d", {"X": [input]},
                   {"ksize": three(pool_size), "pooling_type": pool_type,
                    "adaptive": True}, input.dtype, name=name)


def random_crop(x, shape, seed=0, name=None):
    return _single("random_crop", {"X": [x]},
                   {"shape": list(shape), "seed": int(seed)}, x.dtype,
                   name=name)


# -------------------------------------------------------------- losses

def cos_sim(X, Y, name=None):
    return _single("cos_sim", {"X": [X], "Y": [Y]}, {}, X.dtype,
                   name=name)


def dice_loss(input, label, epsilon=1e-5, name=None):
    """reference layers/nn.py dice_loss (nn.py:6870): one_hot the integer
    label to input's class dim, per-SAMPLE dice over all non-batch dims,
    mean over the batch."""
    from . import nn as nn_mod
    lbl = T.one_hot(nn_mod.squeeze(T.cast(label, "int64"), axes=[-1]),
                    depth=input.shape[-1]) \
        if int(label.shape[-1]) == 1 else T.cast(label, input.dtype)
    lbl = T.cast(lbl, input.dtype)
    dims = list(range(1, len(input.shape)))
    inse = M.reduce_sum(M.elementwise_mul(input, lbl), dim=dims)
    denom = M.elementwise_add(M.reduce_sum(input, dim=dims),
                              M.reduce_sum(lbl, dim=dims))
    dice = M.elementwise_div(
        M.scale(inse, 2.0), M.scale(denom, 1.0, bias=float(epsilon)))
    return M.reduce_mean(M.scale(dice, -1.0, bias=1.0))


def npair_loss(anchor, positive, labels, l2_reg=0.002, name=None):
    """reference layers/loss.py:1618 npair_loss: l2loss * Beta(0.25) *
    l2_reg + reduce_mean(reduce_sum(labels * softmax_ce, 0))."""
    from . import nn as nn_mod
    sim = nn_mod.matmul(anchor, positive, transpose_y=True)
    lbl = T.reshape(labels, [-1, 1])
    same = T.cast(M.equal(lbl, T.transpose(lbl, [1, 0])), anchor.dtype)
    tgt = M.elementwise_div(
        same, M.reduce_sum(same, dim=[1], keep_dim=True))
    ce = L.softmax_with_cross_entropy(sim, tgt, soft_label=True)
    celoss = M.reduce_mean(
        M.reduce_sum(M.elementwise_mul(tgt, ce), dim=[0]))
    l2loss = M.scale(M.elementwise_add(
        M.reduce_mean(M.reduce_sum(M.elementwise_mul(anchor, anchor),
                                   dim=[1])),
        M.reduce_mean(M.reduce_sum(M.elementwise_mul(positive, positive),
                                   dim=[1]))), 0.25 * float(l2_reg))
    return M.elementwise_add(celoss, l2loss)


def rank_loss(label, left, right, name=None):
    return _single("rank_loss",
                   {"Label": [label], "Left": [left], "Right": [right]},
                   {}, left.dtype, name=name)


def margin_rank_loss(label, left, right, margin=0.1, name=None):
    return _single("margin_rank_loss",
                   {"Label": [label], "X1": [left], "X2": [right]},
                   {"margin": float(margin)}, left.dtype, name=name)


def bpr_loss(input, label, name=None):
    return _single("bpr_loss", {"X": [input], "Label": [label]}, {},
                   input.dtype, name=name)


def sigmoid_focal_loss(x, label, fg_num, gamma=2.0, alpha=0.25,
                       name=None):
    return _single("sigmoid_focal_loss",
                   {"X": [x], "Label": [label], "FgNum": [fg_num]},
                   {"gamma": float(gamma), "alpha": float(alpha)},
                   x.dtype, name=name)


def teacher_student_sigmoid_loss(input, label,
                                 soft_max_up_bound=15.0,
                                 soft_max_lower_bound=-15.0):
    """reference loss.py teacher_student_sigmoid_loss (composite):
    z = clip(x); loss = log(1 + exp(z)) - z * label... using the stable
    softplus form."""
    from . import nn as nn_mod
    z = nn_mod.clip(input, soft_max_lower_bound, soft_max_up_bound)
    softplus = nn_mod.softplus(z)
    return M.elementwise_sub(softplus,
                             M.elementwise_mul(z, T.cast(label, z.dtype)))


def center_loss(input, label, num_classes, alpha, param_attr=None,
                update_center=True, name=None):
    helper = LayerHelper("center_loss", name=name, param_attr=param_attr)
    centers = helper.create_parameter(
        param_attr, [num_classes, input.shape[-1]], input.dtype)
    centers.stop_gradient = True
    rate = T.fill_constant([1], "float32", float(alpha))
    loss = helper.create_variable_for_type_inference(input.dtype)
    diff = helper.create_variable_for_type_inference(input.dtype)
    # CentersOut aliases the centers parameter (reference loss.py:141 wires
    # 'CentersOut': [centers_param]) so the in-place center update persists,
    # matching the batch_norm MeanOut/VarianceOut pattern above.
    helper.append_op(
        type="center_loss",
        inputs={"X": [input], "Label": [label], "Centers": [centers],
                "CenterUpdateRate": [rate]},
        outputs={"Loss": [loss], "SampleCenterDiff": [diff],
                 "CentersOut": [centers]},
        attrs={"need_update": update_center})
    return loss


def cross_entropy2(input, label, ignore_index=-100):
    return _multi("cross_entropy2", {"X": [input], "Label": [label]},
                  {"ignore_index": ignore_index},
                  [("Y", input.dtype), ("MatchX", input.dtype)])[0]


# ------------------------------------------------------- decode / metric

def edit_distance(input, label, normalized=True, input_length=None,
                  label_length=None, name=None):
    ins = {"Hyps": [input], "Refs": [label]}
    if input_length is not None:
        ins["HypsLength"] = [input_length]
    if label_length is not None:
        ins["RefsLength"] = [label_length]
    return _multi("edit_distance", ins, {"normalized": normalized},
                  [("Out", "float32"), ("SequenceNum", "int32")],
                  name=name)


def ctc_greedy_decoder(input, blank, input_length=None, name=None):
    """reference ctc_greedy_decoder: argmax over classes then ctc_align
    (collapse repeats, drop blanks)."""
    ids = T.cast(T.argmax(input, axis=-1), "int32")
    ins = {"X": [ids]}
    if input_length is not None:
        ins["Length"] = [input_length]
    else:
        B, Tm = input.shape[0], input.shape[1]
        ins["Length"] = [T.fill_constant([B], "int32", Tm)]
    return _multi("ctc_align", ins, {"blank": blank},
                  [("Output", "int32"), ("OutputLength", "int32")],
                  name=name)


def linear_chain_crf(input, label, param_attr=None, length=None,
                     name=None):
    """reference layers/nn.py linear_chain_crf -> per-sequence negative
    log-likelihood [B, 1]. The Transition parameter ([C+2, C]: start row,
    stop row, pairwise rows) is shared with crf_decoding via param_attr
    name."""
    helper = LayerHelper("linear_chain_crf", name=name,
                         param_attr=param_attr)
    transition = helper.create_parameter(
        param_attr, [input.shape[-1] + 2, input.shape[-1]], input.dtype)
    ins = {"Emission": [input], "Transition": [transition],
           "Label": [label]}
    if length is not None:
        ins["Length"] = [length]
    out = _single("linear_chain_crf", ins, {}, input.dtype,
                  out_slot="LogLikelihood", name=name)
    out.shape = (input.shape[0], 1)
    return out


def crf_decoding(input, param_attr, label=None, length=None, name=None):
    helper = LayerHelper("crf_decoding", name=name, param_attr=param_attr)
    # reuse the SAME transition parameter as linear_chain_crf by name
    transition = helper.create_parameter(
        param_attr, [input.shape[-1] + 2, input.shape[-1]], input.dtype)
    ins = {"Emission": [input], "Transition": [transition]}
    if label is not None:
        ins["Label"] = [label]
    if length is not None:
        ins["Length"] = [length]
    return _single("crf_decoding", ins, {}, "int32",
                   out_slot="ViterbiPath", name=name, stop_gradient=True)


def mean_iou(input, label, num_classes):
    return _multi("mean_iou",
                  {"Predictions": [input], "Labels": [label]},
                  {"num_classes": num_classes},
                  [("OutMeanIou", "float32"), ("OutWrong", "int32"),
                   ("OutCorrect", "int32")])


def hsigmoid(input, label, num_classes, param_attr=None, bias_attr=None,
             name=None):
    helper = LayerHelper("hsigmoid", name=name, param_attr=param_attr,
                         bias_attr=bias_attr)
    w = helper.create_parameter(param_attr,
                                [num_classes - 1, input.shape[-1]],
                                input.dtype)
    bias = helper.create_parameter(bias_attr, [num_classes - 1],
                                   input.dtype, is_bias=True)
    ins = {"X": [input], "W": [w], "Label": [label]}
    if bias is not None:
        ins["Bias"] = [bias]
    return _single("hsigmoid", ins, {"num_classes": num_classes},
                   input.dtype, name=name)


def bipartite_match(dist_matrix, match_type=None, dist_threshold=None,
                    name=None):
    return _multi("bipartite_match", {"DistMat": [dist_matrix]}, {},
                  [("ColToRowMatchIndices", "int32"),
                   ("ColToRowMatchDist", "float32")], name=name)


def sampling_id(x, min=0.0, max=1.0, seed=0, dtype="float32"):
    return _single("sampling_id", {"X": [x]}, {"seed": int(seed)},
                   "int32", stop_gradient=True)


def shard_index(input, index_num, nshards, shard_id, ignore_value=-1):
    return _single("shard_index", {"X": [input]},
                   {"index_num": index_num, "nshards": nshards,
                    "shard_id": shard_id, "ignore_value": ignore_value},
                   input.dtype, stop_gradient=True)


def hash(input, hash_size, num_hash=1, name=None):
    return _single("hash", {"X": [input]},
                   {"mod_by": hash_size, "num_hash": num_hash}, "int32",
                   name=name, stop_gradient=True)


# ------------------------------------------------------ tensor utility

def eye(num_rows, num_columns=None, batch_shape=None, dtype="float32",
        name=None):
    out = _single("eye", {}, {"num_rows": num_rows,
                              "num_columns": num_columns or -1,
                              "dtype": dtype}, dtype,
                  stop_gradient=True)
    out.shape = (num_rows, num_columns or num_rows)
    if batch_shape:
        for _ in batch_shape:
            from . import nn as nn_mod
            out = nn_mod.unsqueeze(out, axes=[0])
        out = T.expand(out, list(batch_shape) + [1, 1])
    return out


def size(input):
    return _single("size", {"Input": [input]}, {}, "int32",
                   stop_gradient=True)


def rank(input):
    return T.fill_constant([1], "int32", len(input.shape or ()))


def _isnan(x):
    return _single("isnan_v2", {"X": [x]}, {}, "bool",
                   stop_gradient=True, shape=x.shape)


def _isfinite_elem(x):
    return _single("isfinite_v2", {"X": [x]}, {}, "bool",
                   stop_gradient=True, shape=x.shape)


def has_nan(x):
    return M.reduce_any(_isnan(x))


def has_inf(x):
    # inf = not finite and not nan
    bad = M.logical_and(M.logical_not(_isfinite_elem(x)),
                        M.logical_not(_isnan(x)))
    return M.reduce_any(bad)


def isfinite(x):
    return M.reduce_all(_isfinite_elem(x))


def add_position_encoding(input, alpha=1.0, beta=1.0, name=None):
    return _single("add_position_encoding", {"X": [input]},
                   {"alpha": float(alpha), "beta": float(beta)},
                   input.dtype, name=name, shape=input.shape)


def bilinear_tensor_product(x, y, size, act=None, name=None,
                            param_attr=None, bias_attr=None):
    helper = LayerHelper("bilinear_tensor_product", name=name,
                         param_attr=param_attr, bias_attr=bias_attr)
    w = helper.create_parameter(
        param_attr, [size, x.shape[-1], y.shape[-1]], x.dtype)
    bias = helper.create_parameter(bias_attr, [1, size], x.dtype,
                                   is_bias=True)
    ins = {"X": [x], "Y": [y], "Weight": [w]}
    if bias is not None:
        ins["Bias"] = [bias]
    out = _single("bilinear_tensor_product", ins, {}, x.dtype, name=name)
    return helper.append_activation(out, act)


def box_clip(input, im_info, name=None):
    return _single("box_clip", {"Input": [input], "ImInfo": [im_info]},
                   {}, input.dtype, out_slot="Output", name=name,
                   shape=input.shape)


def polygon_box_transform(input, name=None):
    return _single("polygon_box_transform", {"X": [input]}, {},
                   input.dtype, out_slot="Output", name=name,
                   shape=input.shape)


def scatter_nd(index, updates, shape, name=None):
    return _single("scatter_nd", {"Index": [index], "Updates": [updates]},
                   {"shape": list(shape)}, updates.dtype, name=name,
                   shape=shape)


def soft_relu(x, threshold=40.0, name=None):
    return _single("soft_relu", {"X": [x]},
                   {"threshold": float(threshold)}, x.dtype, name=name,
                   shape=x.shape)


def custom_op(op_type, inputs=None, attrs=None, outputs=None, name=None):
    """Emit any registered op — including user ops loaded with
    ``fluid.load_op_library`` — into the current program (static graph)
    or the eager tracer (dygraph).

    The generic layers wrapper of the custom-op story (the reference's
    equivalent is writing a python wrapper over a loaded .so op —
    tests/custom_op/test_custom_op.py); here one function serves every
    op because the registry carries build-time shape inference.

    inputs: {slot: Variable | [Variables]}; outputs: {slot: count}
    (default {"Out": 1}) or {slot: (count, dtype)} — dtype defaults to
    the first input's. Returns one Variable, a list (count > 1), or a
    dict when multiple output slots are requested."""
    from ..framework.registry import has_op
    if not has_op(op_type):
        raise NotImplementedError(
            f"custom_op: op {op_type!r} is not registered — register it "
            f"with paddle_tpu.register_op or load its module via "
            f"paddle_tpu.load_op_library")
    helper = LayerHelper(op_type, name=name)
    ins = {}
    first_dtype = None
    for slot, vs in (inputs or {}).items():
        vs = list(vs) if isinstance(vs, (list, tuple)) else [vs]
        if vs and first_dtype is None:
            first_dtype = getattr(vs[0], "dtype", None)
        ins[slot] = vs
    first_dtype = first_dtype or "float32"
    out_spec = outputs or {"Out": 1}
    out_vars = {}
    for slot, spec in out_spec.items():
        n, dt = spec if isinstance(spec, (list, tuple)) else (spec,
                                                              first_dtype)
        out_vars[slot] = [helper.create_variable_for_type_inference(dt)
                          for _ in range(int(n))]
    helper.append_op(type=op_type, inputs=ins, attrs=attrs or {},
                     outputs=out_vars)
    if list(out_spec) == ["Out"]:
        vals = out_vars["Out"]
        return vals[0] if len(vals) == 1 else vals
    return out_vars


def while_loop(cond, body, loop_vars, is_test=False, name=None):
    """reference layers/control_flow.py while_loop: functional While."""
    from .control_flow import While
    from . import tensor as T_

    c = cond(*loop_vars)
    w = While(c)
    vars_ = list(loop_vars)
    with w.block():
        new_vars = body(*vars_)
        if not isinstance(new_vars, (list, tuple)):
            new_vars = [new_vars]
        for old, new in zip(vars_, new_vars):
            T_.assign(new, output=old)
        T_.assign(cond(*vars_), output=c)
    return vars_
