"""Python backing for the C-native training entry (capi/).

Capability parity with the reference's C++ train path: demo_trainer.cc
loads a ProgramDesc pair saved from Python, runs the startup program
once, then drives the executor loop feeding tensors and fetching the
loss with no Python anywhere in the loop
(/root/reference/paddle/fluid/train/demo/demo_trainer.cc:63 — LoadProgram
+ Executor::Run; the C wrapper is framework/c/c_api.cc). Here the same
contract holds at the C ABI: `capi/paddle_c_api.h` PD_Trainer* fronts
this session object; the compute is the XLA-compiled step either way.

Save side (from a Python build script, the reference's
`save_checkpoint`/program-serialization step):

    fluid.capi_train.save_train_model(dirname, main, startup)

writes `main_program.json` + `startup_program.json` (Program.to_dict
IR). The C program then owns the whole training run.
"""
import json
import os

import numpy as np


def save_train_model(dirname, main_program=None, startup_program=None,
                     fetch_vars=None):
    """Serialize a trainable (main, startup) program pair for the C
    trainer. The main program must already contain the optimizer ops
    (minimize() called) — the C side only feeds and steps.

    `fetch_vars` maps stable C-side aliases to Variables (or names), so
    C code can fetch "loss" regardless of the auto-generated var name."""
    from .framework.core import default_main_program, \
        default_startup_program
    main = main_program or default_main_program()
    startup = startup_program or default_startup_program()
    os.makedirs(dirname, exist_ok=True)
    for stem, prog in (("main_program", main),
                       ("startup_program", startup)):
        with open(os.path.join(dirname, stem + ".json"), "w") as f:
            json.dump(prog.to_dict(), f)
    aliases = {alias: getattr(v, "name", v)
               for alias, v in (fetch_vars or {}).items()}
    with open(os.path.join(dirname, "fetch_map.json"), "w") as f:
        json.dump(aliases, f)


class CTrainerSession:
    """One training session driven from C: owns program, scope, executor.

    The C shim calls: feed(name, array) for each input, then
    run_step(fetch_name) -> float32 ndarray. Matches the reference
    demo_trainer loop (feed_targets/fetch_targets + Executor::Run)."""

    def __init__(self, model_dir):
        import paddle_tpu as fluid
        from .framework.core import Program

        def _load(stem):
            path = os.path.join(model_dir, stem + ".json")
            if not os.path.exists(path):
                raise FileNotFoundError(
                    f"{path} not found — save the train model with "
                    f"paddle_tpu.capi_train.save_train_model(dirname)")
            with open(path) as f:
                return Program.from_dict(json.load(f))

        self.main = _load("main_program")
        self.startup = _load("startup_program")
        self._fetch_map = {}
        fm = os.path.join(model_dir, "fetch_map.json")
        if os.path.exists(fm):
            with open(fm) as f:
                self._fetch_map = json.load(f)
        self.scope = fluid.Scope()
        self.exe = fluid.Executor()
        self._guard = fluid
        with fluid.scope_guard(self.scope):
            self.exe.run(self.startup)
        self._feeds = {}

    def feed(self, name, arr):
        self._feeds[name] = np.asarray(arr)

    def run_step(self, fetch_name):
        name = self._fetch_map.get(fetch_name, fetch_name)
        with self._guard.scope_guard(self.scope):
            out, = self.exe.run(self.main, feed=dict(self._feeds),
                                fetch_list=[name])
        return np.ascontiguousarray(np.asarray(out), dtype=np.float32)

    def save_params(self, model_path):
        from . import io
        with self._guard.scope_guard(self.scope):
            io.save(self.main, model_path, scope=self.scope)

    def load_params(self, model_path):
        from . import io
        with self._guard.scope_guard(self.scope):
            io.load(self.main, model_path, scope=self.scope)
