"""paddle.tensor 2.0-preview namespace (reference python/paddle/tensor/
— DEFINE_ALIAS re-exports over fluid tensor/math functions)."""
from .layers.tensor import (  # noqa: F401
    concat, cast, reshape, transpose, slice, split, stack, unstack,
    gather, argmax, argmin, argsort, assign, fill_constant, zeros, ones,
    zeros_like, ones_like, one_hot, range, linspace, expand, shape,
    gather_nd, where, diag,
)
from .layers.nn import squeeze, flatten  # noqa: F401
from .layers.math import (  # noqa: F401
    elementwise_add as add, elementwise_sub as subtract,
    elementwise_mul as multiply, elementwise_div as divide,
    reduce_sum as sum, reduce_mean as mean, reduce_max as max,
    reduce_min as min, reduce_prod as prod, equal, logical_and,
    logical_or, logical_not, scale,
)
from .layers.more import eye, size  # noqa: F401
