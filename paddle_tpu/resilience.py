"""Fault-tolerant training runtime primitives.

The reference Fluid stack survives real fleets with a spread of
mechanisms — gRPC deadline/retry semantics
(/root/reference/paddle/fluid/operators/distributed/grpc/grpc_client.cc,
FLAGS_rpc_deadline / FLAGS_rpc_retry_times), the HeartBeatMonitor
(operators/distributed/heart_beat_monitor.h), checkpoint-notify ops, and
FLAGS_check_nan_inf nan/inf interception (framework/details/
nan_inf_utils_detail.cc). This module centralizes the runtime-neutral
pieces of that story so io.py, distributed/wire.py, distributed/ps.py and
framework/executor.py share one vocabulary:

- typed errors: CheckpointCorruptError, RpcDeadlineError, CircuitOpenError,
  NonFiniteError, WatchdogTimeout
- retry_call(fn, deadline, base_backoff): exponential backoff + jitter
  under a wall-clock deadline
- CircuitBreaker: per-endpoint closed/open/half-open fail-fast gate so a
  dead pserver costs one deadline, not one deadline per call forever
- watchdog(budget)/run_with_watchdog: abort work exceeding a wall-clock
  budget (the host-side analog of a preempted-TPU step that never returns)
- fault_injection(point, ...): test hook arming named failure points that
  production code declares with maybe_fail(point)
- chaos(points, ...): seeded, probabilistic, schedulable fault injection
  across MANY points at once — the serving chaos harness ("The Tail at
  Scale" failure modes on demand: crashes, delays, lost replies)
"""
import random
import threading
import time
import weakref
from contextlib import contextmanager

from .observability.metrics import default_registry as _registry
from .observability.recorder import flight_recorder as _flightrec

_CHAOS_FIRED = _registry().counter(
    "chaos_faults_fired_total",
    "chaos-harness faults actually injected, by armed point",
    labels=("point",), max_series=64)
_BUDGET_EXHAUSTED = _registry().counter(
    "serving_retry_budget_exhausted_total",
    "retries/hedges/failovers refused by the process retry budget, by "
    "consumer",
    labels=("what",), max_series=16)

# every live CircuitBreaker, for the breaker-state metrics collector
_BREAKERS = weakref.WeakSet()
_BREAKER_STATES = {"closed": 0, "half-open": 1, "open": 2}
_BREAKER_SERIES_CAP = 64
# endpoints ever folded past the cap: dropped = len(set) is monotone
# and grows with actual cardinality, not with scrape frequency
_folded_endpoints = set()
_fold_lock = threading.Lock()


def _collect_breakers():
    by_endpoint = {}
    for b in list(_BREAKERS):
        ep = b.endpoint or "unknown"
        st = _BREAKER_STATES.get(b.state, 0)
        by_endpoint[ep] = max(by_endpoint.get(ep, 0), st)
    items = sorted(by_endpoint.items())
    if len(items) > _BREAKER_SERIES_CAP:
        # fold the overflow into one _other series (max state, so an
        # OPEN breaker past the cap still trips dashboards) and feed
        # the fold count to telemetry_series_dropped_total — silent
        # truncation would read as "all breakers closed" mid-outage
        kept = items[:_BREAKER_SERIES_CAP - 1]
        overflow = items[_BREAKER_SERIES_CAP - 1:]
        kept.append(("_other", max(st for _ep, st in overflow)))
        items = kept
        with _fold_lock:
            _folded_endpoints.update(ep for ep, _st in overflow)
    with _fold_lock:
        dropped = len(_folded_endpoints)
    return [{"name": "resilience_breaker_state", "kind": "gauge",
             "help": "circuit breaker state by endpoint "
                     "(0=closed, 1=half-open, 2=open; max across "
                     "same-endpoint breakers)",
             "labels": ("endpoint",),
             "samples": [((ep,), st) for ep, st in items],
             "dropped": dropped}]


_registry().register_collector(
    _collect_breakers,
    families=[{"name": "resilience_breaker_state", "kind": "gauge",
               "help": "circuit breaker state by endpoint "
                       "(0=closed, 1=half-open, 2=open)",
               "labels": ("endpoint",)}])


# --------------------------------------------------------------------------
# typed errors
# --------------------------------------------------------------------------

class CheckpointCorruptError(RuntimeError):
    """A checkpoint file failed its manifest integrity check (sha256
    mismatch, truncation, or unreadable payload). Carries ``path`` — the
    offending file — so operators know what to delete/re-replicate."""

    def __init__(self, message, path=None):
        super().__init__(message)
        self.path = path


class RpcDeadlineError(ConnectionError):
    """An RPC did not succeed within its wall-clock deadline (reference
    gRPC FLAGS_rpc_deadline semantics). Subclasses ConnectionError so
    existing transport-failure handlers keep working. Carries
    ``endpoint`` and ``elapsed`` (seconds spent retrying)."""

    def __init__(self, message, endpoint=None, elapsed=None):
        super().__init__(message)
        self.endpoint = endpoint
        self.elapsed = elapsed


class CircuitOpenError(RpcDeadlineError):
    """Fail-fast rejection: the endpoint's circuit breaker is open after
    repeated failures, so the call is refused without touching the wire."""


class EnforceNotMet(RuntimeError):
    """Runtime enforcement violation (reference platform/enforce.h
    PADDLE_ENFORCE / fluid.core.EnforceNotMet)."""


class NonFiniteError(EnforceNotMet):
    """FLAGS_check_nan_inf tripped: a fetched output or updated parameter
    contains nan/inf. Carries ``var_name`` (first offender) and ``count``
    (non-finite element count in that tensor)."""

    def __init__(self, message, var_name=None, count=None):
        super().__init__(message)
        self.var_name = var_name
        self.count = count


class WatchdogTimeout(RuntimeError):
    """Work under a watchdog exceeded its wall-clock budget."""


class CheckpointIncompleteError(CheckpointCorruptError):
    """A checkpoint loaded for training resume lacks part of the full
    training state (optimizer slabs, the RNG stream record, ...). Resuming
    from it would SILENTLY diverge from the uninterrupted run — reset
    moments, replayed RNG draws — so the load refuses instead. Carries
    ``missing`` (the absent variable/extra names). Subclasses
    CheckpointCorruptError so existing corrupt-checkpoint handlers treat
    it as an unusable checkpoint."""

    def __init__(self, message, path=None, missing=None):
        super().__init__(message, path=path)
        self.missing = list(missing or [])


class PreemptedError(RuntimeError):
    """The training loop was preempted (SIGTERM/SIGINT or an in-process
    ``train.request_preemption``) and exited at a slab boundary after its
    bounded-deadline fast checkpoint. Carries ``slab``/``step`` (progress
    at exit), ``checkpoint_no`` (the newest durable checkpoint — None
    when the fast save missed its deadline and the previous checkpoint
    stands) and ``reason`` (which trigger fired)."""

    def __init__(self, message, slab=None, step=None, checkpoint_no=None,
                 reason=None):
        super().__init__(message)
        self.slab = slab
        self.step = step
        self.checkpoint_no = checkpoint_no
        self.reason = reason


class RestartBudgetExceeded(RuntimeError):
    """The supervised training loop crashed more times than
    ``FLAGS_train_restart_budget`` allows; the last failure is chained as
    ``__cause__``. Carries ``restarts`` and ``errors`` (the typed error
    names of every restart cause, oldest first)."""

    def __init__(self, message, restarts=None, errors=None):
        super().__init__(message)
        self.restarts = restarts
        self.errors = list(errors or [])


class FaultInjected(RuntimeError):
    """Default exception raised by an armed chaos fault point. Distinct
    from real failure types so a soak can tell injected damage from a
    genuine bug in the recovery machinery."""


class HierarchicalCommsError(RuntimeError):
    """The compiled multi-slice executable FAILED the pre-burn comms
    gate (``observability/comms.assert_hier_decomposition``): either
    DCN-priced traffic appears on an axis that should stay on ICI, or
    the cross-slice wire bytes don't beat the flat all-reduce estimate,
    or the program carries no cross-slice collectives at all (the
    hier_grad_sync pass never ran). Raised BEFORE the first slab is
    dispatched, so a mis-decomposed program costs a compile, not a
    DCN-saturated training run. Carries ``violations`` (human-readable
    strings) and ``ledger`` (the offending CommLedger)."""

    def __init__(self, message, violations=None, ledger=None):
        super().__init__(message)
        self.violations = list(violations or [])
        self.ledger = ledger


class SliceWidthError(RuntimeError):
    """A checkpoint restored at a different ``dcn_dp`` width carries
    state incompatible with the rebuilt program (an optimizer slab or
    parameter whose shape disagrees with the program's declaration).
    Raised by ``train.slices.validate_restored_widths`` instead of
    letting GSPMD silently reshard — or jit fail with an opaque shape
    error — mid-recovery. Carries ``var``, ``found`` and ``expected``
    shapes."""

    def __init__(self, message, var=None, found=None, expected=None):
        super().__init__(message)
        self.var = var
        self.found = tuple(found) if found is not None else None
        self.expected = tuple(expected) if expected is not None else None


class RetryBudgetExhausted(RpcDeadlineError):
    """The process retry budget refused this retry/hedge/failover: the
    fleet is already saturated with first-try traffic, and another
    retry would amplify the overload instead of fixing anything (the
    metastable retry-storm mode "The Tail at Scale" warns about).
    Callers must treat it as a fast shed — back off or surface the
    underlying failure — never as one more thing to retry.
    Subclasses :class:`RpcDeadlineError` so transport-failure handlers
    see a connection-class error; ``retry_call`` propagates it without
    retrying (the CircuitOpenError discipline)."""


# --------------------------------------------------------------------------
# retry budget (token bucket bounding ALL tail-fighting machinery)
# --------------------------------------------------------------------------

class RetryBudget:
    """Token bucket bounding retries/hedges/failovers process-wide.

    Every INITIAL request deposits ``ratio`` tokens
    (:meth:`record_request`); every retry-shaped action withdraws one
    (:meth:`try_acquire`/:meth:`acquire`). Steady state therefore allows
    ~``ratio`` retries per request — under a sustained overload every
    layer's retry machinery (client reconnect, hedging, router
    failover, ``retry_call`` backoff loops) collectively drains the
    bucket and converts into fast typed sheds instead of multiplying
    the offered load. A small time-based reserve
    (``min_reserve`` tokens refilled over ``window_s``) keeps isolated
    failures retryable on an otherwise idle process.

    The bucket is shared process-wide by design (per-layer budgets
    would multiply the allowed amplification), but each distinct
    consumer (``what``) also holds a small EMERGENCY reserve
    (``what_reserve`` tokens, refilled over ``window_s``, consulted
    only when the shared pool is dry) — one subsystem's storm draining
    the pool must bound, not STARVE, another subsystem's isolated
    recovery retry (a serving shed storm must not abort a trainer's
    recoverable pserver bounce). ``window_s = 0`` disables both
    time-based refills and the per-consumer reserve.

    ``ratio < 0`` disables the budget entirely (every acquire granted)
    — the A/B lever for demonstrating the retry-storm failure mode.
    """

    def __init__(self, ratio=None, min_reserve=10.0, window_s=10.0,
                 cap=None, what_reserve=2.0):
        if ratio is None:
            from .flags import flag
            ratio = flag("retry_budget_ratio")
        self.ratio = float(ratio)
        self.min_reserve = float(min_reserve)
        self.window_s = float(window_s)
        self.what_reserve = float(what_reserve)
        # cap bounds token accumulation so a long quiet stretch cannot
        # bank an unbounded retry burst
        self.cap = float(cap) if cap is not None \
            else max(4.0 * self.min_reserve, 60.0)
        self._tokens = self.min_reserve
        self._last_refill = time.monotonic()
        self._what = {}        # consumer -> [tokens, last_refill]
        self._lock = threading.Lock()
        self._granted = 0
        self._denied = 0
        self._deposits = 0

    def _refill_locked(self, now):
        if self.window_s > 0:
            dt = now - self._last_refill
            if dt > 0:
                self._tokens = min(
                    self.cap,
                    self._tokens + dt * self.min_reserve / self.window_s)
        self._last_refill = now

    def _what_acquire_locked(self, what, now):
        """Per-consumer trickle reserve: each distinct ``what`` starts
        with ``what_reserve`` emergency tokens and refills at
        ``what_reserve / window_s`` tokens/s — only reached when the
        shared pool is dry, so a storm elsewhere bounds this consumer
        to a trickle instead of starving it outright."""
        if self.window_s <= 0 or self.what_reserve <= 0:
            return False
        cell = self._what.get(what)
        if cell is None:
            if len(self._what) >= 64:   # bounded like a label set
                return False
            cell = self._what[what] = [self.what_reserve, now]
        dt = now - cell[1]
        if dt > 0:
            cell[0] = min(self.what_reserve,
                          cell[0] + dt * self.what_reserve
                          / self.window_s)
        cell[1] = now
        if cell[0] >= 1.0:
            cell[0] -= 1.0
            return True
        return False

    def record_request(self):
        """Deposit ``ratio`` tokens for one initial (non-retry)
        request."""
        if self.ratio < 0:
            return
        now = time.monotonic()
        with self._lock:
            self._refill_locked(now)
            self._tokens = min(self.cap, self._tokens + self.ratio)
            self._deposits += 1

    def try_acquire(self, what="retry"):
        """Withdraw one token for a retry/hedge/failover; False (and a
        bump of ``serving_retry_budget_exhausted_total{what}``) when the
        budget is spent."""
        if self.ratio < 0:
            return True
        now = time.monotonic()
        with self._lock:
            self._refill_locked(now)
            if self._tokens >= 1.0:
                self._tokens -= 1.0
                self._granted += 1
                return True
            if self._what_acquire_locked(str(what), now):
                self._granted += 1
                return True
            self._denied += 1
        _BUDGET_EXHAUSTED.inc(labels=(str(what),))
        _flightrec().record("retry_budget_exhausted", what=str(what))
        return False

    def acquire(self, what="retry"):
        """:meth:`try_acquire` or raise :class:`RetryBudgetExhausted`."""
        if not self.try_acquire(what=what):
            raise RetryBudgetExhausted(
                f"retry budget exhausted for {what} (ratio "
                f"{self.ratio}): the process is already retrying at its "
                f"bound — shedding instead of amplifying the overload")

    def snapshot(self):
        with self._lock:
            return {"tokens": round(self._tokens, 3),
                    "ratio": self.ratio, "granted": self._granted,
                    "denied": self._denied, "deposits": self._deposits}


_default_budget = None
_budget_lock = threading.Lock()


def default_retry_budget():
    """THE process-global retry budget — consulted by ``retry_call``,
    the serving client's reconnect/hedging, and the fleet router's
    failover/hedging, so one bucket bounds every layer's amplification
    at once (per-layer budgets would multiply)."""
    global _default_budget
    with _budget_lock:
        if _default_budget is None:
            _default_budget = RetryBudget()
        return _default_budget


def reset_retry_budget():
    """Drop the process budget so the next use rebuilds it from the
    current ``FLAGS_retry_budget_ratio`` — tests and flag flips."""
    global _default_budget
    with _budget_lock:
        _default_budget = None


# --------------------------------------------------------------------------
# retry with exponential backoff + jitter
# --------------------------------------------------------------------------

def retry_call(fn, deadline=30.0, base_backoff=0.05, max_backoff=2.0,
               retries=None, retry_on=(ConnectionError, OSError),
               jitter=0.5, what="call", endpoint=None, on_retry=None,
               budget=None):
    """Run ``fn()`` until it succeeds, a non-retryable error escapes, the
    attempt budget is spent, or the wall-clock ``deadline`` passes.

    Backoff between attempts is ``base_backoff * 2**k`` capped at
    ``max_backoff``, with up to ``jitter`` fraction of random extra so a
    fleet of trainers retrying a recovered pserver doesn't stampede it.
    ``retries`` bounds ADDITIONAL attempts (None = unlimited within the
    deadline; 0 = single attempt). CircuitOpenError and
    RetryBudgetExhausted always propagate — retrying a breaker- or
    budget-rejected call would defeat the shed.

    Every retry (not the first attempt) withdraws one token from the
    process :func:`default_retry_budget` (``budget=`` overrides; the
    first attempt deposits): when the bucket is dry the call raises
    :class:`RetryBudgetExhausted` chained to the last failure instead
    of sleeping into another attempt — a process full of failing
    callers stops amplifying its own overload.

    Raises RpcDeadlineError (chained to the last failure) when the budget
    is exhausted.
    """
    start = time.monotonic()
    attempt = 0
    backoff = float(base_backoff)
    bud = budget if budget is not None else default_retry_budget()
    bud.record_request()
    while True:
        try:
            return fn()
        except (CircuitOpenError, RetryBudgetExhausted):
            raise
        except retry_on as exc:
            now = time.monotonic()
            elapsed = now - start
            out_of_attempts = retries is not None and attempt >= retries
            # next attempt would land past the deadline: give up now
            # instead of sleeping into guaranteed failure
            out_of_time = deadline is not None and \
                elapsed + backoff >= deadline
            if out_of_attempts or out_of_time:
                raise RpcDeadlineError(
                    f"{what} failed after {attempt + 1} attempt(s) over "
                    f"{elapsed:.2f}s"
                    + (f" (deadline {deadline}s)" if deadline else "")
                    + (f" to {endpoint}" if endpoint else "")
                    + f": {type(exc).__name__}: {exc}",
                    endpoint=endpoint, elapsed=elapsed) from exc
            if not bud.try_acquire(what=what):
                raise RetryBudgetExhausted(
                    f"{what} not retried after {attempt + 1} attempt(s) "
                    f"over {elapsed:.2f}s"
                    + (f" to {endpoint}" if endpoint else "")
                    + f": process retry budget exhausted (last failure "
                    f"{type(exc).__name__}: {exc})") from exc
            if on_retry is not None:
                on_retry(attempt, exc)
            time.sleep(backoff * (1.0 + jitter * random.random()))
            attempt += 1
            backoff = min(backoff * 2.0, float(max_backoff))


# --------------------------------------------------------------------------
# circuit breaker
# --------------------------------------------------------------------------

class CircuitBreaker:
    """Per-endpoint fail-fast gate (closed -> open -> half-open).

    ``failure_threshold`` consecutive failures open the circuit: calls
    raise CircuitOpenError immediately for ``reset_timeout`` seconds.
    After that one trial call is admitted (half-open); success closes the
    circuit, failure re-opens it for another ``reset_timeout``.
    """

    def __init__(self, endpoint=None, failure_threshold=3,
                 reset_timeout=5.0):
        self.endpoint = endpoint
        self.failure_threshold = int(failure_threshold)
        self.reset_timeout = float(reset_timeout)
        self._failures = 0
        self._opened_at = None
        self._half_open_inflight = False
        self._lock = threading.Lock()
        _BREAKERS.add(self)

    @property
    def state(self):
        with self._lock:
            if self._opened_at is None:
                return "closed"
            if time.monotonic() - self._opened_at >= self.reset_timeout:
                return "half-open"
            return "open"

    def before_call(self):
        """Admission check; raises CircuitOpenError when open."""
        with self._lock:
            if self._opened_at is None:
                return
            waited = time.monotonic() - self._opened_at
            if waited < self.reset_timeout:
                raise CircuitOpenError(
                    f"circuit breaker open for {self.endpoint or 'peer'} "
                    f"({self._failures} consecutive failures; retrying "
                    f"in {self.reset_timeout - waited:.1f}s)",
                    endpoint=self.endpoint)
            # half-open: admit exactly one probe at a time
            if self._half_open_inflight:
                raise CircuitOpenError(
                    f"circuit breaker half-open for "
                    f"{self.endpoint or 'peer'}: probe already in flight",
                    endpoint=self.endpoint)
            self._half_open_inflight = True

    def record_success(self):
        with self._lock:
            self._failures = 0
            self._opened_at = None
            self._half_open_inflight = False

    def release_probe(self):
        """Abandon an admitted call without judging the endpoint — for
        failures that are the caller's (encode TypeError, interrupt), not
        the peer's. Frees the half-open probe slot so an abandoned probe
        cannot wedge the breaker in fail-fast forever."""
        with self._lock:
            self._half_open_inflight = False

    def record_failure(self):
        with self._lock:
            self._failures += 1
            self._half_open_inflight = False
            if self._failures >= self.failure_threshold:
                self._opened_at = time.monotonic()


# --------------------------------------------------------------------------
# watchdog
# --------------------------------------------------------------------------

@contextmanager
def watchdog(budget_secs, what="operation"):
    """Abort the enclosed block when it exceeds ``budget_secs``.

    Main-thread only (uses interrupt_main, the same lever Ctrl-C pulls);
    from other threads use run_with_watchdog. The interrupt lands at the
    next Python bytecode boundary — a block stuck inside a single C call
    is aborted as soon as it re-enters Python.
    """
    import signal
    import _thread
    main = threading.main_thread()
    if threading.current_thread() is not main:
        raise RuntimeError("watchdog() only arms on the main thread; "
                           "use run_with_watchdog elsewhere")
    fired = [False]
    armed = [True]
    # _fire sends the signal while HOLDING this lock, and the exit path
    # disarms while holding it — so the interrupt can never land after
    # the with-block has moved on into unrelated code
    arm_lock = threading.Lock()

    def _fire():
        with arm_lock:
            if not armed[0]:
                return
            fired[0] = True
            try:
                # a real SIGINT interrupts blocking syscalls (sleep,
                # socket recv) with EINTR; interrupt_main() only sets a
                # flag the interpreter notices AFTER the syscall returns
                signal.pthread_kill(main.ident, signal.SIGINT)
            except (AttributeError, OSError, ValueError):
                _thread.interrupt_main()

    timer = threading.Timer(float(budget_secs), _fire)
    timer.daemon = True
    timer.start()
    try:
        yield
    except KeyboardInterrupt:
        if fired[0]:
            raise WatchdogTimeout(
                f"{what} exceeded its {budget_secs}s wall-clock budget")
        raise
    finally:
        try:
            with arm_lock:
                armed[0] = False
        except KeyboardInterrupt:
            armed[0] = False
            if not fired[0]:
                raise           # a genuine Ctrl-C, not our timer
            # the timer fired in the instant between the block completing
            # and the disarm: the work finished within budget, absorb the
            # late interrupt instead of letting it escape
        timer.cancel()


def run_with_watchdog(fn, budget_secs, *args, what=None, **kwargs):
    """Run ``fn(*args, **kwargs)`` on a worker thread; raise
    WatchdogTimeout if it does not finish within ``budget_secs``. Safe
    from any thread. The overrunning worker is left to die as a daemon —
    its result is discarded."""
    box = {}

    def _target():
        try:
            box["result"] = fn(*args, **kwargs)
        except BaseException as exc:  # noqa: BLE001 — relayed to caller
            box["error"] = exc

    t = threading.Thread(target=_target, daemon=True)
    t.start()
    t.join(float(budget_secs))
    if t.is_alive():
        what = what or getattr(fn, "__name__", "operation")
        _flightrec().record("watchdog", what=str(what),
                            budget_s=float(budget_secs))
        raise WatchdogTimeout(
            f"{what} exceeded its {budget_secs}s wall-clock budget")
    if "error" in box:
        raise box["error"]
    return box.get("result")


# --------------------------------------------------------------------------
# fault injection (test hook)
# --------------------------------------------------------------------------

_faults = {}
_faults_lock = threading.Lock()


def maybe_fail(point, **context):
    """Production-side failure point: raises the armed exception when a
    test has armed ``point`` via fault_injection. No-op (one dict lookup)
    otherwise."""
    with _faults_lock:
        spec = _faults.get(point)
        if spec is None or spec["remaining"] == 0:
            return
        spec["remaining"] -= 1
        spec["fired"] += 1
        exc = spec["exc"]
    if callable(exc) and not isinstance(exc, type):
        exc = exc(point, context)
        if exc is None:
            return
    raise exc if not isinstance(exc, type) else exc(
        f"fault injected at {point}")


def clear_faults():
    with _faults_lock:
        _faults.clear()


@contextmanager
def fault_injection(point, exc=ConnectionError, times=1):
    """Arm ``point`` to raise ``exc`` for the next ``times`` hits
    (``times=-1`` = every hit while armed). ``exc`` may be an exception
    class, an instance, or a callable ``(point, context) -> exception or
    None``. Yields the spec dict; ``spec['fired']`` counts trips."""
    spec = {"exc": exc, "remaining": int(times), "fired": 0}
    with _faults_lock:
        prev = _faults.get(point)
        _faults[point] = spec
    try:
        yield spec
    finally:
        with _faults_lock:
            if prev is None:
                _faults.pop(point, None)
            else:
                _faults[point] = prev


# --------------------------------------------------------------------------
# chaos harness (seeded, probabilistic, schedulable fault points)
# --------------------------------------------------------------------------

class ChaosMonkey:
    """Handle yielded by :func:`chaos`: per-point hit and fire counters
    (``hits[point]`` = times the armed point was reached, ``fired[point]``
    = times it actually injected a fault/delay)."""

    def __init__(self, seed):
        self.seed = seed
        self.hits = {}
        self.fired = {}
        self._lock = threading.Lock()

    def _record(self, point, fire):
        with self._lock:
            self.hits[point] = self.hits.get(point, 0) + 1
            if fire:
                self.fired[point] = self.fired.get(point, 0) + 1
        if fire:
            # black-box the injection: a chaos-soak postmortem dump
            # names every fault point that actually fired
            _CHAOS_FIRED.inc(labels=(point,))
            _flightrec().record("chaos", point=point, seed=self.seed)

    def total_fired(self):
        with self._lock:
            return sum(self.fired.values())


def _chaos_spec(point, cfg, monkey):
    """Build one armed-point callable from a per-point config dict:
    ``p`` (fire probability per hit), ``after`` (skip the first N hits),
    ``every`` (deterministic: fire on every Nth hit, overriding p),
    ``times`` (stop after N fires; -1 unlimited), ``delay`` (inject a
    stall of that many seconds instead of raising), ``exc`` (exception
    class/instance to raise). Each point draws from its OWN seeded RNG
    stream so arming more points never perturbs another point's
    pattern."""
    p = float(cfg.get("p", 1.0))
    after = int(cfg.get("after", 0))
    every = cfg.get("every")
    times = int(cfg.get("times", -1))
    delay = cfg.get("delay")
    exc = cfg.get("exc", FaultInjected)
    rng = random.Random(f"{monkey.seed}/{point}")
    state = {"hits": 0, "fires": 0}
    lock = threading.Lock()

    def _fire(pt, context):
        with lock:
            state["hits"] += 1
            hit = state["hits"]
            draw = rng.random()       # always drawn: keeps the stream
            if hit <= after:          # aligned whether or not we fire
                fire = False
            elif times >= 0 and state["fires"] >= times:
                fire = False
            elif every is not None:
                fire = (hit - after) % int(every) == 0
            else:
                fire = draw < p
            if fire:
                state["fires"] += 1
        monkey._record(pt, fire)
        if not fire:
            return None
        if delay:
            time.sleep(float(delay))
            return None
        if isinstance(exc, type):
            return exc(f"fault injected at {pt}")
        return exc

    return {"exc": _fire, "remaining": -1, "fired": 0}


@contextmanager
def chaos(points, p=1.0, seed=None, exc=FaultInjected, times=-1,
          after=0, every=None, delay=None):
    """Arm MANY fault points at once with seeded, probabilistic,
    schedulable behavior — the serving chaos harness.

    ``points`` is a point name, an iterable of names, or a dict mapping
    name -> per-point overrides (any of ``p``/``after``/``every``/
    ``times``/``delay``/``exc``); the keyword arguments are the
    defaults every point inherits. ``seed`` None reads
    ``FLAGS_chaos_seed``. Determinism: each point owns an RNG seeded
    from ``(seed, point)``, so a single-threaded test replays the exact
    same fire pattern run after run, and adding a point never shifts
    another's stream (under concurrency the per-point pattern stays
    fixed; which REQUEST absorbs each fault depends on scheduling).

    Yields a :class:`ChaosMonkey` with per-point hit/fire counters.
    """
    if seed is None:
        from .flags import flag
        seed = flag("chaos_seed")
    if isinstance(points, str):
        points = {points: {}}
    elif not isinstance(points, dict):
        points = {pt: {} for pt in points}
    monkey = ChaosMonkey(seed)
    defaults = {"p": p, "after": after, "every": every, "times": times,
                "delay": delay, "exc": exc}
    prev = {}
    with _faults_lock:
        for pt, overrides in points.items():
            cfg = dict(defaults)
            cfg.update(overrides or {})
            prev[pt] = _faults.get(pt)
            _faults[pt] = _chaos_spec(pt, cfg, monkey)
    try:
        yield monkey
    finally:
        with _faults_lock:
            for pt, old in prev.items():
                if old is None:
                    _faults.pop(pt, None)
                else:
                    _faults[pt] = old
