"""Training-data generators for slot-based (CTR) text formats.

Capability parity with the reference's data generator
(/root/reference/python/paddle/fluid/incubate/data_generator/__init__.py —
DataGenerator.run_from_stdin/run_from_memory, MultiSlotDataGenerator
:set_batch/_gen_str): user subclasses implement `generate_sample(line)`
yielding [(slot_name, [values]), ...] per sample; the generator serializes
them into the slot text format the Dataset parser reads
(`name:v1,v2,... name2:...` per line, dataio/dataset.py _parse_line).

The reference emits a count-prefixed token stream for its C++
MultiSlotDataFeed; this build's canonical on-disk format is the
name-tagged line, so files written here feed straight into
DatasetFactory().create_dataset(...).set_filelist(...).
"""
import sys


class DataGenerator:
    def __init__(self):
        self._line_proc = None
        self.batch_size_ = 32

    def set_batch(self, batch_size):
        self.batch_size_ = int(batch_size)

    # -- user hooks (reference API) --------------------------------------
    def generate_sample(self, line):
        """Return a generator function yielding one or more samples for
        `line`; each sample is [(slot_name, [values]), ...]."""
        raise NotImplementedError(
            "subclass DataGenerator and implement generate_sample")

    def generate_batch(self, samples):
        """Optional batch-level hook; yields samples (default identity)."""
        def local_iter():
            for s in samples:
                yield s
        return local_iter

    # -- serialization ----------------------------------------------------
    def _gen_str(self, sample):
        return " ".join(
            f"{name}:{','.join(str(v) for v in values)}"
            for name, values in sample) + "\n"

    # -- drivers -----------------------------------------------------------
    def run_from_stdin(self):
        """stdin lines -> serialized samples on stdout (the pipe_command
        contract of the reference's dataset ingestion)."""
        self._run_lines(sys.stdin, sys.stdout)

    def run_from_files(self, input_files, output_file):
        """Batch conversion: raw text files -> one slot-format file."""
        with open(output_file, "w") as out:
            for path in input_files:
                with open(path) as f:
                    self._run_lines(f, out)
        return output_file

    def run_from_memory(self, lines, output=None):
        out = output or sys.stdout
        self._run_lines(lines, out)

    def _run_lines(self, lines, out):
        batch = []
        for line in lines:
            gen = self.generate_sample(line)
            for sample in gen():
                if sample is None:
                    continue
                batch.append(sample)
                if len(batch) >= self.batch_size_:
                    self._flush(batch, out)
                    batch = []
        if batch:
            self._flush(batch, out)

    def _flush(self, batch, out):
        for sample in self.generate_batch(batch)():
            out.write(self._gen_str(sample))


class MultiSlotDataGenerator(DataGenerator):
    """Numeric slots (int64 ids / float32 values) — the Criteo-style CTR
    format (reference MultiSlotDataGenerator)."""

    def _gen_str(self, sample):
        for name, values in sample:
            if not values:
                raise ValueError(f"slot {name!r} has no values")
        return super()._gen_str(sample)


class MultiSlotStringDataGenerator(DataGenerator):
    """String-valued slots. The slot line format delimits values with
    spaces/colons/commas, so values containing those characters cannot
    round-trip — they are rejected loudly instead of corrupting the
    file."""

    def _gen_str(self, sample):
        for name, values in sample:
            for v in values:
                sv = str(v)
                if any(c in sv for c in " :,\t\n"):
                    raise ValueError(
                        f"slot {name!r} value {sv!r} contains a delimiter "
                        f"(space/colon/comma); encode it first — the slot "
                        f"line format cannot represent it")
        return super()._gen_str(sample)
