"""pslib optimizer factory (reference incubate/fleet/
parameter_server/pslib/optimizer_factory.py: DistributedAdam,
FLEET_GLOBAL_DICT). The reference's factory compiles the user
optimizer + sparse-table configs into a Downpour protobuf plan; the
TPU-native table runtime lives in distributed/downpour.py
(DownpourTableConfig / FleetWrapper / DownpourWorker), so this
factory's job is the reference-shaped `_minimize` contract: run the
dense optimizer locally and hand back per-loss results for
PSLibFleet's worker loop."""

__all__ = ["DistributedAdam", "FLEET_GLOBAL_DICT"]

FLEET_GLOBAL_DICT = {
    "enable": False,
    "emb_to_table": {},
    "emb_to_accessor": {},
    "emb_to_size": {},
}


class DistributedAdam:
    """reference optimizer_factory.py DistributedAdam."""

    def __init__(self, optimizer):
        self._optimizer = optimizer
        self._window = 1
        self.type = "downpour"

    def _minimize(self, losses, startup_program=None,
                  parameter_list=None, no_grad_set=None,
                  strategy=None):
        if not isinstance(losses, (list, tuple)):
            losses = [losses]
        results = [self._optimizer.minimize(
            loss, startup_program, parameter_list, no_grad_set)
            for loss in losses]
        return results[0] if len(results) == 1 else results

    minimize = _minimize
