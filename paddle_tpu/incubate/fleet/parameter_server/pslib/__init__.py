"""pslib-mode fleet (reference
python/paddle/fluid/incubate/fleet/parameter_server/pslib/__init__.py +
optimizer_factory.py DistributedAdam): the production async-CTR driver —
fleet.init / init_server / init_worker lifecycle over the Downpour
runtime (distributed/downpour.py), and DownpourOptimizer, which splits a
model's sparse embedding tables onto accessor-configured PS tables and
leaves the dense part to the local optimizer."""
import numpy as np

from .....distributed.downpour import (DownpourTableConfig, DownpourWorker,
                                       FleetWrapper)
from .....distributed.ps import ParameterServer, PSClient
from ...base.role_maker import PaddleCloudRoleMaker, RoleMakerBase


class PSLibFleet:
    """Lifecycle parity with the reference pslib fleet singleton."""

    def __init__(self):
        self._role_maker = None
        self._servers = []
        self._fleet_wrapper = None
        self._tables = {}

    # -- lifecycle --------------------------------------------------------
    def init(self, role_maker=None):
        if role_maker is None:
            role_maker = PaddleCloudRoleMaker()
        assert isinstance(role_maker, RoleMakerBase)
        self._role_maker = role_maker

    def is_worker(self):
        return self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker.is_server()

    def worker_index(self):
        return self._role_maker.worker_index()

    def server_endpoints(self):
        return self._role_maker.get_pserver_endpoints()

    def register_table(self, table):
        """Declare a DownpourTableConfig (reference table proto in the
        strategy dict of DistributedAdam._minimize)."""
        self._tables[table.table_id] = table

    def init_server(self, model_dir=None, **kwargs):
        """On a server role: host every registered table shard and serve
        (reference fleet.init_server + run_server)."""
        ep = self.server_endpoints()[self._role_maker.server_index()]
        srv = ParameterServer(ep, trainers=self._role_maker.worker_num(),
                              sync_mode=False,
                              heartbeat_timeout=kwargs.get(
                                  "heartbeat_timeout"))
        for t in self._tables.values():
            srv.host_downpour_table(t.table_id, t.emb_dim,
                                    accessor=t.accessor)
        self._servers.append(srv)
        return srv

    def run_server(self, ready_event=None, block=True):
        assert self._servers, "call init_server() first"
        return self._servers[-1].serve(ready_event=ready_event,
                                       block=block)

    def init_worker(self, max_pending=8):
        self._fleet_wrapper = FleetWrapper(self.server_endpoints(),
                                           async_push=True,
                                           max_pending=max_pending)
        return self._fleet_wrapper

    def worker(self, table_id, step_fn, id_slots, label_key):
        """Build the async ingest-train loop for one sparse table."""
        assert self._fleet_wrapper is not None, "call init_worker() first"
        return DownpourWorker(self._fleet_wrapper,
                              self._tables[table_id], step_fn, id_slots,
                              label_key)

    def stop_worker(self):
        if self._fleet_wrapper is not None:
            self._fleet_wrapper.flush()

    def stop_server(self):
        PSClient.instance("downpour").stop_servers(self.server_endpoints())


fleet = PSLibFleet()


class DownpourOptimizer:
    """reference optimizer_factory.py DistributedAdam shape: wraps the
    dense optimizer; `minimize` returns the per-table sparse feed plan
    the worker loop consumes while the dense part trains locally."""

    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = dict(strategy or {})

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        # single dense-minimize implementation: the reference routes
        # pslib's distributed_optimizer through optimizer_factory
        # DistributedAdam; so do we
        from .optimizer_factory import DistributedAdam
        return DistributedAdam(self._optimizer)._minimize(
            loss, startup_program, parameter_list, no_grad_set,
            strategy=self._strategy)


# virtual subclasses of the fleet ABC contract (base/fleet_base.py)
from ...base.fleet_base import Fleet as _Fleet  # noqa: E402
from ...base.fleet_base import DistributedOptimizer as _DO  # noqa: E402
from .optimizer_factory import DistributedAdam as _DA  # noqa: E402
_Fleet.register(PSLibFleet)
_DO.register(DownpourOptimizer)
_DO.register(_DA)
