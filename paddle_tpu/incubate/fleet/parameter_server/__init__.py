"""Fleet parameter-server mode (reference:
python/paddle/fluid/incubate/fleet/parameter_server/distribute_transpiler/
__init__.py — fleet.init / init_server / run_server / init_worker /
stop_worker over DistributeTranspiler). Drives the host PS runtime in
paddle_tpu/distributed/ps.py through the same role-make/transpile flow."""
from ..base.role_maker import PaddleCloudRoleMaker, RoleMakerBase


class ParameterServerFleet:
    def __init__(self):
        self._role_maker = None
        self._transpiler = None
        self._trainer_program = None
        self._pserver_prog = None
        self._pserver_startup = None

    def init(self, role_maker=None):
        if role_maker is None:
            role_maker = PaddleCloudRoleMaker()
        assert isinstance(role_maker, RoleMakerBase)
        self._role_maker = role_maker

    def is_worker(self):
        return self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker.is_server()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def distributed_optimizer(self, optimizer, strategy=None):
        return _TranspilerOptimizer(self, optimizer, strategy)

    # -- server lifecycle -------------------------------------------------
    def init_server(self, *args, **kwargs):
        from ....framework.executor import Executor
        t = self._transpiler
        ep = self._role_maker.get_pserver_endpoints()[
            self._role_maker.server_index()]
        self._pserver_prog, self._pserver_startup = t.get_pserver_programs(
            ep)
        Executor().run(self._pserver_startup)

    def run_server(self):
        from ....framework.executor import Executor
        assert self._pserver_prog is not None, "call init_server() first"
        Executor().run(self._pserver_prog)

    # -- worker lifecycle -------------------------------------------------
    def init_worker(self):
        from ....distributed.ps import PSClient
        PSClient.instance().wait_ports(
            self._role_maker.get_pserver_endpoints())

    def stop_worker(self):
        from ....distributed.ps import PSClient
        if self._role_maker.is_first_worker():
            PSClient.instance().stop_servers(
                self._role_maker.get_pserver_endpoints())

    def save_persistables(self, executor=None, dirname=None,
                          main_program=None):
        """Server-side save of the PS-hosted tables (reference
        fluid/io.py _save_distributed_persistables via fleet): every
        pserver writes its shard under `dirname`."""
        from ....distributed.ps import PSClient
        assert dirname, "save_persistables needs dirname"
        PSClient.instance().save_persistables(
            self._role_maker.get_pserver_endpoints(), dirname)

    def load_persistables(self, executor=None, dirname=None,
                          main_program=None):
        from ....distributed.ps import PSClient
        assert dirname, "load_persistables needs dirname"
        PSClient.instance().load_persistables(
            self._role_maker.get_pserver_endpoints(), dirname)

    @property
    def main_program(self):
        assert self._trainer_program is not None, \
            "call distributed_optimizer(...).minimize(loss) first"
        return self._trainer_program

    @property
    def startup_program(self):
        from ....framework.core import default_startup_program
        return default_startup_program()


class _TranspilerOptimizer:
    def __init__(self, fleet_obj, inner, strategy=None):
        self._fleet = fleet_obj
        self._inner = inner
        self._strategy = strategy  # DistributeTranspilerConfig or None

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        from ....transpiler import DistributeTranspiler
        result = self._inner.minimize(loss, startup_program,
                                      parameter_list, no_grad_set)
        rm = self._fleet._role_maker
        config = self._strategy
        # a fleet DistributedStrategy (distribute_transpiler.
        # distributed_strategy) carries its transpiler config inside
        if hasattr(config, "get_program_config"):
            config = config.get_program_config()
        if config is not None and getattr(config, "geo_sgd_mode", False):
            # GEO: unmodified local program + periodic delta sync
            from ....transpiler import GeoSgdTranspiler
            t = GeoSgdTranspiler(config=config)
        else:
            t = DistributeTranspiler(config=config)
        t.transpile(
            trainer_id=rm.worker_index(),
            program=loss.block.program,
            pservers=",".join(rm.get_pserver_endpoints()),
            trainers=rm.worker_num(),
            sync_mode=getattr(config, "sync_mode", True),
            startup_program=startup_program)
        self._fleet._transpiler = t
        if rm.is_worker():
            self._fleet._trainer_program = t.get_trainer_program(
                wait_port=False)
        return result

    def __getattr__(self, item):
        return getattr(self._inner, item)


fleet = ParameterServerFleet()

# virtual subclasses of the fleet ABC contract (base/fleet_base.py) so
# reference-style isinstance checks hold
from ..base.fleet_base import Fleet as _Fleet  # noqa: E402
from ..base.fleet_base import DistributedOptimizer as _DO  # noqa: E402
_Fleet.register(ParameterServerFleet)
_DO.register(_TranspilerOptimizer)
