"""PS-mode distributed strategies (reference incubate/fleet/
parameter_server/distribute_transpiler/distributed_strategy.py:
TrainerRuntimeConfig, DistributedStrategy, Sync/Async/HalfAsync/Geo
strategies, StrategyFactory). Each strategy carries a
DistributeTranspilerConfig plus the communicator mode the trainer
runtime starts (distributed/communicator.py implements the four
modes)."""
from .....transpiler import DistributeTranspilerConfig

__all__ = ["TrainerRuntimeConfig", "DistributedStrategy",
           "SyncStrategy", "AsyncStrategy", "HalfAsyncStrategy",
           "GeoStrategy", "StrategyFactory"]


class TrainerRuntimeConfig:
    """reference distributed_strategy.py TrainerRuntimeConfig: the
    communicator knobs (send queue sizes / wait times)."""

    def __init__(self):
        self.mode = None
        self.runtime_configs = {
            "communicator_max_merge_var_num": 20,
            "communicator_send_queue_size": 20,
            "communicator_independent_recv_thread": 1,
            "communicator_send_wait_times": 5,
            "communicator_thread_pool_size": 5,
        }

    def get_communicator_flags(self):
        return dict(self.runtime_configs)


class DistributedStrategy:
    """reference DistributedStrategy base: program config + trainer
    runtime config + execute/build strategies."""

    def __init__(self):
        self._program_config = DistributeTranspilerConfig()
        self._trainer_runtime_config = TrainerRuntimeConfig()
        self._build_strategy = None
        self._execute_strategy = None
        self._mode = "sync"

    def get_program_config(self):
        return self._program_config

    def set_program_config(self, config):
        if isinstance(config, DistributeTranspilerConfig):
            self._program_config = config
        elif isinstance(config, dict):
            for k, v in config.items():
                if not hasattr(self._program_config, k):
                    raise ValueError(f"unknown program_config key {k!r}")
                setattr(self._program_config, k, v)
        else:
            raise TypeError(
                "program_config must be DistributeTranspilerConfig or "
                "dict")

    def get_trainer_runtime_config(self):
        return self._trainer_runtime_config

    def set_trainer_runtime_config(self, config):
        if isinstance(config, TrainerRuntimeConfig):
            self._trainer_runtime_config = config
        elif isinstance(config, dict):
            self._trainer_runtime_config.runtime_configs.update(config)
        else:
            raise TypeError(
                "trainer_runtime_config must be TrainerRuntimeConfig "
                "or dict")

    def get_build_strategy(self):
        return self._build_strategy

    def set_build_strategy(self, s):
        self._build_strategy = s

    def get_execute_strategy(self):
        return self._execute_strategy

    def set_execute_strategy(self, s):
        self._execute_strategy = s

    @property
    def sync_mode(self):
        return self._mode == "sync"


class SyncStrategy(DistributedStrategy):
    def __init__(self):
        super().__init__()
        self._mode = "sync"
        self._program_config.sync_mode = True


class AsyncStrategy(DistributedStrategy):
    def __init__(self):
        super().__init__()
        self._mode = "async"
        self._program_config.sync_mode = False


class HalfAsyncStrategy(DistributedStrategy):
    def __init__(self):
        super().__init__()
        self._mode = "half_async"
        # the transpiler derives effective sync from
        # `sync_mode and not half_async` (transpiler/__init__.py:145):
        # half-async keeps the sync program rewrite but drops the
        # per-step barrier
        self._program_config.sync_mode = True
        self._program_config.half_async = True


class GeoStrategy(DistributedStrategy):
    def __init__(self, update_frequency=100):
        super().__init__()
        self._mode = "geo"
        self._program_config.sync_mode = False
        self._program_config.geo_sgd_mode = True
        self._program_config.geo_sgd_need_push_nums = int(
            update_frequency)


class StrategyFactory:
    """reference StrategyFactory: canned strategy constructors."""

    @staticmethod
    def create_sync_strategy():
        return SyncStrategy()

    @staticmethod
    def create_async_strategy():
        return AsyncStrategy()

    @staticmethod
    def create_half_async_strategy():
        return HalfAsyncStrategy()

    @staticmethod
    def create_geo_strategy(update_frequency=100):
        return GeoStrategy(update_frequency)
