"""Reference module path incubate/fleet/parameter_server/
distribute_transpiler/__init__.py — the transpiler-mode PS fleet. The
implementation lives one level up (parameter_server/__init__.py
ParameterServerFleet); this package provides the reference import path
plus the strategy objects, which _TranspilerOptimizer accepts directly
(a DistributedStrategy's program config and sync mode feed the
transpile call)."""
from .. import (  # noqa: F401
    fleet, ParameterServerFleet, _TranspilerOptimizer,
)
from .distributed_strategy import (  # noqa: F401
    TrainerRuntimeConfig, DistributedStrategy, SyncStrategy,
    AsyncStrategy, HalfAsyncStrategy, GeoStrategy, StrategyFactory,
)
