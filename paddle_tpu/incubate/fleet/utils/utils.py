"""Fleet program utilities (reference incubate/fleet/utils/utils.py:
load_program/save_program, program_type_trans, parse_program,
check_saved_vars_try_dump, check_pruned_program_vars, graphviz).

Program files here are the framework's JSON serialization
(Program.to_dict / from_dict) — the reference's binary/pbtxt pair maps
to compact vs indented JSON, and program_type_trans converts between
them."""
import json
import os

from ....framework.core import Program

__all__ = ["load_program", "save_program", "program_type_trans",
           "check_saved_vars_try_dump", "parse_program",
           "check_pruned_program_vars", "graphviz"]


def save_program(program, model_filename, is_text=False):
    """reference utils.py save_program: write a program file (indented
    JSON when is_text, compact otherwise)."""
    with open(model_filename, "w") as f:
        json.dump(program.to_dict(), f,
                  indent=2 if is_text else None)


def load_program(model_filename, is_text=False):
    """reference utils.py load_program."""
    with open(model_filename) as f:
        return Program.from_dict(json.load(f))


def program_type_trans(prog_dir, prog_fn, is_text):
    """reference utils.py program_type_trans: convert a program file
    between the compact (binary-analog) and indented (text-analog)
    forms; returns the converted file name."""
    path = os.path.join(prog_dir, prog_fn)
    prog = load_program(path, is_text)
    out_fn = prog_fn + (".bin" if is_text else ".pbtxt")
    save_program(prog, os.path.join(prog_dir, out_fn),
                 is_text=not is_text)
    return out_fn


def parse_program(program, output_file=None):
    """reference utils.py parse_program: human-readable summary
    (feeds, fetches, per-block op list with IO)."""
    lines = []
    for block in program.blocks:
        lines.append(f"block {block.idx} "
                     f"(parent {block.parent_idx}):")
        for var in block.vars.values():
            lines.append(f"  var {var.name}: shape={var.shape} "
                         f"dtype={var.dtype} "
                         f"persistable={var.persistable}")
        for op in block.ops:
            ins = {k: v for k, v in op.inputs.items()}
            outs = {k: v for k, v in op.outputs.items()}
            lines.append(f"  op {op.type}: in={ins} out={outs}")
    text = "\n".join(lines) + "\n"
    if output_file:
        with open(output_file, "w") as f:
            f.write(text)
    return text


def check_pruned_program_vars(train_prog, pruned_prog):
    """reference utils.py: every var the pruned (inference) program
    reads must exist in the train program with matching shape/dtype;
    returns the list of mismatches (empty = compatible)."""
    train_vars = {}
    for block in train_prog.blocks:
        train_vars.update(block.vars)
    problems = []
    for block in pruned_prog.blocks:
        for var in block.vars.values():
            if getattr(var, "is_data", False):
                continue
            tv = train_vars.get(var.name)
            if tv is None:
                problems.append((var.name, "missing in train program"))
            elif tv.shape != var.shape or tv.dtype != var.dtype:
                problems.append(
                    (var.name,
                     f"shape/dtype mismatch: train ({tv.shape}, "
                     f"{tv.dtype}) vs pruned ({var.shape}, "
                     f"{var.dtype})"))
    return problems


def check_saved_vars_try_dump(dump_dir, dump_prog_fn, is_text_dump_program,
                              feed_config=None, fetch_config=None,
                              batch_size=1, save_filename=None):
    """reference utils.py: load a dumped program and verify it can be
    summarized (the reference also test-runs it; a parse here proves
    the file round-trips)."""
    prog = load_program(os.path.join(dump_dir, dump_prog_fn),
                        is_text_dump_program)
    return parse_program(prog)


def graphviz(block, output_dir="", filename="program.dot"):
    """reference utils.py graphviz: emit a DOT graph of the block's
    op/var dataflow; returns the dot file path."""
    lines = ["digraph G {"]
    for i, op in enumerate(block.ops):
        op_node = f"op_{i}_{op.type}"
        lines.append(f'  "{op_node}" [shape=box, label="{op.type}"];')
        for names in op.inputs.values():
            for n in names:
                lines.append(f'  "{n}" -> "{op_node}";')
        for names in op.outputs.values():
            for n in names:
                lines.append(f'  "{op_node}" -> "{n}";')
    lines.append("}")
    path = os.path.join(output_dir or ".", filename)
    with open(path, "w") as f:
        f.write("\n".join(lines) + "\n")
    return path
