"""FleetUtil (reference: incubate/fleet/utils/fleet_util.py — cross-worker
metric aggregation over gloo + misc helpers). The TPU analog aggregates
via the parameter-server channel when one is up, else locally."""
import numpy as np


class FleetUtil:
    def __init__(self, mode="collective"):
        self.mode = mode

    def all_reduce_sum(self, value, endpoint=None, name="fleet_util_acc",
                       trainers=1):
        """Sum a numpy value across workers via the pserver's dedicated
        all-reduce channel (gloo-wrapper analog,
        framework/fleet/gloo_wrapper.h:102) — isolated from the gradient
        sync rounds; single-process returns the value unchanged."""
        value = np.asarray(value, np.float64)
        if endpoint is None or trainers <= 1:
            return value
        from ...distributed.ps import PSClient
        cli = PSClient.instance(key="fleet_util")
        return np.asarray(cli.allreduce(endpoint, name, value, trainers))

    def calculate_auc(self, stat_pos, stat_neg):
        """AUC from accumulated threshold histograms (the shape the auc op
        and fluid.metrics.Auc keep) — reference FleetUtil.get_global_auc
        math after aggregation."""
        tp = np.cumsum(np.asarray(stat_pos, np.float64)[::-1])
        fp = np.cumsum(np.asarray(stat_neg, np.float64)[::-1])
        if tp[-1] == 0 or fp[-1] == 0:
            return 0.0
        tp0 = np.concatenate([[0.0], tp[:-1]])
        fp0 = np.concatenate([[0.0], fp[:-1]])
        area = np.sum((fp - fp0) * (tp + tp0) / 2.0)
        return float(area / (tp[-1] * fp[-1]))

    def print_global_auc(self, scope, stat_pos_name, stat_neg_name,
                         print_prefix=""):
        from ...framework.executor import global_scope
        scope = scope or global_scope()
        pos = scope.find_var(stat_pos_name)
        neg = scope.find_var(stat_neg_name)
        if pos is None or neg is None:
            missing = stat_pos_name if pos is None else stat_neg_name
            raise KeyError(
                f"print_global_auc: stat var {missing!r} is not in the "
                f"scope (run a step with the auc op first)")
        auc = self.calculate_auc(np.asarray(pos), np.asarray(neg))
        print(f"{print_prefix} global auc = {auc:.6f}")
        return auc
