"""Filesystem abstraction for checkpoints (reference:
incubate/fleet/utils/fs.py / hdfs.py — FS base + LocalFS + HDFSClient).
Checkpoint-restart recovery (incubate/fleet/collective save_checkpoint)
writes through this interface; LocalFS covers shared-filesystem (NFS/GCS
-fuse) deployments, the standard TPU pattern. HDFS has no TPU-pod analog
— the shim raises with guidance instead of silently no-oping."""
import os
import shutil


class FS:
    def ls_dir(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError


class LocalFS(FS):
    """reference fs.py LocalFS."""

    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for e in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, e))
             else files).append(e)
        return dirs, files

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.replace(src, dst)

    def mv(self, src, dst, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src):
            raise FileNotFoundError(src)
        if self.is_exist(dst):
            if not overwrite:
                raise FileExistsError(
                    f"mv destination {dst!r} exists (pass overwrite=True)")
            self.delete(dst)
        os.replace(src, dst)

    def upload(self, local_path, fs_path):
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path, dirs_exist_ok=True)
        else:
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def touch(self, path, exist_ok=True):
        if not exist_ok and self.is_exist(path):
            raise FileExistsError(path)
        open(path, "a").close()


class HDFSClient(FS):
    """Shells out to the hadoop CLI when one is configured (reference
    incubate/fleet/utils/hdfs.py HDFSClient does exactly this); without a
    usable `hadoop` binary it degrades to LocalFS under a sandbox root so
    fleet checkpoint/rendezvous paths still work on shared filesystems
    (NFS / gcsfuse — the standard TPU-pod pattern)."""

    def __init__(self, hadoop_home=None, configs=None,
                 local_root=None):
        import shutil as _sh
        self._configs = dict(configs or {})
        self._hadoop = None
        cand = (os.path.join(hadoop_home, "bin", "hadoop")
                if hadoop_home else _sh.which("hadoop"))
        if cand and os.path.exists(cand):
            self._hadoop = cand
        elif hadoop_home:
            # an EXPLICIT hadoop_home that doesn't resolve is a config
            # error — silently writing to the local sandbox would strand
            # checkpoints on one node
            raise ValueError(
                f"hadoop binary not found under hadoop_home="
                f"{hadoop_home!r} (expected {cand}); fix the path or "
                f"omit hadoop_home to use the LocalFS fallback")
        self._local = LocalFS()
        self._root = local_root or os.path.join(
            os.path.expanduser("~"), ".paddle_tpu_hdfs_local")
        if self._hadoop is None:
            os.makedirs(self._root, exist_ok=True)

    def _run(self, *args, check=False):
        import subprocess
        cmd = [self._hadoop, "fs"]
        for k, v in self._configs.items():
            cmd += ["-D", f"{k}={v}"]
        cmd += list(args)
        res = subprocess.run(cmd, capture_output=True, text=True,
                             check=False)
        if check and res.returncode != 0:
            raise RuntimeError(
                f"hadoop fs {' '.join(args)} failed "
                f"(rc={res.returncode}): {res.stderr.strip()}")
        return res

    def _loc(self, path):
        return os.path.join(self._root, path.lstrip("/"))

    def is_exist(self, path):
        if self._hadoop:
            return self._run("-test", "-e", path).returncode == 0
        return self._local.is_exist(self._loc(path))

    def is_dir(self, path):
        if self._hadoop:
            return self._run("-test", "-d", path).returncode == 0
        return self._local.is_dir(self._loc(path))

    def ls_dir(self, path):
        if self._hadoop:
            res = self._run("-ls", path)
            dirs, files = [], []
            for line in res.stdout.splitlines():
                parts = line.split()
                if len(parts) < 8:
                    continue
                name = parts[-1].rsplit("/", 1)[-1]
                (dirs if parts[0].startswith("d") else files).append(name)
            return dirs, files
        return self._local.ls_dir(self._loc(path))

    def mkdirs(self, path):
        if self._hadoop:
            self._run("-mkdir", "-p", path, check=True)
        else:
            self._local.mkdirs(self._loc(path))

    def delete(self, path):
        if self._hadoop:
            self._run("-rm", "-r", "-f", path, check=True)
        else:
            self._local.delete(self._loc(path))

    def mv(self, src, dst, overwrite=False, test_exists=True):
        if self._hadoop:
            if overwrite:
                self._run("-rm", "-r", "-f", dst)
            self._run("-mv", src, dst, check=True)
        else:
            self._local.mkdirs(os.path.dirname(self._loc(dst)))
            self._local.mv(self._loc(src), self._loc(dst),
                           overwrite=overwrite, test_exists=test_exists)

    def upload(self, local_path, fs_path):
        if self._hadoop:
            self._run("-put", "-f", local_path, fs_path, check=True)
        else:
            dst = self._loc(fs_path)
            self._local.mkdirs(os.path.dirname(dst))
            self._local.upload(local_path, dst)

    def download(self, fs_path, local_path):
        if self._hadoop:
            self._run("-get", fs_path, local_path, check=True)
        else:
            self._local.download(self._loc(fs_path), local_path)

    def touch(self, path, exist_ok=True):
        if self._hadoop:
            self._run("-touchz", path, check=True)
        else:
            dst = self._loc(path)
            self._local.mkdirs(os.path.dirname(dst))
            self._local.touch(dst, exist_ok=exist_ok)
