"""Filesystem abstraction for checkpoints (reference:
incubate/fleet/utils/fs.py / hdfs.py — FS base + LocalFS + HDFSClient).
Checkpoint-restart recovery (incubate/fleet/collective save_checkpoint)
writes through this interface; LocalFS covers shared-filesystem (NFS/GCS
-fuse) deployments, the standard TPU pattern. HDFS has no TPU-pod analog
— the shim raises with guidance instead of silently no-oping."""
import os
import shutil


class FS:
    def ls_dir(self, path):
        raise NotImplementedError

    def is_exist(self, path):
        raise NotImplementedError


class LocalFS(FS):
    """reference fs.py LocalFS."""

    def ls_dir(self, path):
        if not self.is_exist(path):
            return [], []
        dirs, files = [], []
        for e in sorted(os.listdir(path)):
            (dirs if os.path.isdir(os.path.join(path, e))
             else files).append(e)
        return dirs, files

    def is_exist(self, path):
        return os.path.exists(path)

    def is_dir(self, path):
        return os.path.isdir(path)

    def is_file(self, path):
        return os.path.isfile(path)

    def mkdirs(self, path):
        os.makedirs(path, exist_ok=True)

    def delete(self, path):
        if os.path.isdir(path):
            shutil.rmtree(path, ignore_errors=True)
        elif os.path.exists(path):
            os.remove(path)

    def rename(self, src, dst):
        os.replace(src, dst)

    def mv(self, src, dst, overwrite=False, test_exists=True):
        if test_exists and not self.is_exist(src):
            raise FileNotFoundError(src)
        if self.is_exist(dst):
            if not overwrite:
                raise FileExistsError(
                    f"mv destination {dst!r} exists (pass overwrite=True)")
            self.delete(dst)
        os.replace(src, dst)

    def upload(self, local_path, fs_path):
        if os.path.isdir(local_path):
            shutil.copytree(local_path, fs_path, dirs_exist_ok=True)
        else:
            shutil.copy2(local_path, fs_path)

    def download(self, fs_path, local_path):
        self.upload(fs_path, local_path)

    def touch(self, path, exist_ok=True):
        if not exist_ok and self.is_exist(path):
            raise FileExistsError(path)
        open(path, "a").close()


class HDFSClient(FS):
    """Placeholder with guidance (the reference shells out to the hadoop
    CLI; TPU deployments use shared/cloud filesystems via LocalFS)."""

    def __init__(self, hadoop_home=None, configs=None):
        raise NotImplementedError(
            "HDFS is not available in this environment; mount the store "
            "(NFS / gcsfuse) and use LocalFS — every checkpoint API takes "
            "an fs object, so the swap is one argument")
