"""Reference module path incubate/fleet/utils/hdfs.py — HDFSClient.
One implementation, shared with fluid.contrib.utils (both reference
modules wrap the same `hadoop fs` CLI)."""
from ....contrib.utils import HDFSClient  # noqa: F401

__all__ = ["HDFSClient"]
