"""Fleet utilities (reference: python/paddle/fluid/incubate/fleet/utils/)."""
from . import fs  # noqa: F401
from .fleet_util import FleetUtil  # noqa: F401
