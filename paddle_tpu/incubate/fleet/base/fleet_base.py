"""Fleet abstract base (reference incubate/fleet/base/fleet_base.py:
Fleet + DistributedOptimizer). The concrete fleets — Collective
(fleet/collective), ParameterServerFleet (fleet/parameter_server),
PSLibFleet (fleet/parameter_server/pslib) — implement this contract;
the bases exist for user subclassing and isinstance-style checks, as
in the reference."""
import abc

from .mode import Mode  # noqa: F401  (reference re-exports Mode here)

__all__ = ["Fleet", "DistributedOptimizer", "Mode"]


class Fleet(abc.ABC):
    """reference fleet_base.py Fleet: role lifecycle + distributed
    optimizer factory."""

    def __init__(self, mode=Mode.TRANSPILER):
        self._mode = mode
        self._role_maker = None

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def is_worker(self):
        return self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker.is_server()

    @abc.abstractmethod
    def init_worker(self):
        ...

    @abc.abstractmethod
    def init_server(self, *args, **kwargs):
        ...

    @abc.abstractmethod
    def run_server(self):
        ...

    @abc.abstractmethod
    def stop_worker(self):
        ...

    @abc.abstractmethod
    def distributed_optimizer(self, optimizer, strategy=None):
        ...


class DistributedOptimizer(abc.ABC):
    """reference fleet_base.py DistributedOptimizer: wraps a local
    optimizer; minimize() both optimizes and rewrites the program for
    the distributed runtime."""

    def __init__(self, optimizer, strategy=None):
        self._optimizer = optimizer
        self._strategy = strategy

    @abc.abstractmethod
    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        ...
