from . import role_maker  # noqa: F401
from . import mode  # noqa: F401
from .mode import Mode  # noqa: F401
from . import fleet_base  # noqa: F401
from .fleet_base import Fleet, DistributedOptimizer  # noqa: F401
