"""Fleet execution modes (reference incubate/fleet/base/mode.py)."""

__all__ = ["Mode"]


class Mode:
    """reference mode.py Mode: which fleet backend drives training."""
    TRANSPILER = 1
    PSLIB = 2
    COLLECTIVE = 3
