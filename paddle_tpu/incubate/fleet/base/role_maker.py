"""Role makers (reference:
python/paddle/fluid/incubate/fleet/base/role_maker.py — Role :30,
PaddleCloudRoleMaker :441 env-based, UserDefinedRoleMaker :876/:952).
The launcher (paddle_tpu/distributed/launch.py) sets the same PADDLE_*
environment contract the reference cloud launcher uses."""
import os


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_endpoints = []
        self._server_endpoints = []

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return list(self._worker_endpoints)

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)

    def get_current_endpoint(self):
        eps = (self._worker_endpoints if self.is_worker()
               else self._server_endpoints)
        return eps[self._current_id] if eps else ""

    def generate_role(self):
        pass


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-driven (reference role_maker.py:441): TRAINING_ROLE,
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS,
    PADDLE_PSERVERS_IP_PORT_LIST, POD_IP + PADDLE_PORT."""

    def __init__(self, is_collective=False):
        super().__init__()
        self._is_collective = is_collective
        self.generate_role()

    def generate_role(self):
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._worker_endpoints = [
            e for e in os.environ.get("PADDLE_TRAINER_ENDPOINTS",
                                      "").split(",") if e]
        self._server_endpoints = [
            e for e in os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST",
                                      "").split(",") if e]
        if role == "PSERVER":
            self._role = Role.SERVER
            cur = os.environ.get("PADDLE_CURRENT_ENDPOINT") or (
                os.environ.get("POD_IP", "127.0.0.1") + ":" +
                os.environ.get("PADDLE_PORT", "0"))
            self._current_id = (self._server_endpoints.index(cur)
                                if cur in self._server_endpoints else 0)
        else:
            self._role = Role.WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            n = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                   str(max(len(self._worker_endpoints), 1))))
            if not self._worker_endpoints:
                self._worker_endpoints = [""] * n

    def worker_num(self):
        return int(os.environ.get(
            "PADDLE_TRAINERS_NUM",
            str(max(len(self._worker_endpoints), 1))))


class UserDefinedRoleMaker(RoleMakerBase):
    """reference role_maker.py:876 — explicit role wiring, no env."""

    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None, worker_endpoints=None):
        super().__init__()
        self._current_id = int(current_id)
        self._role = role
        self._server_endpoints = list(server_endpoints or [])
        self._worker_endpoints = list(worker_endpoints or
                                      [""] * int(worker_num))

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)
