"""Role makers (reference:
python/paddle/fluid/incubate/fleet/base/role_maker.py — Role :30,
PaddleCloudRoleMaker :441 env-based, UserDefinedRoleMaker :876/:952).
The launcher (paddle_tpu/distributed/launch.py) sets the same PADDLE_*
environment contract the reference cloud launcher uses."""
import os


class Role:
    WORKER = 1
    SERVER = 2


class RoleMakerBase:
    def __init__(self):
        self._role = Role.WORKER
        self._current_id = 0
        self._worker_endpoints = []
        self._server_endpoints = []

    def is_worker(self):
        return self._role == Role.WORKER

    def is_server(self):
        return self._role == Role.SERVER

    def is_first_worker(self):
        return self.is_worker() and self._current_id == 0

    def worker_index(self):
        return self._current_id

    def server_index(self):
        return self._current_id

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)

    def server_num(self):
        return len(self._server_endpoints)

    def get_trainer_endpoints(self):
        return list(self._worker_endpoints)

    def get_pserver_endpoints(self):
        return list(self._server_endpoints)

    def get_current_endpoint(self):
        eps = (self._worker_endpoints if self.is_worker()
               else self._server_endpoints)
        return eps[self._current_id] if eps else ""

    def generate_role(self):
        pass


class PaddleCloudRoleMaker(RoleMakerBase):
    """Env-driven (reference role_maker.py:441): TRAINING_ROLE,
    PADDLE_TRAINER_ID / PADDLE_TRAINERS_NUM / PADDLE_TRAINER_ENDPOINTS,
    PADDLE_PSERVERS_IP_PORT_LIST, POD_IP + PADDLE_PORT."""

    def __init__(self, is_collective=False):
        super().__init__()
        self._is_collective = is_collective
        self.generate_role()

    def generate_role(self):
        role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._worker_endpoints = [
            e for e in os.environ.get("PADDLE_TRAINER_ENDPOINTS",
                                      "").split(",") if e]
        self._server_endpoints = [
            e for e in os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST",
                                      "").split(",") if e]
        if role == "PSERVER":
            self._role = Role.SERVER
            cur = os.environ.get("PADDLE_CURRENT_ENDPOINT") or (
                os.environ.get("POD_IP", "127.0.0.1") + ":" +
                os.environ.get("PADDLE_PORT", "0"))
            self._current_id = (self._server_endpoints.index(cur)
                                if cur in self._server_endpoints else 0)
        else:
            self._role = Role.WORKER
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
            n = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                   str(max(len(self._worker_endpoints), 1))))
            if not self._worker_endpoints:
                self._worker_endpoints = [""] * n

    def worker_num(self):
        return int(os.environ.get(
            "PADDLE_TRAINERS_NUM",
            str(max(len(self._worker_endpoints), 1))))


class UserDefinedRoleMaker(RoleMakerBase):
    """reference role_maker.py:876 — explicit role wiring, no env."""

    def __init__(self, current_id=0, role=Role.WORKER, worker_num=1,
                 server_endpoints=None, worker_endpoints=None):
        super().__init__()
        self._current_id = int(current_id)
        self._role = role
        self._server_endpoints = list(server_endpoints or [])
        self._worker_endpoints = list(worker_endpoints or
                                      [""] * int(worker_num))

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)


class UserDefinedCollectiveRoleMaker(RoleMakerBase):
    """reference role_maker.py:952 — explicit collective wiring: every
    node is a worker."""

    def __init__(self, current_id=0, worker_endpoints=None):
        super().__init__()
        self._current_id = int(current_id)
        self._role = Role.WORKER
        self._worker_endpoints = list(worker_endpoints or [""])

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)


class MPISymetricRoleMaker(RoleMakerBase):
    """reference role_maker.py MPISymetricRoleMaker: ranks split
    symmetrically — EVEN ranks are servers, ODD ranks are workers,
    worker_num == server_num == size // 2. Re-keyed off the launcher
    env (the reference reads mpi4py COMM_WORLD; there is no MPI on a
    TPU pod — the PADDLE_TRAINER_* contract carries the same
    rank/size info)."""

    def __init__(self):
        super().__init__()
        rank = int(os.environ.get("PADDLE_TRAINER_ID", "0"))
        eps = [e for e in os.environ.get("PADDLE_TRAINER_ENDPOINTS",
                                         "").split(",") if e]
        size = int(os.environ.get("PADDLE_TRAINERS_NUM",
                                  str(max(len(eps), 2))))
        if size % 2 != 0:
            raise ValueError(
                f"MPISymetricRoleMaker needs an even world size "
                f"(got {size}): even ranks serve, odd ranks train")
        eps = eps or [""] * size
        self._server_endpoints = eps[0::2]
        self._worker_endpoints = eps[1::2]
        self._role = Role.SERVER if rank % 2 == 0 else Role.WORKER
        self._current_id = rank // 2

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)

    def server_num(self):
        return max(len(self._server_endpoints), 1)


class GeneralRoleMaker(RoleMakerBase):
    """reference role_maker.py GeneralRoleMaker: env-driven like
    PaddleCloudRoleMaker but with explicit endpoint-list kwargs
    overriding the environment."""

    def __init__(self, current_id=None, role=None,
                 worker_endpoints=None, server_endpoints=None, **kwargs):
        super().__init__()
        env_role = os.environ.get("TRAINING_ROLE", "TRAINER").upper()
        self._role = role if role is not None else (
            Role.SERVER if env_role == "PSERVER" else Role.WORKER)
        self._worker_endpoints = list(worker_endpoints or [
            e for e in os.environ.get("PADDLE_TRAINER_ENDPOINTS",
                                      "").split(",") if e])
        self._server_endpoints = list(server_endpoints or [
            e for e in os.environ.get("PADDLE_PSERVERS_IP_PORT_LIST",
                                      "").split(",") if e])
        if current_id is not None:
            self._current_id = int(current_id)
        elif self._role == Role.WORKER:
            self._current_id = int(os.environ.get("PADDLE_TRAINER_ID",
                                                  "0"))
        else:
            cur = os.environ.get("PADDLE_CURRENT_ENDPOINT", "")
            self._current_id = (self._server_endpoints.index(cur)
                                if cur in self._server_endpoints else 0)

    def worker_num(self):
        return max(len(self._worker_endpoints), 1)
