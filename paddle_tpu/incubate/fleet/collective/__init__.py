"""Fleet collective mode (reference:
python/paddle/fluid/incubate/fleet/collective/__init__.py — Collective
:64, CollectiveOptimizer :384, DistributedStrategy :334; fleet_base.py:34).

TPU mapping: fleet.init wires jax.distributed (coordinator = trainer 0's
endpoint, Gloo/ICI backend chosen by jax) so every process sees the global
device set; distributed_optimizer(...).minimize builds the program as
usual, and fleet.main_program is a CompiledProgram over a global dp mesh —
GSPMD emits the gradient all-reduces the reference's transpiler inserted as
c_allreduce_sum ops (transpiler/collective.py:209). Each trainer feeds its
local batch; the executor assembles the global array
(framework/executor.py _shard_feed)."""
import os

from ..base.role_maker import PaddleCloudRoleMaker, RoleMakerBase


class DistributedStrategy:
    """reference collective/__init__.py:334 (knobs that map to XLA are
    honored; stream/fusion knobs are XLA's job)."""

    def __init__(self):
        self.mode = "collective"
        self.collective_mode = "grad_allreduce"
        self.nccl_comm_num = 1
        self.use_local_sgd = False
        self.local_sgd_steps = 1
        self.forward_recompute = False
        self.recompute_checkpoints = []
        self.use_amp = False
        self.amp_loss_scaling = 2 ** 15


class TrainStatus:
    """reference collective/__init__.py:49 — the tiny restart token saved
    next to a checkpoint (recovery = reload last checkpoint + status)."""

    def __init__(self, epoch_no=-1):
        self._epoch_no = int(epoch_no)

    def next(self):
        return self._epoch_no + 1

    def __eq__(self, other):
        return isinstance(other, TrainStatus) and \
            self._epoch_no == other._epoch_no

    def __ne__(self, other):
        return not self == other


class Collective:
    def __init__(self):
        self._role_maker = None
        self._compiled = None
        self._origin_program = None
        self._strategy = None
        self._inited = False

    # -- lifecycle (fleet_base.py:34 contract) ---------------------------
    def init(self, role_maker=None):
        if role_maker is None:
            role_maker = PaddleCloudRoleMaker(is_collective=True)
        assert isinstance(role_maker, RoleMakerBase)
        self._role_maker = role_maker
        n = role_maker.worker_num()
        if n > 1:
            import jax
            eps = role_maker.get_trainer_endpoints()
            coordinator = eps[0] if eps and eps[0] else None
            assert coordinator, \
                "multi-process fleet needs PADDLE_TRAINER_ENDPOINTS"
            jax.distributed.initialize(
                coordinator_address=coordinator,
                num_processes=n,
                process_id=role_maker.worker_index())
        self._inited = True

    def is_worker(self):
        return self._role_maker.is_worker()

    def is_server(self):
        return self._role_maker.is_server()

    def is_first_worker(self):
        return self._role_maker.is_first_worker()

    def worker_index(self):
        return self._role_maker.worker_index()

    def worker_num(self):
        return self._role_maker.worker_num()

    def init_worker(self):
        pass

    def stop_worker(self):
        pass

    def distributed_optimizer(self, optimizer, strategy=None):
        assert self._inited, "call fleet.init(role) first"
        self._strategy = strategy or DistributedStrategy()
        return CollectiveOptimizer(self, optimizer, self._strategy)

    @property
    def main_program(self):
        assert self._compiled is not None, \
            "call distributed_optimizer(...).minimize(loss) first"
        return self._compiled

    @property
    def startup_program(self):
        from ....framework.core import default_startup_program
        return default_startup_program()

    def save_persistables(self, executor, dirname, main_program=None):
        from .... import io
        io.save_persistables(executor, dirname,
                             main_program or self._origin_program)

    # -- checkpoint-restart recovery (reference collective/__init__.py
    # :166 save_checkpoint/load_checkpoint with TrainStatus; recovery =
    # reload the newest checkpoint, §5.3 of the reference's failure
    # model) --------------------------------------------------------------
    _KEEP_UNSET = object()

    def _saver(self, path, max_to_keep=_KEEP_UNSET):
        from .... import io
        # one saver per path: repeated async saves share the number
        # reservation (no staging collisions) and checkpoint_wait() joins
        # every pending write, not just the newest saver's. Only a save
        # (which passes max_to_keep) may change retention policy — a
        # load_checkpoint must not reset it under a pending async save.
        savers = getattr(self, "_savers", None)
        if savers is None:
            savers = self._savers = {}
        saver = savers.get(path)
        if saver is None:
            keep = None if max_to_keep is self._KEEP_UNSET else max_to_keep
            saver = savers[path] = io.CheckpointSaver(
                path, max_to_keep=keep,
                prefix="__paddle_checkpoint__")
        elif max_to_keep is not self._KEEP_UNSET:
            saver.max_to_keep = (None if max_to_keep is None
                                 else int(max_to_keep))
        return saver

    def save_checkpoint(self, executor, path, train_status,
                        main_program=None, fs=None, local_cache_path=None,
                        remain_all_checkpoint=True, max_to_keep=_KEEP_UNSET,
                        async_save=False):
        """Numbered atomic checkpoint (io.CheckpointSaver: staged
        directory + manifest + atomic rename, so a preempted worker never
        leaves a half-written checkpoint that load_checkpoint would
        trust). ``async_save`` snapshots synchronously and writes on a
        background thread — call ``checkpoint_wait()`` before exiting.
        ``max_to_keep`` prunes old checkpoints (``remain_all_checkpoint=
        False`` is the legacy spelling of ``max_to_keep=1``); omitting it
        keeps the path's current retention policy (initially: keep
        all)."""
        if not remain_all_checkpoint:
            max_to_keep = 1
        saver = self._saver(path, max_to_keep=max_to_keep)
        extra = {"train_status.json":
                 {"epoch_no": train_status._epoch_no}}
        kwargs = dict(main_program=main_program or self._origin_program,
                      extra_files=extra)
        if async_save:
            return saver.save_async(executor, **kwargs)
        return saver.save(executor, **kwargs)

    def checkpoint_wait(self):
        """Join pending async checkpoint writes (re-raises failures)."""
        for saver in getattr(self, "_savers", {}).values():
            saver.wait()

    def load_checkpoint(self, executor, path, trainer_id=0,
                        main_program=None, fs=None, local_cache_path=None,
                        ignore_empty=True):
        import json
        import os
        saver = self._saver(path)
        no, ckpt = saver.latest()
        if no is None:
            if ignore_empty:
                return TrainStatus(-1)
            raise RuntimeError(f"no checkpoint under {path}")
        from .... import io
        # typed full-state load: a checkpoint missing optimizer slabs or
        # the RNG stream record raises CheckpointIncompleteError instead
        # of silently resuming with reset training state
        io.load_checkpoint(executor, ckpt,
                           main_program=main_program or
                           self._origin_program)
        status_path = os.path.join(ckpt, "train_status.json")
        io._verify_against_manifest(ckpt, "train_status.json",
                                    io._read_manifest(ckpt))
        with open(status_path) as f:
            return TrainStatus(json.load(f)["epoch_no"])

    def save_inference_model(self, executor, dirname, feeded_var_names,
                             target_vars, main_program=None,
                             export_for_deployment=True):
        from .... import io
        io.save_inference_model(dirname, feeded_var_names, target_vars,
                                executor,
                                main_program or self._origin_program)


class CollectiveOptimizer:
    """reference CollectiveOptimizer (collective/__init__.py:384): minimize
    + compile the program for the global mesh."""

    def __init__(self, fleet_obj, inner, strategy):
        self._fleet = fleet_obj
        self._inner = inner
        self._strategy = strategy

    def minimize(self, loss, startup_program=None, parameter_list=None,
                 no_grad_set=None):
        import jax
        from ....optimizer import RecomputeOptimizer
        from ....parallel.compiler import CompiledProgram
        from ....parallel.mesh import Mesh
        import numpy as np

        inner = self._inner
        if self._strategy.forward_recompute:
            inner = RecomputeOptimizer(inner)
            inner._set_checkpoints(self._strategy.recompute_checkpoints)
        result = inner.minimize(loss, startup_program, parameter_list,
                                no_grad_set)
        program = loss.block.program
        self._fleet._origin_program = program
        mesh = Mesh(np.asarray(jax.devices()), ("dp",))
        self._fleet._compiled = CompiledProgram(program).with_data_parallel(
            loss_name=loss.name, mesh=mesh)
        return result

    def __getattr__(self, item):
        return getattr(self._inner, item)


fleet = Collective()

# virtual subclasses of the fleet ABC contract (base/fleet_base.py)
from ..base.fleet_base import Fleet as _Fleet  # noqa: E402
from ..base.fleet_base import DistributedOptimizer as _DO  # noqa: E402
_Fleet.register(Collective)
_DO.register(CollectiveOptimizer)
