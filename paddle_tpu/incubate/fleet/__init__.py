"""Fleet distributed-training API (reference:
python/paddle/fluid/incubate/fleet/ — base/fleet_base.py:34)."""
from . import base  # noqa: F401
from . import utils  # noqa: F401
