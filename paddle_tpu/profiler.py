"""Profiler surface (reference: python/paddle/fluid/profiler.py —
profiler context manager :253, start_profiler :129, stop_profiler :180,
reset_profiler :113; C++ host/device tracers platform/profiler.h:206 +
CUPTI device_tracer.h:41, summary tables profiler_helper.h).

TPU mapping: the device-side tracer is jax.profiler (XLA xplane traces,
viewable in TensorBoard/Perfetto — the timeline.py analog); the host-side
event table is kept here: Executor.run reports compile/execute spans per
program, RecordEvent covers user scopes, and `profile_program` produces
the reference-style PER-OP table by interpreting a program once with
per-op timers (normal runs stay one fused XLA module, so op cost only
exists when you ask for it)."""
import contextlib
import threading
import time

import numpy as np

_events = {}          # name -> [calls, total_s, max_s, min_s]
# (name, start_s, end_s, tid[, trace_id, span_id, parent_id]) — the
# unified timeline source: profiler events AND sampled request-trace
# spans (observability.tracing) land here, so tools/timeline.py renders
# one Chrome trace interleaving both. A deque: at the _MAX_SPANS cap a
# bounded PROFILING session keeps the first N (a run's head is what a
# bench wants), while the always-on traced stream of a long-lived
# server rotates the OLDEST span out (a postmortem wants the newest) —
# either way drops are counted, never silent
import collections as _collections
_spans = _collections.deque()
# the traced stream appends from server threads while a driver may be
# dumping/clearing — every structural span-table access takes this lock
# (appends are rare enough that a ~100ns lock is in the noise)
_spans_lock = threading.Lock()
_MAX_SPANS = 200000   # bound memory on long profiled runs
_spans_dropped = 0    # spans lost to the _MAX_SPANS cap since reset
_spans_dropped_cum = 0  # process-lifetime drop total: reset_profiler
                        # zeroes the session counter only, so the
                        # exported telemetry_spans_dropped_total stays
                        # monotonic (Prometheus counter contract)
_active = False
_trace_dir = None

# step-time histogram: log2 buckets over per-step wall time, fed by the
# training loop (Executor.run_steps amortizes one slab measurement over
# its K steps). Bounded by construction — counters, not samples.
_STEP_BUCKETS_MS = (0.01, 0.03, 0.1, 0.3, 1.0, 3.0, 10.0, 30.0, 100.0,
                    300.0, 1000.0, 3000.0, 10000.0)
_step_hist = [0] * (len(_STEP_BUCKETS_MS) + 1)
_step_stats = [0, 0.0]  # count, total_s


def _record(name, seconds, start=None):
    global _spans_dropped, _spans_dropped_cum
    if not _active:
        return
    row = _events.setdefault(name, [0, 0.0, 0.0, float("inf")])
    row[0] += 1
    row[1] += seconds
    row[2] = max(row[2], seconds)
    row[3] = min(row[3], seconds)
    if start is not None:
        with _spans_lock:
            if len(_spans) < _MAX_SPANS:
                _spans.append((name, start, start + seconds,
                               threading.get_ident()))
            else:
                # count the loss: silent truncation reads as full
                # coverage
                _spans_dropped += 1
                _spans_dropped_cum += 1


def record_span(name, start_s, end_s, trace=None):
    """Append a completed span to the unified span table. ``trace`` is
    an optional ``(trace_id, span_id, parent_id)`` triple from
    ``observability.tracing``; TRACED spans record even while profiling
    is inactive (they are the always-on sampled request stream).
    Untraced spans record only under an active profiler. At the
    ``_MAX_SPANS`` cap an active profiling session keeps the FIRST N
    spans, the always-on traced stream rotates the oldest out — a
    long-lived server's stream never silently dies; drops are counted
    either way (:func:`spans_dropped`)."""
    global _spans_dropped, _spans_dropped_cum
    if trace is None and not _active:
        return
    row = (name, float(start_s), float(end_s), threading.get_ident())
    with _spans_lock:
        if len(_spans) >= _MAX_SPANS:
            _spans_dropped += 1
            _spans_dropped_cum += 1
            if _active:
                return          # profiling session: keep the run's head
            _spans.popleft()    # traced stream: keep the newest
        _spans.append(row if trace is None else row + tuple(trace))


# counter track: (name, t_s, value) samples — the memory profiler's
# hbm_live_bytes live-set timeline rides here so tools/timeline.py can
# render a Perfetto counter track under the op-level spans. Bounded
# like the span table; recorded only under an active profiler (the
# always-on path is the measured-op TABLE, not the counter track).
_counters = _collections.deque()
_MAX_COUNTERS = 100000


def record_counter(name, t_s, value):
    """Append one counter sample to the counter track (no-op while
    profiling is inactive; silently bounded at ``_MAX_COUNTERS``)."""
    if not _active:
        return
    with _spans_lock:
        if len(_counters) >= _MAX_COUNTERS:
            return
        _counters.append((str(name), float(t_s), float(value)))


def counters():
    """Snapshot of the counter track (name, t_s, value) rows."""
    with _spans_lock:
        return [list(c) for c in _counters]


def spans_dropped():
    """Spans lost to the ``_MAX_SPANS`` cap since the last
    ``reset_profiler()``."""
    return _spans_dropped


def spans_dropped_total():
    """Process-lifetime span-drop total — NEVER reset (the monotonic
    counter the metrics exposition exports)."""
    return _spans_dropped_cum


def is_profiling():
    return _active


def record_duration(name, seconds):
    """Record an externally timed span into the event table (no-op while
    profiling is off). The serving runtime's stage histograms feed their
    measurements through here, so a ``profiler.profiler()`` block around
    live traffic shows ``serving/*`` rows in the summary table."""
    _record(name, float(seconds))


def record_step_time(seconds, steps=1):
    """Accumulate `steps` training steps of `seconds` each into the
    step-time histogram (no-op while profiling is off). The fused loop
    measures once per slab and amortizes over its K steps."""
    if not _active:
        return
    import bisect
    i = bisect.bisect_left(_STEP_BUCKETS_MS, float(seconds) * 1e3)
    _step_hist[i] += int(steps)
    _step_stats[0] += int(steps)
    _step_stats[1] += float(seconds) * int(steps)


def step_time_histogram():
    """{"count", "mean_ms", "buckets": [(le_ms, n), ..., (inf, n)]} of
    every step recorded since the last reset_profiler()."""
    buckets = [(le, n) for le, n in zip(_STEP_BUCKETS_MS, _step_hist)]
    buckets.append((float("inf"), _step_hist[-1]))
    count = _step_stats[0]
    return {"count": count,
            "mean_ms": (_step_stats[1] / count * 1e3) if count else 0.0,
            "buckets": buckets}


@contextlib.contextmanager
def record_event(name):
    """RAII event span (reference platform::RecordEvent)."""
    t0 = time.perf_counter()
    try:
        yield
    finally:
        _record(name, time.perf_counter() - t0, start=t0)


def reset_profiler():
    """reference profiler.py:113."""
    global _spans_dropped
    _events.clear()
    with _spans_lock:
        _spans.clear()
        _counters.clear()
        _spans_dropped = 0
    for i in range(len(_step_hist)):
        _step_hist[i] = 0
    _step_stats[0] = 0
    _step_stats[1] = 0.0


def start_profiler(state="All", tracer_option="Default",
                   trace_dir=None):
    """reference profiler.py:129. `state` kept for parity ("CPU"/"GPU"/
    "All" pick the same path here — XLA owns the device). With trace_dir,
    also starts a jax.profiler xplane trace."""
    global _active, _trace_dir, _spans_dropped, _spans_dropped_cum
    if state not in ("CPU", "GPU", "All"):
        raise ValueError("state must be 'CPU', 'GPU' or 'All'")
    # the always-on traced stream may have filled the span table while
    # profiling was off; the session cap policy keeps the FIRST N, so
    # starting against a full table would drop 100% of the session's
    # spans. Trim the backlog to its newest half: every session starts
    # with headroom, recent traced spans stay for interleaving, and the
    # drops are counted, never silent.
    with _spans_lock:
        keep = _MAX_SPANS // 2
        while len(_spans) > keep:
            _spans.popleft()
            _spans_dropped += 1
            _spans_dropped_cum += 1
    _active = True
    if trace_dir:
        import jax
        _trace_dir = trace_dir
        jax.profiler.start_trace(trace_dir)


def stop_profiler(sorted_key=None, profile_path="/tmp/profile"):
    """reference profiler.py:180: stop + print the summary table, write
    the recorded spans to `profile_path` (the artifact
    tools/timeline.py converts to a Chrome trace — the reference's
    profiler proto -> tools/timeline.py flow), and finish the xplane
    trace when one was started."""
    global _active, _trace_dir
    _active = False
    if _trace_dir:
        import jax
        jax.profiler.stop_trace()
        print(f"[profiler] xplane trace written to {_trace_dir} "
              f"(load in TensorBoard / Perfetto)")
        _trace_dir = None
    with _spans_lock:       # a traced request may append mid-dump
        span_snapshot = [list(s) for s in _spans]
        counter_snapshot = [list(c) for c in _counters]
    if profile_path and span_snapshot:
        import json
        with open(profile_path, "w") as f:
            json.dump({"spans": span_snapshot,
                       "counters": counter_snapshot,
                       "dropped": _spans_dropped}, f)
    if _spans_dropped:
        print(f"[profiler] {_spans_dropped} spans dropped (span table "
              f"capped at {_MAX_SPANS}; the event table and step "
              f"histogram still cover every call)")
    rows = summary(sorted_key)
    if rows:
        print(_format_table(rows))
    hist = step_time_histogram()
    if hist["count"]:
        buckets = ", ".join(
            (f"<={le:g}ms: {n}" if le != float("inf")
             else f">{_STEP_BUCKETS_MS[-1]:g}ms: {n}")
            for le, n in hist["buckets"] if n)
        print(f"[profiler] step time: {hist['count']} steps, mean "
              f"{hist['mean_ms']:.3f}ms [{buckets}]")
    return rows


def summary(sorted_key=None):
    rows = [(name, c, tot, tot / c, mx, mn)
            for name, (c, tot, mx, mn) in _events.items()]
    key = {None: lambda r: 0, "calls": lambda r: -r[1],
           "total": lambda r: -r[2], "ave": lambda r: -r[3],
           "max": lambda r: -r[4], "min": lambda r: -r[5]}.get(sorted_key)
    if key is None:
        raise ValueError(f"unknown sorted_key {sorted_key!r}")
    return sorted(rows, key=key)


def _format_table(rows):
    head = (f"{'Event':<44} {'Calls':>7} {'Total(ms)':>11} "
            f"{'Ave(ms)':>9} {'Max(ms)':>9} {'Min(ms)':>9}")
    lines = ["-------------------------     Profiling Report     "
             "-------------------------", head]
    for name, c, tot, ave, mx, mn in rows:
        lines.append(f"{name[:44]:<44} {c:>7} {tot * 1e3:>11.3f} "
                     f"{ave * 1e3:>9.3f} {mx * 1e3:>9.3f} "
                     f"{mn * 1e3:>9.3f}")
    return "\n".join(lines)


@contextlib.contextmanager
def profiler(state="All", sorted_key=None, profile_path="/tmp/profile",
             tracer_option="Default", trace_dir=None):
    """reference profiler.py:253 context manager."""
    start_profiler(state, tracer_option, trace_dir=trace_dir)
    try:
        yield
    finally:
        stop_profiler(sorted_key, profile_path)


@contextlib.contextmanager
def cuda_profiler(*a, **k):
    """reference profiler.py:39 — CUDA-specific; no TPU analog, no-op."""
    yield


def profile_program(program, feed, scope=None, repeat=1, sync=True):
    """Reference-style PER-OP cost table: interpret the global block once,
    timing each op's lowering+execution eagerly (block_until_ready between
    ops). Normal execution fuses everything into one XLA module, so this
    is the explicit op-cost probe (reference pays this bookkeeping on
    every run — profiler.cc RecordEvent around each op->Run). One
    replay loop serves this, FLAGS_profile_ops sampling, and
    profile_program(measured=True): observability.profiling.
    measure_op_times (side effects allowed here — this walk IS the
    execution the caller asked for, not a replay next to one).
    Returns [(op_type, calls, total_s)] sorted by total."""
    from .framework.executor import global_scope
    from .observability import profiling as _profiling

    scope = scope or global_scope()
    env = {}
    for name, val in scope.items():
        env[name] = val
    for name, val in (feed or {}).items():
        env[name] = np.asarray(val)
    per_op = {}
    for _ in range(repeat):
        out = _profiling.measure_op_times(
            program, env, tag=f"program_{program._uid}",
            allow_side_effects=True, sync=sync)
        for r in out["rows"]:
            row = per_op.setdefault(r["type"], [0, 0.0])
            row[0] += 1
            row[1] += r["ms"] / 1e3
    rows = sorted(((t, c, tot) for t, (c, tot) in per_op.items()),
                  key=lambda r: -r[2])
    return rows
