"""Input-pipeline stall profiler: measure the host-bound data path.

ROADMAP item 5 diagnoses widedeep's 0.008 MFU as a host-bound input
pipeline — but until now no instrument PROVED it. This module hangs
cheap wait/occupancy telemetry on the two producer/consumer queues the
data path runs through (``dataio.decorator.buffered`` and
``dataio.reader._QueueIterator``):

- ``dataio_queue_occupancy_ratio{queue}`` — queue fill level, sampled
  every 16th consumer pull (a persistently EMPTY queue = producer-bound
  = the training loop will stall; persistently FULL = consumer-bound =
  the pipeline has headroom),
- ``dataio_producer_wait_ms{queue}`` / ``dataio_consumer_wait_ms{queue}``
  — wait histograms, observed ONLY when a put/get actually blocked (the
  balanced fast path pays one ``put_nowait``/``get_nowait`` try),
- a ``data_stall`` flight-recorder event + ``dataio_data_stalls_total``
  when consumer waits dominate a window: over any window of at least
  ``FLAGS_dataio_stall_window_s`` seconds, consumer-blocked time above
  ``FLAGS_dataio_stall_ratio`` of wall flags the window — the moment
  "training is input-bound" becomes a recorded, timestamped fact,
- a ``dataio/queue_depth/<queue>`` Perfetto counter track under an
  active profiler, so ``tools/timeline.py`` shows the queue draining
  against the slab spans.

The goodput ledger's ``data_stall`` category is measured separately (at
the supervisor's iterator pull) — this module answers WHY that category
is large, per queue, without double-charging the ledger.
"""
import time

from ..flags import flag as _flag
from .metrics import default_registry as _registry
from .recorder import flight_recorder as _flightrec

_WAIT_BOUNDS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                   100.0, 250.0, 500.0, 1000.0, 5000.0)

_OCC = _registry().gauge(
    "dataio_queue_occupancy_ratio",
    "input-pipeline queue fill level (size/capacity) at the last "
    "sampled consumer pull, by queue",
    labels=("queue",), max_series=16)
_PROD_WAIT = _registry().histogram(
    "dataio_producer_wait_ms",
    "time an input-pipeline producer spent blocked on a full queue "
    "(consumer-bound pipeline), by queue",
    labels=("queue",), bounds=_WAIT_BOUNDS_MS, max_series=16)
_CONS_WAIT = _registry().histogram(
    "dataio_consumer_wait_ms",
    "time an input-pipeline consumer spent blocked on an empty queue "
    "(producer-bound pipeline — the training loop is data-stalled), "
    "by queue",
    labels=("queue",), bounds=_WAIT_BOUNDS_MS, max_series=16)
_STALLS = _registry().counter(
    "dataio_data_stalls_total",
    "windows in which consumer waits dominated wall time "
    "(FLAGS_dataio_stall_window_s / FLAGS_dataio_stall_ratio) — each "
    "one also lands a data_stall flight-recorder event",
    labels=("queue",), max_series=16)


class StallTracker:
    """Per-queue wait accounting + stall-window detection. One tracker
    per queue instance; metric families are shared (labeled by the
    queue's role name, e.g. ``buffered`` / ``dataloader``)."""

    def __init__(self, queue_label, capacity):
        self.label = str(queue_label)
        self.capacity = max(int(capacity), 1)
        self._labels = (self.label,)
        self._n_pulls = 0
        self._win_t0 = time.perf_counter()
        self._win_wait = 0.0

    # -- wait observations (called only when a block actually happened)
    def producer_wait(self, seconds):
        _PROD_WAIT.observe(float(seconds) * 1e3, labels=self._labels)

    def consumer_wait(self, seconds):
        s = float(seconds)
        _CONS_WAIT.observe(s * 1e3, labels=self._labels)
        self._win_wait += s
        self._window_tick(time.perf_counter())

    def _window_tick(self, now):
        """Close the current stall window when it has run its span.
        Ticked from EVERY consumer pull (blocking or not) — a window
        must never stretch across minutes of healthy pipeline and
        dilute a real stall below the flag threshold."""
        elapsed = now - self._win_t0
        if elapsed < float(_flag("dataio_stall_window_s")):
            return
        frac = self._win_wait / elapsed if elapsed > 0 else 0.0
        if self._win_wait > 0 \
                and frac >= float(_flag("dataio_stall_ratio")):
            _STALLS.inc(labels=self._labels)
            _flightrec().record(
                "data_stall", queue=self.label,
                wait_ms=round(self._win_wait * 1e3, 3),
                window_s=round(elapsed, 3),
                fraction=round(frac, 4))
        self._win_t0 = now
        self._win_wait = 0.0

    def sample_occupancy(self, qsize):
        """Sample the queue fill level (every 16th pull — a gauge set
        per sample would make telemetry the hot path). Also advances
        the stall window on every pull so healthy stretches close
        their (empty) windows instead of accumulating into the next
        stall's denominator."""
        self._window_tick(time.perf_counter())
        self._n_pulls += 1
        if (self._n_pulls - 1) & 15:   # first pull, then every 16th
            return
        _OCC.set(min(int(qsize) / self.capacity, 1.0),
                 labels=self._labels)
        from .. import profiler as _prof
        if _prof.is_profiling():
            _prof.record_counter(f"dataio/queue_depth/{self.label}",
                                 time.perf_counter(), int(qsize))
