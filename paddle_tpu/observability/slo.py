"""Rule-driven SLO monitor: metric streams -> typed alert events.

The fleet routes on live gauges (PR 11) but nothing watched those
gauges for SLO breaches — a regressed replica degraded p99 silently
until a human read a dashboard. Following "The Tail at Scale" (latency
SLOs must be enforced by machinery, not dashboards) and Autopilot
(EuroSys 2020 — remediation driven by continuously evaluated service
signals), :class:`SloMonitor` evaluates declarative :class:`SloRule`\\ s
on a supervised loop and turns threshold crossings into:

- ``slo_breach`` / ``slo_recovered`` flight-recorder events (the
  postmortem trail),
- ``slo_breached_total{scope, rule}`` and
  ``slo_rule_state{scope, rule}`` registry metrics (dashboards/alerts),
- an optional callback (the remediation hook — the serving ``Router``
  consumes a replica's breach state as a dispatch-score penalty).

Rule sources (checked in this order):

- ``getter`` — any callable returning a float (or None = no data);
  the per-instance escape hatch: several in-process servers share one
  process registry, so per-server signals (queue depth, kvpool
  occupancy) read the server object directly.
- ``hist`` — a ``serving.metrics.LatencyHistogram``: the rule value is
  the ``q`` quantile over the observations SINCE THE LAST evaluation
  (the ``histogram_quantile(rate(...))`` idiom) — a cumulative
  histogram can never recover, a windowed one can. An empty window is
  "no data".
- ``metric`` (+ ``labels``) — a family in a ``MetricsRegistry``
  (native or collector-declared): ``source="value"`` reads the current
  counter/gauge, ``source="rate"`` the per-second delta between
  evaluations, ``source="quantile"`` the windowed bucket-delta
  quantile of a registry histogram.

Breach semantics: the condition must hold for ``for_s`` seconds
(Prometheus ``for:``) before the rule trips; recovery is immediate
once the condition reads false or the source goes silent ("no data" is
healthy — an idle replica is not a breached replica; pair with the
utilization staleness flag for idle-vs-dead).
"""
import threading
import time

from ..flags import flag as _flag
from .metrics import default_registry
from .recorder import flight_recorder as _flightrec

# 256, not the default 64: every InferenceServer mints a monitor scope
# with several rules, and an in-process fleet (tests, bench, embedded
# replicas) legitimately churns through far more than 64 (scope, rule)
# pairs — overflowing the cap folds a NEW server's series into _other
# and its breach state reads as permanently 0 (the kvpool families hit
# the same wall in PR 11)
_BREACHED = default_registry().counter(
    "slo_breached_total",
    "SLO rule breach transitions (ok -> breached), by monitor scope "
    "and rule",
    labels=("scope", "rule"), max_series=256)
_STATE = default_registry().gauge(
    "slo_rule_state",
    "current SLO rule state (0 = ok, 1 = breached), by monitor scope "
    "and rule",
    labels=("scope", "rule"), max_series=256)

_OPS = {
    "<": lambda v, t: v < t,
    "<=": lambda v, t: v <= t,
    ">": lambda v, t: v > t,
    ">=": lambda v, t: v >= t,
}


class SloRule:
    """One declarative rule: ``value <op> threshold`` held ``for_s``
    seconds = breach. Exactly one source: ``getter``, ``hist``, or
    ``metric`` (see module docstring)."""

    __slots__ = ("name", "op", "threshold", "for_s", "metric", "labels",
                 "source", "q", "getter", "hist")

    def __init__(self, name, op, threshold, *, metric=None, labels=(),
                 source="value", q=0.99, getter=None, hist=None,
                 for_s=0.0):
        if op not in _OPS:
            raise ValueError(f"rule {name!r}: op must be one of "
                             f"{sorted(_OPS)}, got {op!r}")
        if source not in ("value", "rate", "quantile"):
            raise ValueError(f"rule {name!r}: unknown source {source!r}")
        if getter is None and hist is None and metric is None:
            raise ValueError(f"rule {name!r} needs a getter, hist, or "
                             f"metric source")
        self.name = str(name)
        self.op = op
        self.threshold = float(threshold)
        self.for_s = float(for_s)
        self.metric = metric
        self.labels = tuple(labels)
        self.source = source
        self.q = float(q)
        self.getter = getter
        self.hist = hist


def _bucket_quantile(bounds, counts, q):
    """q-quantile (0..1) over per-bucket counts (NOT cumulative), with
    the standard linear interpolation; None when the window is empty.
    The overflow bucket interpolates to the last finite bound (the
    Prometheus convention)."""
    total = sum(counts)
    if not total:
        return None
    target = total * q
    seen = 0
    for i, c in enumerate(counts):
        if not c:
            continue
        if seen + c >= target:
            lo = bounds[i - 1] if i > 0 else 0.0
            hi = bounds[i] if i < len(bounds) else bounds[-1]
            return lo + (max(hi, lo) - lo) * ((target - seen) / c)
        seen += c
    return bounds[-1]


class SloMonitor:
    """Evaluates a rule set on a supervised loop (or explicitly via
    :meth:`evaluate_once` — the deterministic test/embedding path).

    ``on_event(rule, breached, value)`` fires on every transition.
    ``scope`` labels this monitor's metric series (several in-process
    servers must not collide on one gauge)."""

    def __init__(self, rules, *, registry=None, scope="default",
                 poll_s=None, on_event=None):
        self.rules = list(rules)
        names = [r.name for r in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate rule names: {sorted(names)}")
        self.registry = registry or default_registry()
        self.scope = str(scope)
        self.poll_s = float(poll_s if poll_s is not None
                            else _flag("slo_poll_s"))
        self.on_event = on_event
        self._state = {r.name: {"breached": False, "pending_since": None,
                                "value": None, "since": None}
                       for r in self.rules}
        # per-rule window memory for rate/quantile sources
        self._prev = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread = None
        self.evaluations = 0
        for r in self.rules:
            _STATE.set(0, labels=(self.scope, r.name))

    # -- sources ----------------------------------------------------------
    def _registry_samples(self, name, scrape):
        """One registry scrape is shared by every metric rule of an
        evaluation pass (collect() runs every scrape-time collector in
        the process — paying it per RULE per poll would make rule count
        a scrape multiplier)."""
        if scrape.get("_cat") is None:
            scrape["_cat"] = self.registry.collect()
        fam = scrape["_cat"].get(name)
        return fam["samples"] if fam else []

    def _match(self, samples, labels):
        for values, payload in samples:
            if tuple(values) == tuple(labels):
                return payload
        return None

    def _value(self, rule, now, scrape):
        """Current rule value, or None = no data this window."""
        if rule.getter is not None:
            return rule.getter()
        if rule.hist is not None:
            with rule.hist._lock:
                counts = list(rule.hist._counts)
            prev = self._prev.get(rule.name)
            self._prev[rule.name] = ("hist", now, counts)
            if prev is None:
                window = counts
            else:
                window = [a - b for a, b in zip(counts, prev[2])]
            return _bucket_quantile(rule.hist.bounds_ms, window, rule.q)
        payload = self._match(self._registry_samples(rule.metric,
                                                     scrape),
                              rule.labels)
        if payload is None:
            return None
        if rule.source == "quantile":
            # payload: {"buckets": [(le, cumulative)], "count", "sum"}
            cum = [c for _le, c in payload["buckets"]]
            bounds = [le for le, _c in payload["buckets"]
                      if le != float("inf")]
            counts = [c - (cum[i - 1] if i else 0)
                      for i, c in enumerate(cum)]
            prev = self._prev.get(rule.name)
            self._prev[rule.name] = ("q", now, counts)
            window = counts if prev is None else \
                [a - b for a, b in zip(counts, prev[2])]
            return _bucket_quantile(bounds, window, rule.q)
        value = float(payload)
        if rule.source == "rate":
            prev = self._prev.get(rule.name)
            self._prev[rule.name] = ("rate", now, value)
            if prev is None or now <= prev[1]:
                return None
            return (value - prev[2]) / (now - prev[1])
        return value

    # -- evaluation -------------------------------------------------------
    def evaluate_once(self, now=None):
        """One evaluation pass over every rule; returns the snapshot.
        Safe to call concurrently with the loop (shared lock)."""
        now = time.monotonic() if now is None else now
        scrape = {"_cat": None}    # lazy, shared across this pass
        with self._lock:
            self.evaluations += 1
            for rule in self.rules:
                st = self._state[rule.name]
                try:
                    value = self._value(rule, now, scrape)
                except Exception:  # noqa: BLE001 — one rule never kills
                    value = None   # the monitor; no-data semantics
                st["value"] = value
                violated = (value is not None
                            and _OPS[rule.op](value, rule.threshold))
                if violated:
                    if st["pending_since"] is None:
                        st["pending_since"] = now
                    held = now - st["pending_since"]
                    if not st["breached"] and held >= rule.for_s:
                        self._transition(rule, st, True, value, now)
                else:
                    st["pending_since"] = None
                    if st["breached"]:
                        self._transition(rule, st, False, value, now)
            return self._snapshot_locked()

    def _transition(self, rule, st, breached, value, now):
        st["breached"] = breached
        st["since"] = now
        labels = (self.scope, rule.name)
        _STATE.set(1 if breached else 0, labels=labels)
        if breached:
            _BREACHED.inc(labels=labels)
        _flightrec().record(
            "slo_breach" if breached else "slo_recovered",
            scope=self.scope, rule=rule.name,
            value=None if value is None else round(float(value), 4),
            threshold=rule.threshold, op=rule.op)
        if self.on_event is not None:
            try:
                self.on_event(rule, breached, value)
            except Exception:  # noqa: BLE001 — user hook never kills us
                pass

    def _snapshot_locked(self):
        return {name: {"breached": st["breached"], "value": st["value"],
                       "since": st["since"]}
                for name, st in self._state.items()}

    def snapshot(self):
        with self._lock:
            return self._snapshot_locked()

    def breached(self):
        """Names of currently breached rules (the Router's dispatch
        penalty reads the count)."""
        with self._lock:
            return [n for n, st in self._state.items() if st["breached"]]

    def breached_count(self):
        return len(self.breached())

    # -- supervised loop --------------------------------------------------
    def start(self):
        if self._thread is not None:
            return self
        self._stop.clear()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name="slo-monitor")
        self._thread.start()
        return self

    def _run(self):
        while not self._stop.wait(self.poll_s):
            try:
                self.evaluate_once()
            except Exception:  # noqa: BLE001 — the monitor never dies
                pass

    def stop(self, timeout=2.0):
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout)
        # a stopped monitor's gauges report 0: its scope is gone, and a
        # frozen breached=1 series would page forever for a dead server
        for r in self.rules:
            _STATE.set(0, labels=(self.scope, r.name))


def default_server_rules(server):
    """The default serving ruleset (wired by ``InferenceServer.start``
    under ``FLAGS_slo_monitor``): p99 inter-token latency (windowed
    decode-stage quantile), queue-depth ratios, kvpool occupancy, and —
    opt-in via ``FLAGS_slo_mfu_floor`` > 0 — an MFU floor on the decode
    path. Thresholds come from the ``FLAGS_slo_*`` knobs; a threshold
    of 0 disables its rule."""
    from .utilization import utilization
    rules = []
    cap = max(int(server.config.queue_depth), 1)
    p99_ms = float(_flag("slo_decode_p99_ms"))
    q_ratio = float(_flag("slo_queue_ratio"))
    kv_ratio = float(_flag("slo_kvpool_ratio"))
    mfu_floor = float(_flag("slo_mfu_floor"))
    if server.gen_queue is not None:
        if p99_ms > 0:
            # the "token" stage is one WHOLE decode-loop step (decode +
            # sample + any stall) — the true inter-token latency
            rules.append(SloRule(
                "intertoken_p99_ms", ">", p99_ms,
                hist=server.stats_sink.hist["token"], q=0.99,
                for_s=1.0))
        if q_ratio > 0:
            rules.append(SloRule(
                "decode_queue_ratio", ">", q_ratio,
                getter=lambda q=server.gen_queue: len(q) / cap))
        pool = server.gen_engine.pool
        if pool is not None and kv_ratio > 0:
            def _occ(pool=pool):
                c = pool.capacity_blocks
                return (pool.blocks_in_use() / c) if c else 0.0
            rules.append(SloRule("kvpool_occupancy", ">", kv_ratio,
                                 getter=_occ))
        if mfu_floor > 0:
            def _mfu():
                u = utilization("decode")
                if u.get("stale") or not u["mfu"]:
                    return None        # idle/unknown chip: no data
                return u["mfu"]
            rules.append(SloRule("decode_mfu_floor", "<", mfu_floor,
                                 getter=_mfu, for_s=5.0))
    if server.queue is not None and q_ratio > 0:
        rules.append(SloRule(
            "infer_queue_ratio", ">", q_ratio,
            getter=lambda q=server.queue: len(q) / cap))
    return rules
