"""Flight recorder: a bounded ring of recent structured events.

Chaos-soak postmortems previously had interleaved prints; this is the
black box instead. Subsystems ``record(kind, **fields)`` cheap
structured events (admissions, evictions, loop/train restarts, chaos
firings, non-finite hits, weight reloads, preemptions, watchdog trips);
the ring (``FLAGS_flight_recorder_events`` entries) keeps the most
recent N. Dumps:

- the ``"debug_dump"`` serving wire op returns the events inline;
- :meth:`FlightRecorder.dump` writes a JSON file on demand;
- :meth:`FlightRecorder.auto_dump` fires when a typed Internal/Watchdog
  error crosses the serving wire boundary — rate-limited, written under
  ``FLAGS_flight_recorder_dir`` (empty = automatic dumps off).

Event fields are coerced into the wire protocol's typed value universe
(str/int/float/bool/None) so a snapshot crosses the wire unchanged.
"""
import json
import os
import threading
import time
from collections import deque

from ..flags import flag as _flag
from .metrics import default_registry

_EVENTS = default_registry().counter(
    "flight_recorder_events_total",
    "structured events recorded into the flight-recorder ring",
    labels=("kind",), max_series=64)
_DUMPS = default_registry().counter(
    "flight_recorder_dumps_total",
    "flight-recorder JSON dumps written (manual + automatic)")

_AUTO_DUMP_MIN_INTERVAL_S = 30.0


def _wire_safe(v):
    if v is None or isinstance(v, (str, bool, int, float)):
        return v
    return str(v)


class FlightRecorder:
    """Thread-safe bounded event ring with JSON dumps."""

    def __init__(self, capacity=None):
        # capacity=None tracks FLAGS_flight_recorder_events live (the
        # singleton); an explicit capacity stays pinned
        self._flag_sized = capacity is None
        cap = int(capacity if capacity is not None
                  else _flag("flight_recorder_events"))
        self._ring = deque(maxlen=max(cap, 1))
        self._lock = threading.Lock()
        self._seq = 0
        self._dumps = 0
        self._last_auto = 0.0

    def _maybe_resize(self):
        """set_flags({"flight_recorder_events": N}) must take effect on
        the live singleton — every other telemetry flag is read per
        call, so a pre-soak resize silently ignored would shrink the
        postmortem window with no error. Rebuilds the deque (keeping
        the most recent events) only when the flag actually changed."""
        if not self._flag_sized:
            return
        cap = max(int(_flag("flight_recorder_events")), 1)
        if cap != self._ring.maxlen:
            with self._lock:
                if cap != self._ring.maxlen:
                    self._ring = deque(self._ring, maxlen=cap)

    def record(self, kind, **fields):
        """Append one event; ``fields`` coerced wire-safe. Cheap enough
        for per-request call sites (dict build + deque append under a
        lock)."""
        self._maybe_resize()
        ev = {"kind": str(kind), "t": time.time()}
        for k, v in fields.items():
            ev[k] = _wire_safe(v)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self._ring.append(ev)
        _EVENTS.inc(labels=(str(kind),))
        return ev

    def snapshot(self):
        """The retained events, oldest first (copies — wire-safe)."""
        with self._lock:
            return [dict(ev) for ev in self._ring]

    def counts(self):
        """{kind: n} over the retained window."""
        out = {}
        with self._lock:
            for ev in self._ring:
                out[ev["kind"]] = out.get(ev["kind"], 0) + 1
        return out

    def clear(self):
        with self._lock:
            self._ring.clear()

    def dump(self, path=None, reason=None):
        """Write the ring to a JSON file (atomic tmp+rename) and return
        the path. Default path lands in ``FLAGS_flight_recorder_dir``
        (or the OS tempdir when the flag is empty) as
        ``flightrec-<pid>-<seq>.json``."""
        events = self.snapshot()
        if path is None:
            import tempfile
            d = _flag("flight_recorder_dir") or tempfile.gettempdir()
            os.makedirs(d, exist_ok=True)
            with self._lock:
                self._dumps += 1
                n = self._dumps
            # per-recorder dump counter in the name: two dumps with no
            # intervening events must not overwrite each other
            path = os.path.join(
                d, f"flightrec-{os.getpid()}-{n:04d}.json")
        doc = {"reason": reason, "dumped_at": time.time(),
               "events": events}
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1)
        os.replace(tmp, path)
        _DUMPS.inc()
        return path

    def auto_dump(self, reason):
        """The server-boundary trigger: dump iff
        ``FLAGS_flight_recorder_dir`` is set, rate-limited to one dump
        per 30s so an error storm costs one file, not thousands.
        Returns the path or None."""
        d = _flag("flight_recorder_dir")
        if not d:
            return None
        now = time.monotonic()
        with self._lock:
            if now - self._last_auto < _AUTO_DUMP_MIN_INTERVAL_S:
                return None
            self._last_auto = now
        try:
            return self.dump(reason=reason)
        except OSError:
            return None          # a full disk must not break serving


_recorder = None
_recorder_lock = threading.Lock()


def flight_recorder():
    """The process-global recorder (lazily sized from
    ``FLAGS_flight_recorder_events``)."""
    global _recorder
    if _recorder is None:
        with _recorder_lock:
            if _recorder is None:
                _recorder = FlightRecorder()
    return _recorder
