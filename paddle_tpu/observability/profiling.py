"""Performance attribution: per-op cost profiler + HBM live-set memory
profiler.

The telemetry layer (metrics/tracing/utilization) says *how fast* the
system runs — whole-executable MFU/HBM-bw gauges, stage histograms —
but nothing says *why* it is slow: one fused XLA module has no per-op
boundary, so nobody can name the op that burns the time or the tensor
that pins the memory. This module is the attribution half:

- :func:`profile_program` — **estimated** per-op cost breakdown: walk
  the (optionally pass-optimized clone of the) program's global block
  and attribute flops/bytes per op from the declared shapes (the same
  registry shape info build-time inference populates), then rank ops by
  roofline-limited time against the SAME peak tables the live
  ``utilization`` gauges and ``bench.py`` read — attribution and the
  production MFU gauge agree by construction. Estimates can be
  validated against XLA's own ``executable_cost()`` via ``cost=``.
- **measured** mode (``FLAGS_profile_ops``, or ``measured=True``):
  interpret the op list eagerly over a CLONE-derived program (the pass
  pipeline's clone machinery — the user program is never mutated),
  syncing between ops, so each op's real wall time lands in a
  ``passes.stats()``-style table AND as Perfetto child spans
  (``op/<type>#<i>`` under one ``profile/ops`` parent) in the unified
  span table — ``tools/timeline.py`` renders an op-level flame chart.
  The executor samples this automatically every N-th dispatch when
  ``FLAGS_profile_ops=N`` (see ``Executor.run``); the committed step
  result still comes from the fused executable, so numerics are
  untouched even with the flag on.
- :func:`memory_profile` — the **HBM live-set** profiler: built on the
  PR-8 liveness/def-use analysis + declared shapes, it computes the
  byte-weighted live-set timeline across the program (persistable
  params as the resident baseline, temporaries live from their def to
  their last use, fetches live to the end), reports peak HBM, the op
  index at peak and the top-k tensors live at peak — the "why is this
  OOM / 0.008-MFU" tool — and (in measured mode) emits a
  ``hbm_live_bytes`` Perfetto counter track next to the op spans.

``FLAGS_profile_ops=0`` (the default) leaves every hot path untouched:
the executor pays one flag read per dispatch and nothing else.
"""
import threading
import time

import numpy as np

from .. import profiler as _prof
from ..flags import flag as _flag
from . import tracing as _tracing
from .metrics import default_registry
from .utilization import hbm_peak, peak_flops

# reference-chip peaks used for RANKING when the local device's peaks
# are unknown (CPU dev boxes): v5e bf16 / HBM — the ordering of
# roofline-limited times is what matters offline, not absolute ms
REF_PEAK_FLOPS = 197e12
REF_HBM_PEAK = 819e9

_REPLAYS = default_registry().counter(
    "profile_op_replays_total",
    "measured op-granular profile replays recorded "
    "(FLAGS_profile_ops sampling)")
_REPLAY_MS = default_registry().counter(
    "profile_op_ms_total",
    "wall ms spent inside measured op-granular profile replays")

_last = {"measured": None}
_last_lock = threading.Lock()


# ---------------------------------------------------------------------------
# Shape resolution + per-op flop/byte estimation.
# ---------------------------------------------------------------------------

def _shape_table(program, feed=None, batch=None):
    """name -> concrete shape tuple for every var the global block
    declares. Feed arrays pin their own shapes; remaining -1 dims take
    ``batch`` (default: the leading dim of any fed array, else 1)."""
    block = program.global_block()
    shapes = {}
    if feed:
        for n, a in feed.items():
            shp = tuple(a) if isinstance(a, (tuple, list)) \
                else tuple(np.shape(a))
            shapes[n] = shp
            if batch is None and shp:
                batch = int(shp[0])
    if batch is None:
        batch = 1
    for n, v in block.vars.items():
        if n in shapes:
            continue
        shp = getattr(v, "shape", None)
        if shp is None:
            continue
        shapes[n] = tuple(int(batch) if int(d) == -1 else int(d)
                          for d in shp)
    return shapes


def _var_bytes(program, shapes, name, _memo):
    b = _memo.get(name)
    if b is not None:
        return b
    from ..framework.dtype import np_dtype
    shp = shapes.get(name)
    b = 0
    if shp is not None:
        try:
            var = program.global_block().var(name)
            itemsize = np.dtype(np_dtype(var.dtype)).itemsize
            b = int(np.prod(shp, dtype=np.int64)) * itemsize
        except (ValueError, TypeError):
            b = 0
    _memo[name] = b
    return b


def _prod(shp):
    return int(np.prod(shp, dtype=np.int64)) if shp else 1


# op types with a specific flop rule ("named" attribution — everything
# else falls into the default one-flop-per-output-element bucket)
_MATMUL_OPS = ("mul", "matmul")

# per-param-element flop counts of the optimizer update kernels (moment
# updates + bias correction + the parameter write)
_OPT_FLOPS_PER_ELEM = {"sgd": 2.0, "momentum": 4.0, "adam": 12.0,
                       "adamw": 14.0}


def _op_flops(op, shapes):
    """(flops, rule): estimated FLOPs for one op plus the rule that
    produced them ("matmul"/"conv"/"gather"/"reduce"/"softmax"/
    "elementwise"). Grad ops take 2x their forward's estimate (the
    generic vjp computes both input cotangents; XLA CSEs the recomputed
    forward against the live one)."""
    t = op.type
    grad = t.endswith("_grad")
    base = t[:-5] if grad else t
    if base.startswith("fused_"):
        base = base[6:]
    mult = 2.0 if grad else 1.0

    def shp(slot, i=0):
        names = op.inputs.get(slot) or ()
        if i < len(names):
            return shapes.get(names[i])
        return None

    def out_shp(slot="Out", i=0):
        names = op.outputs.get(slot) or ()
        if i < len(names):
            return shapes.get(names[i])
        return None

    if base in _MATMUL_OPS:
        x = shp("X")
        y = shp("Y")
        out = out_shp()
        if x and out:
            if base == "mul":
                ncd = int(op.attrs.get("x_num_col_dims", 1))
                k = _prod(x[ncd:])
            else:
                k = int(x[-2] if op.attrs.get("transpose_X") else x[-1])
            return mult * 2.0 * _prod(out) * k, "matmul"
        if x and y:
            return mult * 2.0 * _prod(x) * (y[-1] if y else 1), "matmul"
    elif base in ("conv2d", "depthwise_conv2d"):
        out = out_shp("Output") or out_shp()
        flt = shp("Filter")
        if out and flt:
            per_out = 2.0 * _prod(flt[1:])     # Ci/groups * kh * kw MACs
            return mult * _prod(out) * per_out, "conv"
    elif base in ("lookup_table", "lookup_table_v2"):
        if grad:
            # backward is a scatter-ADD into the table: one add per
            # incoming grad element
            g = shp("Out@GRAD")
            return float(_prod(g)) if g else 0.0, "gather"
        return 0.0, "gather"                   # forward: pure movement
    elif base in _OPT_FLOPS_PER_ELEM and not grad:
        n = sum(_prod(shapes[nm]) for nm in op.inputs.get("Param", ())
                if nm in shapes)
        if n:
            return _OPT_FLOPS_PER_ELEM[base] * n, "optimizer"
    elif base in ("softmax", "softmax_with_cross_entropy"):
        x = shp("X") or shp("Logits")
        if x:
            return mult * 5.0 * _prod(x), "softmax"
    elif base in ("reduce_sum", "reduce_mean", "mean", "sum"):
        x = shp("X")
        if x:
            return mult * _prod(x), "reduce"
    elif base == "layer_norm":
        x = shp("X")
        if x:
            return mult * 8.0 * _prod(x), "reduce"
    # default: one flop per output element
    total = 0
    for names in op.outputs.values():
        for n in names:
            s = shapes.get(n)
            if s is not None:
                total += _prod(s)
    return mult * float(total), "elementwise"


def _op_bytes(program, op, shapes, memo):
    """HBM traffic estimate: every distinct input read once + every
    output written once (XLA fusion can do better; this is the
    attribution upper bound, same convention as cost_analysis)."""
    seen = set()
    total = 0
    for names in op.inputs.values():
        for n in names:
            if n not in seen:
                seen.add(n)
                total += _var_bytes(program, shapes, n, memo)
    for names in op.outputs.values():
        for n in names:
            if n not in seen:
                seen.add(n)
                total += _var_bytes(program, shapes, n, memo)
    return total


def profile_program(program, feed=None, fetch_list=None, scope=None,
                    batch=None, topk=None, cost=None, optimize=True,
                    measured=None):
    """Per-op cost attribution for ``program``'s global block.

    Returns a report dict:

    - ``ops``: one row per op, RANKED by roofline-limited time —
      ``{"index", "type", "outputs", "flops", "bytes", "est_ms",
      "bound", "rule", "share"}`` (``share`` = fraction of the total
      estimated time; ``bound`` = "compute"/"bandwidth").
    - ``totals``: summed ``flops``/``bytes``/``est_ms`` plus the peak
      table used.
    - ``coverage`` (when ``cost`` — an ``executable_cost()`` dict — is
      given): ``est_vs_xla_flops_ratio`` / ``est_vs_xla_bytes_ratio``,
      the validation against XLA's own analysis.
    - ``named_share``: fraction of estimated flops/bytes attributed by
      a SPECIFIC rule (matmul/conv/gather/reduce/softmax) rather than
      the default elementwise bucket.
    - ``measured`` (measured mode): the per-op wall-time table from one
      eager, synced interpretation (see :func:`measure_op_times`).

    ``optimize=True`` profiles the pass pipeline's optimized CLONE (what
    actually lowers; the user program is never mutated); pass False to
    profile the program as written. ``measured`` defaults to
    ``bool(FLAGS_profile_ops)``.
    """
    from ..framework.passes import optimize_program
    fetch_names = []
    for f in (fetch_list or ()):
        fetch_names.append(getattr(f, "name", None) or str(f))
    prog = optimize_program(program, fetch_names=tuple(fetch_names)) \
        if optimize else program
    shapes = _shape_table(prog, feed=feed, batch=batch)
    pf = peak_flops() or REF_PEAK_FLOPS
    pb = hbm_peak() or REF_HBM_PEAK
    memo = {}
    rows = []
    tot_f = tot_b = tot_t = 0.0
    named_f = named_b = 0.0
    for i, op in enumerate(prog.global_block().ops):
        flops, rule = _op_flops(op, shapes)
        nbytes = _op_bytes(prog, op, shapes, memo)
        t_c = flops / pf
        t_m = nbytes / pb
        est_s = max(t_c, t_m)
        rows.append({
            "index": i, "type": op.type,
            "outputs": list(op.output_arg_names)[:4],
            "flops": flops, "bytes": nbytes,
            "est_ms": est_s * 1e3,
            "bound": "compute" if t_c >= t_m else "bandwidth",
            "rule": rule,
        })
        tot_f += flops
        tot_b += nbytes
        tot_t += est_s
        if rule != "elementwise":
            named_f += flops
            named_b += nbytes
    rows.sort(key=lambda r: -r["est_ms"])
    for r in rows:
        r["share"] = (r["est_ms"] / (tot_t * 1e3)) if tot_t else 0.0
    report = {
        "n_ops": len(rows),
        "ops": rows[:topk] if topk else rows,
        "totals": {"flops": tot_f, "bytes": tot_b,
                   "est_ms": tot_t * 1e3,
                   "peak_flops": pf, "peak_hbm_bytes_per_s": pb},
        "named_share": {
            "flops": (named_f / tot_f) if tot_f else 0.0,
            "bytes": (named_b / tot_b) if tot_b else 0.0,
        },
    }
    if cost:
        report["coverage"] = {
            "est_vs_xla_flops_ratio": (tot_f / cost["flops"])
            if cost.get("flops") else None,
            "est_vs_xla_bytes_ratio": (tot_b / cost["bytes"])
            if cost.get("bytes") else None,
        }
    if measured is None:
        measured = bool(_flag("profile_ops"))
    if measured:
        if scope is None:
            from ..framework.executor import global_scope
            scope = global_scope()
        env = {n: v for n, v in scope.items()}
        for n, a in (feed or {}).items():
            env[n] = np.asarray(a) if not hasattr(a, "dtype") else a
        report["measured"] = measure_op_times(prog, env,
                                              tag=str(program._uid))
    return report


def format_table(report, topk=12):
    """passes.stats()-style text table of the top-k rows."""
    lines = [f"{'#':>4} {'op':<28} {'GFLOP':>10} {'MiB':>9} "
             f"{'est_ms':>8} {'share':>6}  bound"]
    for r in report["ops"][:topk]:
        lines.append(
            f"{r['index']:>4} {r['type'][:28]:<28} "
            f"{r['flops'] / 1e9:>10.3f} {r['bytes'] / 2**20:>9.2f} "
            f"{r['est_ms']:>8.3f} {r['share'] * 100:>5.1f}%  "
            f"{r['bound']}")
    t = report["totals"]
    lines.append(f"{'':>4} {'TOTAL (' + str(report['n_ops']) + ' ops)':<28} "
                 f"{t['flops'] / 1e9:>10.3f} {t['bytes'] / 2**20:>9.2f} "
                 f"{t['est_ms']:>8.3f}")
    return "\n".join(lines)


# ---------------------------------------------------------------------------
# HBM live-set memory profiler (liveness + shapes -> byte timeline).
# ---------------------------------------------------------------------------

def memory_profile(program, fetch_names=(), feed=None, batch=None,
                   topk=8, optimize=False):
    """Byte-weighted live-set timeline over the global block.

    Persistable vars (params, optimizer state) are the resident
    baseline — live across the whole program. A temporary is live from
    the op that defines it through its last read (def-use chains,
    framework/analysis.py); fed vars are live from op 0; fetch targets
    stay live to the end. Returns::

        {"peak_bytes", "peak_op_index", "peak_op_type",
         "baseline_bytes", "timeline": [bytes per op index],
         "top": [{"name", "bytes", "producer", "kind"}, ...],  # at peak
         "n_ops"}
    """
    from ..framework.passes import optimize_program
    if isinstance(fetch_names, str):
        fetch_names = (fetch_names,)
    prog = optimize_program(program, fetch_names=tuple(fetch_names)) \
        if optimize else program
    block = prog.global_block()
    ops = block.ops
    n = len(ops)
    shapes = _shape_table(prog, feed=feed, batch=batch)
    memo = {}

    persist = set()
    for name, v in block.vars.items():
        if getattr(v, "persistable", False):
            persist.add(name)
    baseline = sum(_var_bytes(prog, shapes, p, memo) for p in persist)

    first_def, last_use, producer = {}, {}, {}
    for i, op in enumerate(ops):
        for nm in op.input_arg_names:
            if nm in persist:
                continue
            last_use[nm] = i
            first_def.setdefault(nm, 0)        # fed/scope state: live at 0
        for nm in op.output_arg_names:
            if nm in persist:
                continue
            first_def.setdefault(nm, i)
            last_use[nm] = max(last_use.get(nm, i), i)
            producer.setdefault(nm, op.type)
    for nm in fetch_names:
        if nm in first_def:
            last_use[nm] = n - 1

    # sweep: +bytes at first_def, -bytes after last_use
    delta = [0] * (n + 1)
    for nm, d0 in first_def.items():
        b = _var_bytes(prog, shapes, nm, memo)
        if not b:
            continue
        delta[d0] += b
        delta[last_use.get(nm, d0) + 1] -= b
    timeline = []
    cur = baseline
    peak, peak_idx = baseline, 0
    for i in range(n):
        cur += delta[i]
        timeline.append(cur)
        if cur > peak:
            peak, peak_idx = cur, i
    top = []
    for nm, d0 in first_def.items():
        if d0 <= peak_idx <= last_use.get(nm, d0):
            b = _var_bytes(prog, shapes, nm, memo)
            if b:
                top.append({"name": nm, "bytes": b,
                            "producer": producer.get(nm, "feed"),
                            "kind": "temp"})
    for p in persist:
        b = _var_bytes(prog, shapes, p, memo)
        if b:
            top.append({"name": p, "bytes": b, "producer": "persistable",
                        "kind": "param"})
    top.sort(key=lambda r: -r["bytes"])
    return {
        "peak_bytes": int(peak),
        "peak_op_index": int(peak_idx),
        "peak_op_type": ops[peak_idx].type if n else None,
        "baseline_bytes": int(baseline),
        "timeline": timeline,
        "top": top[:topk],
        "n_ops": n,
    }


# ---------------------------------------------------------------------------
# Measured mode: eager, synced op-by-op interpretation with spans + the
# hbm_live_bytes counter track.
# ---------------------------------------------------------------------------

def _replay_safe(program):
    """Only pure programs replay: a measured replay EXECUTES every op a
    second time, and a side-effecting op (print, py_func, PS push)
    must never run twice for telemetry."""
    from ..framework.analysis import is_side_effect_type
    for blk in program.blocks:
        for op in blk.ops:
            if is_side_effect_type(op.type):
                return False
    return True


def measure_op_times(program, env, tag="program", mem=None,
                     allow_side_effects=False, sync=True):
    """Interpret the global block eagerly over ``env`` (a plain dict —
    the caller's scope/feed values; never written back), timing each op
    with a device sync in between. Emits:

    - ``op/<type>#<i>`` spans as children of one ``profile/ops_<tag>``
      parent (under the ambient trace context when one is active, so a
      traced request's flame chart nests op-level detail under its
      execute span) — always recorded (traced spans bypass the
      profiler-active gate);
    - a ``hbm_live_bytes`` counter sample per op (the live-set estimate
      from :func:`memory_profile`, with -1 batch dims resolved from the
      REAL fed arrays in ``env``) while the profiler is active;
    - a ``passes.stats()``-style row table, also stored for
      :func:`last_op_profile`.

    Returns ``{"tag", "rows", "total_ms", "n_ops"}`` or ``None`` when
    the program is not replay-safe (side-effecting ops present) —
    unless ``allow_side_effects`` (the explicit, user-invoked
    ``profiler.profile_program`` path, where this walk IS the one
    execution rather than a replay next to one).
    """
    if not allow_side_effects and not _replay_safe(program):
        return None
    import jax
    from ..framework.lowering import LowerCtx, run_op
    if mem is None:
        # resolve -1 (batch) dims from the arrays actually bound in the
        # env, so the counter track reports the REAL live set, not a
        # batch-1 one disagreeing with the estimate tables
        feed_shapes = {
            n: tuple(np.shape(env[n]))
            for n, v in program.global_block().vars.items()
            if getattr(v, "is_data", False) and n in env}
        mem = memory_profile(program, feed=feed_shapes or None)
    timeline = mem["timeline"]
    block = program.global_block()
    base_key = env.get("@RNG_KEY@")
    if base_key is None:
        base_key = jax.random.PRNGKey(0)
    ctx = LowerCtx(program, block, dict(env), base_key)
    parent = _tracing.current() or _tracing.new_trace()
    rows = []
    t_begin = time.perf_counter()
    with _tracing.ambient(parent):
        with _tracing.span(f"profile/ops_{tag}") as span_ctx:
            for i, op in enumerate(block.ops):
                t0 = time.perf_counter()
                run_op(ctx, op)
                if sync:
                    for nm in op.output_arg_names:
                        v = ctx.env.get(nm)
                        if hasattr(v, "block_until_ready"):
                            v.block_until_ready()
                t1 = time.perf_counter()
                _tracing.record_child(f"op/{op.type}#{i}", t0, t1,
                                      span_ctx)
                if i < len(timeline):
                    _prof.record_counter("hbm_live_bytes", t1,
                                         timeline[i])
                rows.append({"index": i, "type": op.type,
                             "ms": (t1 - t0) * 1e3})
    total_ms = (time.perf_counter() - t_begin) * 1e3
    out = {"tag": str(tag), "rows": rows, "total_ms": total_ms,
           "n_ops": len(rows),
           "peak_bytes": mem["peak_bytes"],
           "peak_op_index": mem["peak_op_index"]}
    with _last_lock:
        _last["measured"] = out
    _REPLAYS.inc()
    _REPLAY_MS.inc(total_ms)
    return out


def last_op_profile():
    """The most recent measured per-op table (None until a measured
    replay ran — via ``FLAGS_profile_ops`` sampling in the executor or
    ``profile_program(measured=True)``)."""
    with _last_lock:
        return _last["measured"]
