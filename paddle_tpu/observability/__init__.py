"""Unified telemetry substrate (the observability PR's tentpole).

Four pieces, one package:

- :mod:`metrics` — ``MetricsRegistry`` of labeled counters / gauges /
  histograms with Prometheus text exposition; existing stat sinks
  (``ServingStats``, ``Executor.cache_stats()``, ``passes.stats()``,
  breaker states, the train supervisor) report into it via native
  instruments or scrape-time collectors without changing their Python
  payloads. Scraped by the ``"metrics"`` serving wire op and
  ``tools/export_metrics.py``.
- :mod:`tracing` — Dapper-style trace/span contexts minted at the
  client, wire-propagated next to ``rid``, threaded through queue /
  pad / compile / execute and the decode slot bank, recorded into the
  profiler's unified span table so ``tools/timeline.py`` renders one
  Chrome/Perfetto trace. ``FLAGS_trace_sample_rate`` keeps the
  off-path cost near zero.
- :mod:`utilization` — live MFU / HBM-bandwidth gauges: each cached
  AOT executable's ``cost_analysis()`` flops/bytes attached to its
  runtime step timings (``bench.py`` imports the same peak tables, so
  live gauges and the offline roofline agree by construction).
- :mod:`recorder` — the flight recorder: a bounded ring of recent
  structured events (admissions, evictions, restarts, chaos firings,
  non-finite hits, weight reloads, preemptions) dumped to JSON on a
  typed server-boundary error or the ``"debug_dump"`` wire op.
- :mod:`profiling` — performance attribution: the per-op cost profiler
  (estimated flops/bytes roofline ranking + ``FLAGS_profile_ops``
  measured op-granular replays with Perfetto spans) and the HBM
  live-set memory profiler (peak residency, op index at peak, top-k
  tensors live at peak).
- :mod:`slo` — the rule-driven SLO monitor: declarative rules over
  metric streams become ``slo_breach``/``slo_recovered`` flight events,
  ``slo_*`` metrics, and dispatch-penalty signals the fleet Router
  consumes.
- :mod:`goodput` — the training goodput ledger: every second of a
  supervised training run attributed to compute / compile / data_stall
  / h2d / checkpoint / recovery / preempt / other (MegaScale-style),
  exported as ``train_time_seconds_total{category}`` +
  ``train_goodput_ratio`` + a Perfetto counter track.
- :mod:`inputstall` — the input-pipeline stall profiler: queue
  occupancy gauges, producer/consumer wait histograms, and
  ``data_stall`` flight events on the dataio queues.
- :mod:`sharding` — the sharding audit: per-tensor ACTUAL shardings of
  a compiled mesh executable diffed against declared
  ``dist_attr``/PartitionSpecs, typed findings
  (replicated-large-param, unsharded-batch, sharding-mismatch,
  reshard-inserted) as flight events + metrics.
- :mod:`comms` — the collective-traffic ledger: every
  all-reduce/all-gather/reduce-scatter/all-to-all/collective-permute
  in a compiled executable's HLO attributed to a mesh axis via its
  replica_groups, bytes+counts per (collective, axis), rooflined
  against the ICI/DCN peak tables into ``device_comm_bound_ratio``.
"""
from .comms import CommLedger, parse_collectives  # noqa: F401
from .goodput import CATEGORIES, GoodputLedger  # noqa: F401
from .inputstall import StallTracker  # noqa: F401
from .metrics import (  # noqa: F401
    DEFAULT_BOUNDS_MS, Family, MetricsRegistry, UNIT_SUFFIXES,
    default_registry, render_metrics,
)
from .profiling import (  # noqa: F401
    format_table, last_op_profile, measure_op_times, memory_profile,
    profile_program,
)
from .recorder import FlightRecorder, flight_recorder  # noqa: F401
from .sharding import (  # noqa: F401
    ShardingAuditReport, ShardingFinding, audit_executable,
    lower_program, maybe_observe, observe_executable,
    recent_observations,
)
from .slo import SloMonitor, SloRule, default_server_rules  # noqa: F401
from .tracing import (  # noqa: F401
    SpanContext, ambient, current, from_wire, maybe_trace, new_trace,
    record_child, record_span, span, to_wire,
)
from .utilization import (  # noqa: F401
    dcn_peak, executable_cost, hbm_peak, ici_peak, observe_execution,
    peak_flops, set_peaks,
)
