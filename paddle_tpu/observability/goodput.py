"""Training goodput ledger: every second of a supervised run, attributed.

MegaScale (NSDI 2024) runs production LLM training on a per-second
accounting of where wall time went — compute vs. data stalls vs.
recovery — because at scale the difference between 0.55 and 0.60 MFU is
a category of waste somebody has to NAME before they can remove it.
This module is that instrument for ``train.TrainingSupervisor``: a
:class:`GoodputLedger` attributes the run's wall clock to the closed
category set :data:`CATEGORIES`:

- ``compute``   — fused-slab execution (run_steps dispatch + device run)
- ``compile``   — trace/XLA-compile share of slab wall (cache-miss
  slabs; split out of the slab span via ``Executor.cache_stats()``
  deltas so steady state reports pure compute)
- ``data_stall``— the loop blocked pulling the next slab from the
  dataset iterator (the host-bound input path, measured at last)
- ``h2d``       — host-to-device slab transfer dispatch
- ``checkpoint``— critical-path checkpoint time (the sync gather for
  async saves, the full write otherwise)
- ``recovery``  — supervised-restart work: backoff, checkpoint reload,
  deposed-scope re-init, and REPLAYED slabs (work the crash destroyed)
- ``preempt``   — the bounded-deadline preemption fast checkpoint +
  typed exit
- ``other``     — everything unattributed (startup init, fetch
  materialization, user callbacks); computed as wall − attributed, so
  the categories always sum to wall and OVER-counting shows up as a
  reported ``overcount_s`` instead of hiding

The accounting is exclusive by construction: only the (single-threaded)
supervisor loop reports, and each report covers a disjoint interval of
its own wall clock. Exports:

- ``train_time_seconds_total{category}`` counters +
  ``train_goodput_ratio`` gauge in the default registry,
- a ``goodput/<category>_s`` Perfetto counter track (cumulative
  seconds, recorded only under an active profiler) so
  ``tools/timeline.py`` renders the ledger under the slab spans,
- :meth:`GoodputLedger.report` — the structured dict behind
  ``supervisor.goodput_report()`` and ``tools/train_report.py``.
"""
import threading
import time
from contextlib import contextmanager

from .metrics import default_registry as _registry

CATEGORIES = ("compute", "compile", "data_stall", "h2d", "checkpoint",
              "recovery", "preempt", "other")

_TIME = _registry().counter(
    "train_time_seconds_total",
    "supervised-training wall seconds attributed per goodput-ledger "
    "category (compute/compile/data_stall/h2d/checkpoint/recovery/"
    "preempt/other)",
    labels=("category",), max_series=16)
_GOODPUT = _registry().gauge(
    "train_goodput_ratio",
    "compute seconds / wall seconds of the most recent supervised "
    "training run (goodput in the MegaScale sense)")


class GoodputLedger:
    """Per-run wall-time attribution. One ledger per supervised run;
    ``add``/``span`` charge seconds to a category, ``report`` closes
    the books (``other`` absorbs the unattributed remainder)."""

    def __init__(self, clock=time.perf_counter):
        self._clock = clock
        self._lock = threading.Lock()
        self._acc = {c: 0.0 for c in CATEGORIES}
        self._t0 = None
        self._t_end = None

    # -- lifecycle --------------------------------------------------------
    def start(self):
        self._t0 = self._clock()
        self._t_end = None
        return self

    def stop(self):
        if self._t0 is not None and self._t_end is None:
            self._t_end = self._clock()
            # fold the unattributed remainder into the exported
            # ``other`` counter so the Prometheus series sum to wall
            # like the in-process report does (idempotent: only the
            # first stop folds)
            with self._lock:
                attributed = sum(self._acc.values())
            rem = self.wall_s() - attributed
            if rem > 0:
                self.add("other", rem)
        return self

    def wall_s(self):
        if self._t0 is None:
            return 0.0
        end = self._t_end if self._t_end is not None else self._clock()
        return max(end - self._t0, 0.0)

    # -- recording --------------------------------------------------------
    def add(self, category, seconds):
        """Charge ``seconds`` to ``category`` (exported immediately;
        the per-run books live in this ledger)."""
        if category not in self._acc:
            raise ValueError(
                f"unknown goodput category {category!r} "
                f"(one of {CATEGORIES})")
        s = max(float(seconds), 0.0)
        with self._lock:
            self._acc[category] += s
            cum = self._acc[category]
            compute = self._acc["compute"]
        _TIME.inc(s, labels=(category,))
        wall = self.wall_s()
        if wall > 0:
            _GOODPUT.set(min(compute / wall, 1.0))
        # Perfetto counter track (active profiler only): cumulative
        # seconds per category, timestamped on the profiler's clock
        from .. import profiler as _prof
        if _prof.is_profiling():
            _prof.record_counter(f"goodput/{category}_s",
                                 self._clock(), cum)
        return s

    @contextmanager
    def span(self, category):
        """Charge the duration of the block to ``category`` (exception-
        safe — a raising block still lands its elapsed time)."""
        t0 = self._clock()
        try:
            yield
        finally:
            self.add(category, self._clock() - t0)

    # -- reporting --------------------------------------------------------
    def report(self):
        """Close the books: ``{"wall_s", "categories", "goodput_ratio",
        "attributed_s", "unattributed_s", "overcount_s", "sum_s"}``.
        ``categories`` includes ``other`` = explicit other + the
        unattributed remainder, so ``sum_s`` equals ``wall_s`` unless
        the explicit categories OVER-counted (then ``overcount_s`` > 0
        and the 1% sum gate in ``bench.py --config goodput`` fails)."""
        wall = self.wall_s()
        with self._lock:
            acc = dict(self._acc)
        attributed = sum(acc.values())
        remainder = wall - attributed
        cats = dict(acc)
        cats["other"] += max(remainder, 0.0)
        total = sum(cats.values())
        compute = cats["compute"]
        return {
            "wall_s": wall,
            "categories": cats,
            "goodput_ratio": (compute / wall) if wall > 0 else 0.0,
            "attributed_s": attributed,
            "unattributed_s": max(remainder, 0.0),
            "overcount_s": max(-remainder, 0.0),
            "sum_s": total,
        }
