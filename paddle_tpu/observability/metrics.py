"""MetricsRegistry: the one telemetry substrate everything reports into.

Labeled counters / gauges / histograms with Prometheus text-format
exposition. Design constraints (Dapper's "always-on, cheap enough to
never turn off" discipline applied to metrics):

- **lock-cheap integer bumps**: one small lock per family, integer/float
  adds under it — no allocation on the hot path after the first
  observation of a label set.
- **bounded label cardinality**: each family holds at most
  ``max_series`` distinct label sets; overflow folds into a reserved
  ``"_other"`` series and bumps the registry-wide
  ``telemetry_series_dropped_total`` counter, so adversarial label
  traffic degrades to coarse aggregation instead of OOMing the host.
- **two report paths**: native instruments (``counter``/``gauge``/
  ``histogram``) for new subsystems, and scrape-time **collectors** for
  existing stat sinks (``ServingStats``, ``Executor.cache_stats()``,
  ``passes.stats()``, breaker states) — those keep their current Python
  payload shapes (``server.stats()`` keys unchanged) and are rendered
  into the same exposition at scrape time, the standard custom-collector
  idiom. Collectors DECLARE their family metadata up front so the
  catalog (and ``tools/lint_metrics.py``) sees every name without
  traffic.

Naming is linted (``tools/lint_metrics.py``, a tier-1 gate): snake_case,
globally unique, unit-suffixed with one of :data:`UNIT_SUFFIXES`, and
present in the README metric catalog.
"""
import re
import threading
import weakref

# closed set of accepted metric-name unit suffixes (lint-enforced):
# _total  monotonic counters          _ms     millisecond durations
# _bytes  byte sizes                  _ratio  0..1 utilizations
# _state  small state enums (0/1/2)   _count  gauge-valued counts
# _value  dimensionless instantaneous readings (loss, norms)
UNIT_SUFFIXES = ("_total", "_ms", "_bytes", "_ratio", "_state", "_count",
                 "_value")

_NAME_RE = re.compile(r"^[a-z][a-z0-9_]*$")

# log-spaced default histogram bounds in milliseconds (last bucket +inf)
DEFAULT_BOUNDS_MS = (0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 25.0, 50.0,
                     100.0, 250.0, 500.0, 1000.0, 2500.0, 5000.0, 10000.0)

_OTHER = "_other"      # reserved label value for cardinality overflow


def _check_name(name):
    if not _NAME_RE.match(name):
        raise ValueError(f"metric name {name!r} is not snake_case")
    if not name.endswith(UNIT_SUFFIXES):
        raise ValueError(
            f"metric name {name!r} lacks a unit suffix "
            f"({', '.join(UNIT_SUFFIXES)})")
    return name


def _escape_label(v):
    return str(v).replace("\\", "\\\\").replace('"', '\\"') \
                 .replace("\n", "\\n")


def _fmt(v):
    if v == float("inf"):
        return "+Inf"
    f = float(v)
    if f != f:                      # NaN: Prometheus's "no value"
        return "NaN"
    if f == float("-inf"):
        return "-Inf"
    return str(int(f)) if f == int(f) else repr(f)


class Family:
    """One metric family (a name + label names + kind); holds the
    per-label-set series. Instruments are label-positional:
    ``fam.inc(1, labels=("queue",))`` — a tuple matching
    ``label_names``."""

    __slots__ = ("name", "kind", "help", "label_names", "bounds",
                 "max_series", "_series", "_lock", "_registry",
                 "dropped")

    def __init__(self, registry, name, kind, help, label_names=(),
                 bounds=None, max_series=64):
        self.name = _check_name(name)
        self.kind = kind
        self.help = help
        self.label_names = tuple(label_names)
        self.bounds = tuple(float(b) for b in bounds) \
            if bounds is not None else None
        self.max_series = int(max_series)
        self._series = {}
        self._lock = threading.Lock()
        self._registry = registry
        # observations folded into _other by the cardinality cap;
        # per-family under the family lock (the registry sums at
        # render time — a cross-family shared counter would need its
        # own lock on every fold)
        self.dropped = 0

    def _slot(self, labels):
        """The mutable series cell for ``labels`` (created on first
        use; overflow past ``max_series`` folds into the ``_other``
        set)."""
        if len(labels) != len(self.label_names):
            raise ValueError(
                f"{self.name} takes labels {self.label_names}, "
                f"got {labels!r}")
        cell = self._series.get(labels)
        if cell is None:
            if len(self._series) >= self.max_series:
                self.dropped += 1
                labels = (_OTHER,) * len(self.label_names)
                cell = self._series.get(labels)
                if cell is not None:
                    return cell
            if self.kind == "histogram":
                cell = [[0] * (len(self.bounds) + 1), 0, 0.0]
            else:
                cell = [0.0]
            self._series[labels] = cell
        return cell

    # -- instruments ------------------------------------------------------
    def inc(self, n=1, labels=()):
        with self._lock:
            self._slot(tuple(labels))[0] += n

    def set(self, value, labels=()):
        with self._lock:
            self._slot(tuple(labels))[0] = float(value)

    def observe(self, value, labels=()):
        v = float(value)
        idx = len(self.bounds)
        for i, b in enumerate(self.bounds):
            if v <= b:
                idx = i
                break
        with self._lock:
            cell = self._slot(tuple(labels))
            cell[0][idx] += 1
            cell[1] += 1
            cell[2] += v

    def value(self, labels=()):
        """Current value (counter/gauge) or (counts, count, sum)
        (histogram) of one series; 0/empty when never touched."""
        with self._lock:
            cell = self._series.get(tuple(labels))
            if cell is None:
                return 0.0 if self.kind != "histogram" else ([], 0, 0.0)
            if self.kind == "histogram":
                return (list(cell[0]), cell[1], cell[2])
            return cell[0]

    def samples(self):
        """Snapshot: [(label_values, payload)] — payload is a number
        for counter/gauge, ``{"buckets": [(le, cumulative)], "count",
        "sum"}`` for histograms (buckets CUMULATIVE, prometheus
        style)."""
        with self._lock:
            snap = [(k, (list(v[0]), v[1], v[2])
                     if self.kind == "histogram" else v[0])
                    for k, v in self._series.items()]
        if self.kind != "histogram":
            return snap
        out = []
        for k, (counts, count, total) in snap:
            cum, buckets = 0, []
            for le, c in zip(self.bounds + (float("inf"),), counts):
                cum += c
                buckets.append((le, cum))
            out.append((k, {"buckets": buckets, "count": count,
                            "sum": total}))
        return out


class MetricsRegistry:
    """Families + collectors with one text-format renderer."""

    def __init__(self):
        self._families = {}
        self._collectors = []       # (fn, declared family dicts)
        self._declared = {}         # name -> meta (collector families)
        self._lock = threading.Lock()

    # -- registration -----------------------------------------------------
    def _family(self, name, kind, help, labels, bounds=None,
                max_series=64):
        with self._lock:
            fam = self._families.get(name)
            if fam is not None:
                if fam.kind != kind or fam.label_names != tuple(labels):
                    raise ValueError(
                        f"metric {name!r} already registered as "
                        f"{fam.kind}{fam.label_names}")
                return fam
            if name in self._declared:
                raise ValueError(f"metric {name!r} already declared by "
                                 f"a collector")
            fam = Family(self, name, kind, help, labels, bounds=bounds,
                         max_series=max_series)
            self._families[name] = fam
            return fam

    def counter(self, name, help="", labels=(), max_series=64):
        return self._family(name, "counter", help, labels,
                            max_series=max_series)

    def gauge(self, name, help="", labels=(), max_series=64):
        return self._family(name, "gauge", help, labels,
                            max_series=max_series)

    def histogram(self, name, help="", labels=(),
                  bounds=DEFAULT_BOUNDS_MS, max_series=64):
        return self._family(name, "histogram", help, labels,
                            bounds=bounds, max_series=max_series)

    def register_collector(self, fn, families):
        """Register a scrape-time collector. ``fn()`` returns a list of
        family dicts ``{"name", "kind", "help", "labels", "samples"}``
        (samples as :meth:`Family.samples` produces), plus an optional
        cumulative ``"dropped"`` count of series the collector folded
        away under its own cardinality cap — it feeds
        ``telemetry_series_dropped_total`` and must be monotone.
        ``families`` declares, up front, every family the collector may
        emit — the catalog/lint surface."""
        with self._lock:
            for meta in families:
                name = _check_name(meta["name"])
                if name in self._families or name in self._declared:
                    raise ValueError(f"metric {name!r} already "
                                     f"registered")
                self._declared[name] = dict(meta)
            self._collectors.append(fn)

    def catalog(self):
        """{name: {"kind", "help", "labels"}} across native families
        AND collector-declared ones — every name the exposition can
        ever emit (plus the registry's own drop counter)."""
        with self._lock:
            out = {n: {"kind": f.kind, "help": f.help,
                       "labels": f.label_names}
                   for n, f in self._families.items()}
            for n, meta in self._declared.items():
                out[n] = {"kind": meta.get("kind", "counter"),
                          "help": meta.get("help", ""),
                          "labels": tuple(meta.get("labels", ()))}
        out["telemetry_series_dropped_total"] = {
            "kind": "counter",
            "help": "observations folded into an _other series by the "
                    "per-family label-cardinality cap", "labels": ()}
        return out

    def collect(self):
        """Structured snapshot of every family's CURRENT samples —
        native instruments AND collector-emitted ones::

            {name: {"kind", "help", "labels", "samples"}}

        with ``samples`` in :meth:`Family.samples` shape. This is the
        programmatic scrape the SLO monitor evaluates rules against and
        the fleet-metrics aggregation re-exposes; :meth:`render` is the
        same data as Prometheus text."""
        with self._lock:
            fams = list(self._families.items())
            collectors = list(self._collectors)
            declared = dict(self._declared)
        out = {}
        for name, fam in fams:
            out[name] = {"kind": fam.kind, "help": fam.help,
                         "labels": fam.label_names,
                         "samples": fam.samples()}
        for fn in collectors:
            try:
                emitted = fn()
            except Exception:  # noqa: BLE001 — one sink never kills it
                continue
            for f in emitted:
                meta = declared.get(f["name"], {})
                out[f["name"]] = {
                    "kind": f.get("kind", meta.get("kind", "counter")),
                    "help": f.get("help", meta.get("help", "")),
                    "labels": tuple(f.get("labels",
                                          meta.get("labels", ()))),
                    "samples": list(f.get("samples", ())),
                }
        return out

    # -- exposition -------------------------------------------------------
    @staticmethod
    def _labelstr(names, values):
        if not names:
            return ""
        inner = ",".join(f'{n}="{_escape_label(v)}"'
                         for n, v in zip(names, values))
        return "{" + inner + "}"

    @staticmethod
    def _render_family(lines, name, kind, help, label_names, samples):
        lines.append(f"# HELP {name} {help}")
        lines.append(f"# TYPE {name} {kind}")
        for values, payload in samples:
            if kind == "histogram":
                for le, cum in payload["buckets"]:
                    ls = MetricsRegistry._labelstr(
                        tuple(label_names) + ("le",),
                        tuple(values) + (_fmt(le),))
                    lines.append(f"{name}_bucket{ls} {cum}")
                ls = MetricsRegistry._labelstr(label_names, values)
                lines.append(f"{name}_sum{ls} {_fmt(payload['sum'])}")
                lines.append(f"{name}_count{ls} {payload['count']}")
            else:
                ls = MetricsRegistry._labelstr(label_names, values)
                lines.append(f"{name}{ls} {_fmt(payload)}")

    def render(self):
        """Prometheus text exposition (format 0.0.4) of every native
        family and every collector's current samples."""
        with self._lock:
            fams = sorted(self._families.items())
            collectors = list(self._collectors)
        dropped = sum(f.dropped for _n, f in fams)
        lines = []
        for name, fam in fams:
            self._render_family(lines, name, fam.kind, fam.help,
                                fam.label_names, fam.samples())
        for fn in collectors:
            try:
                emitted = fn()
            except Exception:  # noqa: BLE001 — one sink never kills scrape
                continue
            for f in emitted:
                # collectors report their own cumulative series-cap
                # folds (e.g. the breaker collector's endpoint cap)
                dropped += int(f.get("dropped", 0))
                self._render_family(lines, f["name"],
                                    f.get("kind", "counter"),
                                    f.get("help", ""),
                                    tuple(f.get("labels", ())),
                                    f.get("samples", ()))
        self._render_family(
            lines, "telemetry_series_dropped_total", "counter",
            "observations folded into an _other series by the "
            "per-family label-cardinality cap", (),
            [((), dropped)])
        return "\n".join(lines) + "\n"


class InstanceAggregator:
    """The WeakSet-of-live-instances + finalizer-banked-retired-totals
    skeleton shared by per-instance sink bridges (``ServingStats``,
    ``Executor`` caches). Exported ``*_total`` counters must stay
    monotonic across instance churn — a scraped counter falling to 0
    when a server or executor object dies reads as a counter reset and
    fabricates rate() spikes — so :meth:`track` registers a finalizer
    that folds the dying instance's final counter values into a banked
    total, and :meth:`totals` sums live instances plus the bank.

    Only the scalar-counter banking lives here; site-specific
    retirement (histogram bucket merges, cache clearing) rides the same
    finalizer via ``extra_retire``."""

    def __init__(self, counter_keys):
        self._instances = weakref.WeakSet()
        self._lock = threading.Lock()
        self._retired = {k: 0 for k in counter_keys}

    def track(self, instance, final_counts_fn, extra_retire=None):
        """Track a live instance. ``final_counts_fn()`` must close over
        the instance's stat containers (NOT the instance itself — the
        finalizer must not keep it alive) and return its final
        ``{key: count}``. ``extra_retire()``, if given, runs after the
        bank fold."""
        self._instances.add(instance)
        weakref.finalize(instance, self._retire, final_counts_fn,
                         extra_retire)

    def _retire(self, final_counts_fn, extra_retire):
        counts = final_counts_fn()
        with self._lock:
            for k in self._retired:
                self._retired[k] += counts.get(k, 0)
        if extra_retire is not None:
            extra_retire()

    def live(self):
        return list(self._instances)

    def totals(self, live_counts_fn, live_only_keys=()):
        """Retired bank + ``live_counts_fn(instance)`` summed over every
        live instance. ``live_only_keys`` (gauges — they retire WITH
        the instance they describe) are summed over live instances but
        never banked. An instance that raises is skipped — one broken
        sink never kills the scrape."""
        # strong refs FIRST: an instance can then only retire before
        # this point (so it's in the bank) or after the scrape — never
        # in between, where it would be missed by both and dent the
        # exported counter's monotonicity for one scrape
        live = self.live()
        with self._lock:
            totals = dict(self._retired)
        for k in live_only_keys:
            totals.setdefault(k, 0)
        for inst in live:
            try:
                counts = live_counts_fn(inst)
            except Exception:  # noqa: BLE001 — scrape survives any sink
                continue
            for k in totals:
                totals[k] += counts.get(k, 0)
        return totals


_default = MetricsRegistry()


def default_registry():
    """The process-global registry every subsystem reports into (the
    ``"metrics"`` wire op / ``tools/export_metrics.py`` scrape it)."""
    return _default


def render_metrics():
    """Prometheus text exposition of the default registry."""
    return _default.render()
