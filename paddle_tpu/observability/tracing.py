"""Request-scoped tracing (Dapper-style trace/span propagation).

A :class:`SpanContext` is minted at the edge (``serving.Client`` — or
any caller via :func:`maybe_trace`/:func:`new_trace`), carried in the
wire frames next to the existing ``rid`` as a ``"trace"`` dict, and
threaded through admission -> queue -> pad/compile/execute and the
decode slot bank. Every recorded span lands in the profiler's unified
span table (``paddle_tpu.profiler``), so ``tools/timeline.py`` emits ONE
Chrome/Perfetto trace interleaving server stages with training/executor
spans — the Dapper property that makes tail debugging tractable.

Sampling (``FLAGS_trace_sample_rate``) happens ONCE at the edge; an
untraced request pays a single ``random()`` draw client-side and one
``None`` attribute read per server stage — near-zero off-path cost.
Traced spans record even while the profiler is inactive (they are the
always-on sampled stream); ``profiler.reset_profiler()`` clears them and
the ``_MAX_SPANS`` bound + drop counter cap memory.
"""
import random
import threading
import time
import uuid
from contextlib import contextmanager

from .. import profiler as _prof
from ..flags import flag as _flag
from .metrics import default_registry

_tls = threading.local()

_TRACES_SAMPLED = default_registry().counter(
    "telemetry_traces_sampled_total",
    "trace contexts minted at the client edge (FLAGS_trace_sample_rate)")

default_registry().register_collector(
    lambda: [{"name": "telemetry_spans_dropped_total",
              "kind": "counter",
              "help": "spans lost to the profiler span-table cap "
                      "(process-lifetime total; reset_profiler only "
                      "zeroes the session count, keeping this "
                      "monotonic)",
              "labels": (),
              "samples": [((), _prof.spans_dropped_total())]}],
    families=[{"name": "telemetry_spans_dropped_total",
               "kind": "counter",
               "help": "spans lost to the profiler span-table cap "
                       "(process-lifetime, monotonic)",
               "labels": ()}])


class SpanContext:
    """(trace_id, span_id, parent_id) triple. ``span_id`` names THIS
    span; children are minted with :meth:`child`."""

    __slots__ = ("trace_id", "span_id", "parent_id")

    def __init__(self, trace_id, span_id=None, parent_id=""):
        self.trace_id = trace_id
        self.span_id = span_id if span_id is not None else _new_id()
        self.parent_id = parent_id

    def child(self):
        return SpanContext(self.trace_id, _new_id(), self.span_id)

    def __repr__(self):
        return (f"SpanContext({self.trace_id}/{self.span_id}"
                f"<-{self.parent_id or 'root'})")


def _new_id():
    return uuid.uuid4().hex[:16]


def new_trace():
    """Unconditionally mint a root span context (the explicit API —
    sampling is the caller's business)."""
    _TRACES_SAMPLED.inc()
    return SpanContext(_new_id())


def maybe_trace():
    """The edge sampler: the ambient context's child if one is active,
    else a fresh root with probability ``FLAGS_trace_sample_rate``,
    else None. One random() draw on the untraced path."""
    ctx = current()
    if ctx is not None:
        return ctx.child()
    if random.random() < _flag("trace_sample_rate"):
        return new_trace()
    return None


def current():
    """The ambient span context of this thread (None when untraced)."""
    return getattr(_tls, "ctx", None)


@contextmanager
def ambient(ctx):
    """Install ``ctx`` as this thread's ambient context for the block
    (``Request._init_lifecycle`` picks it up so spans recorded by the
    batcher threads parent correctly). ``ctx=None`` is a no-op."""
    if ctx is None:
        yield None
        return
    prev = getattr(_tls, "ctx", None)
    _tls.ctx = ctx
    try:
        yield ctx
    finally:
        _tls.ctx = prev


def record_span(name, start_s, end_s, ctx):
    """Record a completed span AS ``ctx`` (trace/span/parent ids ride
    into the profiler span table). No-op when ``ctx`` is None."""
    if ctx is None:
        return
    _prof.record_span(name, start_s, end_s,
                      trace=(ctx.trace_id, ctx.span_id, ctx.parent_id))


def record_child(name, start_s, end_s, parent):
    """Record a completed span as a fresh CHILD of ``parent``; returns
    the child context (None when untraced)."""
    if parent is None:
        return None
    ctx = parent.child()
    record_span(name, start_s, end_s, ctx)
    return ctx


@contextmanager
def span(name, parent=None):
    """Span context manager: times the block and records it as a child
    of ``parent`` (default: the ambient context), installing the child
    as ambient inside the block so nested spans chain."""
    parent = parent if parent is not None else current()
    if parent is None:
        yield None
        return
    ctx = parent.child()
    t0 = time.perf_counter()
    with ambient(ctx):
        try:
            yield ctx
        finally:
            record_span(name, t0, time.perf_counter(), ctx)


# -- wire representation (inside the typed wire value universe) ----------

def to_wire(ctx):
    """``{"tid", "sid"}`` dict for the wire frame (None passthrough)."""
    if ctx is None:
        return None
    return {"tid": ctx.trace_id, "sid": ctx.span_id}


def from_wire(d):
    """SpanContext from a wire ``"trace"`` dict (None / malformed ->
    None; a hostile frame must never raise here)."""
    if not isinstance(d, dict):
        return None
    tid, sid = d.get("tid"), d.get("sid")
    if not (isinstance(tid, str) and isinstance(sid, str)):
        return None
    return SpanContext(tid[:64], sid[:64])
