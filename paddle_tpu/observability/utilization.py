"""Live MFU / HBM-bandwidth-utilization gauges.

``bench.py`` derives MFU and bandwidth utilization offline from XLA's
``cost_analysis()`` of the compiled step; this module makes the same
measurement ALWAYS-ON: each cached AOT executable's flops/bytes are read
once at compile time (:func:`executable_cost` — the bench ``_step_cost``
machinery) and attached to its runtime step timings, so ``run_steps``,
the serving engine and the decode slot bank export continuous
``device_mfu_ratio`` / ``device_hbm_bw_util_ratio`` gauges. bench.py
imports the peak tables from HERE, so the live gauges and the offline
roofline agree by construction.

Gauge semantics (the same for every ``where`` label): achieved rate
over the recent MEASURED-EXECUTION window — i.e. utilization while the
executable is actually running. Serving/decode stages time each
execution exactly (they sync on the result); the executor's train/step
labels use dispatch-to-dispatch deltas of a steady loop as the
execution-time proxy (no telemetry-forced sync) and DROP deltas far
above the loop's recent cadence, so an idle pause reads as "no new
observation", never as a utilization collapse or a phantom busy chip.
For duty cycle (how much of wall clock the chip computed at all),
compare the ``device_compute_ms_total`` counter against scrape-interval
wall time — the raw ``device_flops_total`` / ``device_hbm_bytes_total``
counters ride along for the same reason.
"""
import threading
from collections import deque

from .metrics import default_registry

# chip peak bf16 TFLOP/s by device_kind substring (public specs) — the
# single source bench.py's roofline reads too
PEAK_TFLOPS = {
    "v5 lite": 197.0, "v5e": 197.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 45.0,
    "v6": 918.0,
}

# chip HBM peak bytes/s by device_kind substring (public specs)
HBM_PEAK = {
    "v5 lite": 819e9, "v5e": 819e9,
    "v4": 1228e9,
    "v3": 900e9,
    "v2": 700e9,
    "v6": 1638e9,
}

_override = {"flops": None, "bytes": None}

_MFU = default_registry().gauge(
    "device_mfu_ratio",
    "achieved / peak FLOP rate over the recent measured-execution "
    "window (utilization WHILE executing; duty cycle comes from "
    "device_compute_ms_total vs wall clock)",
    labels=("where",), max_series=16)
_BW = default_registry().gauge(
    "device_hbm_bw_util_ratio",
    "achieved / peak HBM bandwidth over the recent measured-execution "
    "window (clamped at 1.0: XLA bytes-accessed is pre-fusion and can "
    "overcount)",
    labels=("where",), max_series=16)
_FLOPS = default_registry().counter(
    "device_flops_total", "cost_analysis FLOPs dispatched",
    labels=("where",), max_series=16)
_BYTES = default_registry().counter(
    "device_hbm_bytes_total", "cost_analysis bytes accessed",
    labels=("where",), max_series=16)
_MS = default_registry().counter(
    "device_compute_ms_total",
    "wall milliseconds attributed to measured executions",
    labels=("where",), max_series=16)


def peak_flops(device=None):
    """Peak bf16 FLOP/s of ``device`` (default: jax.devices()[0]), or
    None when the chip is not in the table (e.g. CPU). An operator (or
    test) override via :func:`set_peaks` wins."""
    if _override["flops"] is not None:
        return _override["flops"]
    kind = _device_kind(device)
    for key, tf in PEAK_TFLOPS.items():
        if key in kind:
            return tf * 1e12
    return None


def hbm_peak(device=None):
    """Peak HBM bytes/s of ``device``; same contract as
    :func:`peak_flops`."""
    if _override["bytes"] is not None:
        return _override["bytes"]
    kind = _device_kind(device)
    for key, b in HBM_PEAK.items():
        if key in kind:
            return b
    return None


def _device_kind(device):
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:  # noqa: BLE001 — no backend, no gauges
            return ""
    return getattr(device, "device_kind", "").lower()


# default-device peaks memo for the hot path: the device kind cannot
# change within a process, so observe_execution must not re-resolve
# jax.devices() + rescan the tables per execution. set_peaks
# invalidates.
_peaks_memo = None


def _default_peaks():
    global _peaks_memo
    if _peaks_memo is None:
        _peaks_memo = (peak_flops(), hbm_peak())
    return _peaks_memo


def set_peaks(flops_per_s=None, hbm_bytes_per_s=None):
    """Override the peak tables (unlisted hardware, or tests that need
    deterministic ratios on CPU). ``None`` restores table lookup."""
    global _peaks_memo
    _override["flops"] = flops_per_s
    _override["bytes"] = hbm_bytes_per_s
    _peaks_memo = None


def executable_cost(compiled):
    """{"flops", "bytes"} from a compiled XLA executable's
    ``cost_analysis()`` (the bench ``_step_cost`` read), or None when
    the backend reports nothing usable. Call once per executable and
    memoize — the analysis walk is not free."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
        nbytes = float(ca.get("bytes accessed", 0.0))
        if flops <= 0 and nbytes <= 0:
            return None
        return {"flops": max(flops, 0.0), "bytes": max(nbytes, 0.0)}
    except Exception:  # noqa: BLE001 — backend-dependent surface
        return None


def cost_for(memo, key, compiled):
    """:func:`executable_cost` for ``compiled``, memoized in the LRU
    ``memo`` under ``key`` (False = backend reports nothing). Misses
    RECOMPUTE from the executable in hand, so an evicted memo entry for
    a still-cached executable never freezes the gauges. One helper for
    the executor, the serving engine and the generator — the False
    sentinel contract lives here only."""
    cost = memo.get(key)
    if cost is None:
        cost = executable_cost(compiled) or False
        memo.put(key, cost)
    return cost


class _Window:
    """Sliding window with O(1) running totals (add the new
    observation, subtract the evicted one) and its OWN lock, so the
    decode loop, the micro-batcher and the executor never contend on
    one global lock for O(window) re-summation. The totals are
    recomputed from the deque every 4096 observations to shed
    accumulated float drift."""

    __slots__ = ("obs", "t", "f", "b", "n", "lock")

    def __init__(self):
        self.obs = deque(maxlen=64)     # (seconds, flops, bytes)
        self.t = self.f = self.b = 0.0
        self.n = 0
        self.lock = threading.Lock()

    def add(self, seconds, flops, nbytes):
        with self.lock:
            if len(self.obs) == self.obs.maxlen:
                es, ef, eb = self.obs[0]
                self.t -= es
                self.f -= ef
                self.b -= eb
            self.obs.append((seconds, flops, nbytes))
            self.t += seconds
            self.f += flops
            self.b += nbytes
            self.n += 1
            if self.n % 4096 == 0:      # shed float drift
                self.t = sum(o[0] for o in self.obs)
                self.f = sum(o[1] for o in self.obs)
                self.b = sum(o[2] for o in self.obs)
            return self.t, self.f, self.b


_windows = {}
_lock = threading.Lock()        # guards the _windows dict only


def observe_execution(where, cost, seconds):
    """Attach one timed execution of an executable with ``cost``
    (:func:`executable_cost` dict) to the live gauges for ``where``
    ("train", "step", "infer", "prefill", "decode", ...). Counters bump
    unconditionally; the MFU/BW gauges update only when the device's
    peaks are known."""
    if not cost or seconds <= 0:    # None AND cost_for's False sentinel
        return
    flops, nbytes = cost["flops"], cost["bytes"]
    lab = (where,)
    _FLOPS.inc(flops, labels=lab)
    _BYTES.inc(nbytes, labels=lab)
    _MS.inc(seconds * 1e3, labels=lab)
    pf, pb = _default_peaks()
    if pf is None and pb is None:
        return
    w = _windows.get(where)
    if w is None:
        with _lock:
            w = _windows.setdefault(where, _Window())
    t, f, b = w.add(seconds, flops, nbytes)
    if t <= 0:
        return
    if pf:
        _MFU.set(min(f / t / pf, 1.0), labels=lab)
    if pb:
        _BW.set(min(b / t / pb, 1.0), labels=lab)


def utilization(where):
    """Current gauge readings {mfu, hbm_bw_util} for ``where`` (0.0
    when never observed / peaks unknown)."""
    return {"mfu": _MFU.value(labels=(where,)),
            "hbm_bw_util": _BW.value(labels=(where,))}


def reset_windows():
    """Drop the sliding windows (tests; gauges keep their last value
    until the next observation)."""
    with _lock:
        _windows.clear()
