"""Live MFU / HBM-bandwidth-utilization gauges.

``bench.py`` derives MFU and bandwidth utilization offline from XLA's
``cost_analysis()`` of the compiled step; this module makes the same
measurement ALWAYS-ON: each cached AOT executable's flops/bytes are read
once at compile time (:func:`executable_cost` — the bench ``_step_cost``
machinery) and attached to its runtime step timings, so ``run_steps``,
the serving engine and the decode slot bank export continuous
``device_mfu_ratio`` / ``device_hbm_bw_util_ratio`` gauges. bench.py
imports the peak tables from HERE, so the live gauges and the offline
roofline agree by construction.

Gauge semantics (the same for every ``where`` label): achieved rate
over the recent MEASURED-EXECUTION window — i.e. utilization while the
executable is actually running. Serving/decode stages time each
execution exactly (they sync on the result); the executor's train/step
labels use dispatch-to-dispatch deltas of a steady loop as the
execution-time proxy (no telemetry-forced sync) and DROP deltas far
above the loop's recent cadence, so an idle pause reads as "no new
observation", never as a utilization collapse or a phantom busy chip.
For duty cycle (how much of wall clock the chip computed at all),
compare the ``device_compute_ms_total`` counter against scrape-interval
wall time — the raw ``device_flops_total`` / ``device_hbm_bytes_total``
counters ride along for the same reason.
"""
import threading
import time
from collections import deque

from .metrics import default_registry

# chip peak bf16 TFLOP/s by device_kind substring (public specs) — the
# single source bench.py's roofline reads too
PEAK_TFLOPS = {
    "v5 lite": 197.0, "v5e": 197.0,
    "v4": 275.0,
    "v3": 123.0,
    "v2": 45.0,
    "v6": 918.0,
}

# chip HBM peak bytes/s by device_kind substring (public specs)
HBM_PEAK = {
    "v5 lite": 819e9, "v5e": 819e9,
    "v4": 1228e9,
    "v3": 900e9,
    "v2": 700e9,
    "v6": 1638e9,
}

# per-chip aggregate ICI (inter-chip interconnect) bandwidth in bytes/s
# (public per-chip-kind "interchip interconnect BW" specs, Gbps -> B/s).
# The collective-traffic ledger (observability/comms.py) rooflines each
# mesh axis's per-step bytes against this — bench.py and the live
# device_comm_bound_ratio gauge import the SAME table, the PR-9 MFU
# agreement-by-construction discipline applied to communication.
ICI_PEAK = {
    "v5 lite": 200e9, "v5e": 200e9,   # 1600 Gbps
    "v5p": 600e9,                     # 4800 Gbps
    "v4": 300e9,                      # 2400 Gbps
    "v3": 82e9,                       # 656 Gbps
    "v2": 62e9,                       # 496 Gbps
    "v6": 448e9,                      # 3584 Gbps (Trillium)
}

# per-host DCN (data-center network) bandwidth in bytes/s — the
# cross-slice fabric collectives ride when a mesh axis spans slices
# (mesh.py: intra-slice traffic rides ICI, cross-slice DCN). Public
# per-host NIC specs; coarser than ICI by construction.
DCN_PEAK = {
    "v5 lite": 25e9, "v5e": 25e9,     # 200 Gbps host NIC
    "v5p": 25e9,
    "v4": 25e9,
    "v3": 12.5e9,                     # 100 Gbps
    "v2": 12.5e9,
    "v6": 50e9,                       # 400 Gbps
}

_override = {"flops": None, "bytes": None, "ici": None, "dcn": None}

_FLOPS = default_registry().counter(
    "device_flops_total", "cost_analysis FLOPs dispatched",
    labels=("where",), max_series=16)
_BYTES = default_registry().counter(
    "device_hbm_bytes_total", "cost_analysis bytes accessed",
    labels=("where",), max_series=16)
_MS = default_registry().counter(
    "device_compute_ms_total",
    "wall milliseconds attributed to measured executions",
    labels=("where",), max_series=16)


def peak_flops(device=None):
    """Peak bf16 FLOP/s of ``device`` (default: jax.devices()[0]), or
    None when the chip is not in the table (e.g. CPU). An operator (or
    test) override via :func:`set_peaks` wins."""
    if _override["flops"] is not None:
        return _override["flops"]
    kind = _device_kind(device)
    for key, tf in PEAK_TFLOPS.items():
        if key in kind:
            return tf * 1e12
    return None


def hbm_peak(device=None):
    """Peak HBM bytes/s of ``device``; same contract as
    :func:`peak_flops`."""
    if _override["bytes"] is not None:
        return _override["bytes"]
    kind = _device_kind(device)
    for key, b in HBM_PEAK.items():
        if key in kind:
            return b
    return None


def ici_peak(device=None):
    """Per-chip ICI bandwidth (bytes/s) of ``device``; same
    substring-match + :func:`set_peaks` override contract as
    :func:`peak_flops` (None on unlisted hardware, e.g. CPU)."""
    if _override["ici"] is not None:
        return _override["ici"]
    kind = _device_kind(device)
    for key, b in ICI_PEAK.items():
        if key in kind:
            return b
    return None


def dcn_peak(device=None):
    """Per-host DCN bandwidth (bytes/s) of ``device``; same contract as
    :func:`ici_peak`."""
    if _override["dcn"] is not None:
        return _override["dcn"]
    kind = _device_kind(device)
    for key, b in DCN_PEAK.items():
        if key in kind:
            return b
    return None


def _device_kind(device):
    if device is None:
        try:
            import jax
            device = jax.devices()[0]
        except Exception:  # noqa: BLE001 — no backend, no gauges
            return ""
    return getattr(device, "device_kind", "").lower()


# default-device peaks memo for the hot path: the device kind cannot
# change within a process, so observe_execution must not re-resolve
# jax.devices() + rescan the tables per execution. set_peaks
# invalidates.
_peaks_memo = None


def _default_peaks():
    global _peaks_memo
    if _peaks_memo is None:
        _peaks_memo = (peak_flops(), hbm_peak())
    return _peaks_memo


def set_peaks(flops_per_s=None, hbm_bytes_per_s=None,
              ici_bytes_per_s=None, dcn_bytes_per_s=None):
    """Override the peak tables (unlisted hardware, or tests that need
    deterministic ratios on CPU). ``None`` restores table lookup for
    that peak — every call re-states all four, so ``set_peaks()`` is a
    full reset. Invalidates the hot-path memos."""
    global _peaks_memo
    _override["flops"] = flops_per_s
    _override["bytes"] = hbm_bytes_per_s
    _override["ici"] = ici_bytes_per_s
    _override["dcn"] = dcn_bytes_per_s
    _peaks_memo = None


def executable_cost(compiled):
    """{"flops", "bytes"} from a compiled XLA executable's
    ``cost_analysis()`` (the bench ``_step_cost`` read), or None when
    the backend reports nothing usable. Call once per executable and
    memoize — the analysis walk is not free."""
    try:
        ca = compiled.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0))
        nbytes = float(ca.get("bytes accessed", 0.0))
        if flops <= 0 and nbytes <= 0:
            return None
        return {"flops": max(flops, 0.0), "bytes": max(nbytes, 0.0)}
    except Exception:  # noqa: BLE001 — backend-dependent surface
        return None


def executable_memory(compiled):
    """Device-memory footprint of a compiled executable from XLA's
    ``memory_analysis()``: argument/output/temp/alias byte sizes plus a
    derived ``peak_bytes`` (args + temps + outputs - aliased, i.e. the
    live bytes while the executable runs — the validation target for
    the static HBM live-set profiler). None when the backend reports
    nothing."""
    try:
        ma = compiled.memory_analysis()
        arg = int(ma.argument_size_in_bytes)
        out = int(ma.output_size_in_bytes)
        tmp = int(ma.temp_size_in_bytes)
        alias = int(ma.alias_size_in_bytes)
        if arg <= 0 and out <= 0 and tmp <= 0:
            return None
        return {"argument_bytes": arg, "output_bytes": out,
                "temp_bytes": tmp, "alias_bytes": alias,
                "peak_bytes": arg + out + tmp - alias}
    except Exception:  # noqa: BLE001 — backend-dependent surface
        return None


def cost_for(memo, key, compiled):
    """:func:`executable_cost` for ``compiled``, memoized in the LRU
    ``memo`` under ``key`` (False = backend reports nothing). Misses
    RECOMPUTE from the executable in hand, so an evicted memo entry for
    a still-cached executable never freezes the gauges. One helper for
    the executor, the serving engine and the generator — the False
    sentinel contract lives here only."""
    cost = memo.get(key)
    if cost is None:
        cost = executable_cost(compiled) or False
        memo.put(key, cost)
    return cost


class _Window:
    """Sliding window with O(1) running totals (add the new
    observation, subtract the evicted one) and its OWN lock, so the
    decode loop, the micro-batcher and the executor never contend on
    one global lock for O(window) re-summation. The totals are
    recomputed from the deque every 4096 observations to shed
    accumulated float drift. Each observation also stamps wall time
    (monotonic) — the staleness contract below reads the stamps."""

    __slots__ = ("obs", "t", "f", "b", "n", "lock", "last_wall")

    def __init__(self):
        self.obs = deque(maxlen=64)     # (seconds, flops, bytes, wall)
        self.t = self.f = self.b = 0.0
        self.n = 0
        self.last_wall = 0.0
        self.lock = threading.Lock()

    def add(self, seconds, flops, nbytes):
        now = time.monotonic()
        with self.lock:
            if len(self.obs) == self.obs.maxlen:
                es, ef, eb, _ew = self.obs[0]
                self.t -= es
                self.f -= ef
                self.b -= eb
            self.obs.append((seconds, flops, nbytes, now))
            self.t += seconds
            self.f += flops
            self.b += nbytes
            self.n += 1
            self.last_wall = now
            if self.n % 4096 == 0:      # shed float drift
                self.t = sum(o[0] for o in self.obs)
                self.f = sum(o[1] for o in self.obs)
                self.b = sum(o[2] for o in self.obs)

    def snapshot(self):
        """(exec_seconds, flops, bytes, wall_span, last_wall) of the
        retained window — one consistent copy."""
        with self.lock:
            if not self.obs:
                return None
            span = self.last_wall - self.obs[0][3]
            return self.t, self.f, self.b, span, self.last_wall


# a window is STALE once it has been idle longer than the wall span it
# covers (floored so a two-observation window isn't stale a split
# second later): a stopped/idle server must read as "no current
# utilization", not as its last busy-period gauge forever
_STALE_FLOOR_S = 1.0

_windows = {}
_lock = threading.Lock()        # guards the _windows dict only


def observe_execution(where, cost, seconds):
    """Attach one timed execution of an executable with ``cost``
    (:func:`executable_cost` dict) to the live gauges for ``where``
    ("train", "step", "infer", "prefill", "decode", ...). Counters bump
    unconditionally; the MFU/BW ratio gauges are derived from the
    sliding window AT SCRAPE TIME (see :func:`_collect_ratios`) so an
    idle window goes stale instead of freezing at its last value."""
    if not cost or seconds <= 0:    # None AND cost_for's False sentinel
        return
    flops, nbytes = cost["flops"], cost["bytes"]
    lab = (where,)
    _FLOPS.inc(flops, labels=lab)
    _BYTES.inc(nbytes, labels=lab)
    _MS.inc(seconds * 1e3, labels=lab)
    pf, pb = _default_peaks()
    if pf is None and pb is None:
        return
    w = _windows.get(where)
    if w is None:
        with _lock:
            w = _windows.setdefault(where, _Window())
    w.add(seconds, flops, nbytes)


def _window_ratios(where, now=None):
    """(mfu, bw, stale) computed from the retained window, or None when
    never observed / peaks unknown. Each ratio is individually None
    when ITS peak is unknown (an operator who only set the FLOP peak
    must not export a false 0.0 bandwidth utilization)."""
    w = _windows.get(where)
    if w is None:
        return None
    snap = w.snapshot()
    if snap is None:
        return None
    t, f, b, span, last_wall = snap
    if t <= 0:
        return None
    pf, pb = _default_peaks()
    if pf is None and pb is None:
        return None
    now = time.monotonic() if now is None else now
    stale = (now - last_wall) > max(span, _STALE_FLOOR_S)
    mfu = min(f / t / pf, 1.0) if pf else None
    bw = min(b / t / pb, 1.0) if pb else None
    return mfu, bw, stale


def _collect_ratios():
    """Scrape-time collector for the MFU / HBM-bw ratio gauges: derived
    from the sliding windows at scrape time, SKIPPING stale windows —
    a stopped server's exposition simply stops carrying the series
    instead of exporting its last busy reading forever."""
    with _lock:
        wheres = list(_windows)
    mfu_s, bw_s = [], []
    now = time.monotonic()
    for where in wheres:
        r = _window_ratios(where, now=now)
        if r is None or r[2]:           # unknown peaks / stale: skip
            continue
        if r[0] is not None:
            mfu_s.append(((where,), r[0]))
        if r[1] is not None:
            bw_s.append(((where,), r[1]))
    return [
        {"name": "device_mfu_ratio", "kind": "gauge",
         "help": "achieved / peak FLOP rate over the recent "
                 "measured-execution window (utilization WHILE "
                 "executing; stale/idle windows are omitted — duty "
                 "cycle comes from device_compute_ms_total vs wall "
                 "clock)",
         "labels": ("where",), "samples": mfu_s},
        {"name": "device_hbm_bw_util_ratio", "kind": "gauge",
         "help": "achieved / peak HBM bandwidth over the recent "
                 "measured-execution window (clamped at 1.0: XLA "
                 "bytes-accessed is pre-fusion and can overcount; "
                 "stale/idle windows are omitted)",
         "labels": ("where",), "samples": bw_s},
    ]


default_registry().register_collector(
    _collect_ratios,
    families=[
        {"name": "device_mfu_ratio", "kind": "gauge",
         "help": "achieved / peak FLOP rate over the recent "
                 "measured-execution window", "labels": ("where",)},
        {"name": "device_hbm_bw_util_ratio", "kind": "gauge",
         "help": "achieved / peak HBM bandwidth over the recent "
                 "measured-execution window", "labels": ("where",)},
    ])


def utilization(where):
    """Current window readings ``{mfu, hbm_bw_util, stale}`` for
    ``where`` (zeros / stale=False when never observed or peaks
    unknown). ``stale=True`` means the window has been idle longer
    than the wall span it covers — the reading describes a PAST busy
    period, not the present (the Prometheus collector omits the series
    entirely in that state)."""
    r = _window_ratios(where)
    if r is None:
        return {"mfu": 0.0, "hbm_bw_util": 0.0, "stale": False}
    return {"mfu": r[0] or 0.0, "hbm_bw_util": r[1] or 0.0,
            "stale": r[2]}


def reset_windows():
    """Drop the sliding windows (tests; the ratio series disappear from
    the exposition until the next observation)."""
    with _lock:
        _windows.clear()
