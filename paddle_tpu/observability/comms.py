"""Collective-traffic ledger: what the mesh actually moves per step.

The utilization gauges say how hard one chip works; nothing says what
the MESH does — GSPMD (arXiv 2105.04663) decides where all-reduces,
all-gathers, reduce-scatters, all-to-alls and collective-permutes land,
and those decisions are invisible until the step is slow. This module
parses a compiled executable's HLO (``compiled.as_text()``), attributes
every collective to a mesh axis via its ``replica_groups`` (or
``source_target_pairs``) shape, and aggregates **bytes + counts per
(collective, axis) per executable** — the MegaScale-style communication
attribution the mesh PRs (tensor-parallel serving, 1F1B pipeline, MoE)
get gated on.

Conventions (documented because they ARE the numbers):

- ``payload_bytes`` — the tensor bytes the collective operates on (the
  result for all-reduce/all-gather/all-to-all/collective-permute, the
  larger OPERAND for reduce-scatter), per step, per instance.
- ``wire_bytes`` — per-device link traffic under the standard ring
  algorithms: all-reduce ``2(S-1)/S``, all-gather / reduce-scatter /
  all-to-all ``(S-1)/S`` of the payload, collective-permute ``1x``
  (S = replica-group size). An upper-bound model, same spirit as the
  pre-fusion ``cost_analysis`` bytes the HBM gauge rides.
- axis attribution — replica-group device ids are unraveled over the
  mesh's axis sizes (XLA's device assignment follows the flattened
  mesh device list); the label is the ``+``-join of every axis the
  group varies over (``"tp"``, ``"dp+sp"``), ``"none"`` for
  single-device groups.

Rooflining divides each axis's per-step wire bytes by the ICI (or DCN,
for axes the caller marks cross-slice) bandwidth peak tables in
:mod:`utilization` — the same ``set_peaks()``-overridable tables
``bench.py`` reads, so the live ``device_comm_bound_ratio`` gauge and
the offline bench agree by construction. On hardware with no table
entry (CPU dev boxes) the reference-chip peaks below rank/predict
instead, flagged ``ref_peaks`` — the profiling.py convention.
"""
import re
import time

import numpy as np

from .. import profiler as _prof
from . import tracing as _tracing
from .metrics import default_registry
from .utilization import dcn_peak, ici_peak, peak_flops, hbm_peak

# reference-chip comm peaks for prediction when the local device is
# unlisted (CPU CI): v5e ICI / host DCN — ordering and fractions are
# what matter offline, not absolute seconds (profiling.REF_PEAK_* idiom)
REF_ICI_PEAK = 200e9
REF_DCN_PEAK = 25e9

COLLECTIVE_KINDS = ("all-reduce", "all-gather", "reduce-scatter",
                    "all-to-all", "collective-permute")

# wire-traffic multiplier per payload byte under ring algorithms; S is
# the replica-group size (lambdas so S=1 degenerates to 0 traffic)
_WIRE_FACTOR = {
    "all-reduce": lambda s: 2.0 * (s - 1) / s if s > 1 else 0.0,
    "all-gather": lambda s: (s - 1) / s if s > 1 else 0.0,
    "reduce-scatter": lambda s: (s - 1) / s if s > 1 else 0.0,
    "all-to-all": lambda s: (s - 1) / s if s > 1 else 0.0,
    "collective-permute": lambda s: 1.0,
}

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "f16": 2,
    "bf16": 2, "s32": 4, "u32": 4, "f32": 4, "s64": 8, "u64": 8,
    "f64": 8, "c64": 8, "c128": 16, "s4": 1, "u4": 1, "f8e4m3fn": 1,
    "f8e5m2": 1,
}

_KIND_RE = re.compile(
    r"\b(all-reduce|all-gather|reduce-scatter|all-to-all|"
    r"collective-permute)(-start|-done)?\(")
_SHAPE_RE = re.compile(r"\b([a-z][a-z0-9]*)\[([0-9,]*)\]")
_GROUPS_RE = re.compile(
    r"replica_groups=(\{\}|\{\{[0-9,\s]+\}(?:,\s*\{[0-9,\s]+\})*\}|"
    r"\[[0-9,]+\]<=\[[0-9,]+\](?:T\([0-9,]+\))?)")
_PAIRS_RE = re.compile(r"source_target_pairs=\{((?:\{[0-9]+,[0-9]+\},?)*)\}")
_METADATA_RE = re.compile(r'op_name="([^"]*)"')

_BYTES_TOTAL = default_registry().counter(
    "comms_bytes_total",
    "predicted per-step collective wire bytes of newly audited "
    "executables, by collective kind and mesh axis",
    labels=("collective", "axis"), max_series=64)
_OPS_TOTAL = default_registry().counter(
    "comms_ops_total",
    "collective instances found in newly audited executables' HLO, by "
    "collective kind and mesh axis",
    labels=("collective", "axis"), max_series=64)
_COMM_BOUND = default_registry().gauge(
    "device_comm_bound_ratio",
    "predicted fraction of step time spent in collectives for the most "
    "recently compiled executable (ledger wire bytes / axis bandwidth "
    "vs the compute/HBM roofline)",
    labels=("where",), max_series=16)


def _matching_paren(line, open_idx):
    """Index of the ')' closing the '(' at ``open_idx`` — TPU tiled
    layouts put parens INSIDE operand shapes (``{1,0:T(8,128)}``), so
    a first-')' scan truncates variadic operand lists."""
    depth = 0
    for i in range(open_idx, len(line)):
        c = line[i]
        if c == "(":
            depth += 1
        elif c == ")":
            depth -= 1
            if depth == 0:
                return i
    return -1


def _shapes_bytes(text):
    """Total bytes of every typed shape literal in ``text`` (handles
    tuple result types and multi-operand lists)."""
    total = 0
    for dtype, dims in _SHAPE_RE.findall(text):
        nbytes = _DTYPE_BYTES.get(dtype)
        if nbytes is None:
            continue                       # token/opaque types
        n = 1
        if dims:
            for d in dims.split(","):
                n *= int(d)
        total += n * nbytes
    return total


def parse_replica_groups(attr):
    """Replica groups from either HLO syntax: explicit
    ``{{0,1},{2,3}}`` or iota ``[G,S]<=[d0,d1,..]T(p0,p1,..)``.
    Returns a list of int tuples."""
    attr = attr.strip()
    if attr.startswith("{"):
        groups = []
        for grp in re.findall(r"\{([0-9,\s]*?)\}", attr):
            ids = tuple(int(x) for x in grp.replace(" ", "").split(",")
                        if x != "")
            if ids:
                groups.append(ids)
        return groups
    m = re.match(r"\[([0-9,]+)\]<=\[([0-9,]+)\](?:T\(([0-9,]+)\))?",
                 attr)
    if not m:
        return []
    out_shape = [int(x) for x in m.group(1).split(",")]
    dims = [int(x) for x in m.group(2).split(",")]
    perm = [int(x) for x in m.group(3).split(",")] if m.group(3) \
        else list(range(len(dims)))
    ids = np.arange(int(np.prod(dims))).reshape(dims).transpose(perm) \
        .reshape(out_shape)
    return [tuple(int(x) for x in row) for row in ids]


def axes_label(groups, mesh):
    """The mesh-axis attribution of a replica-group list: device ids
    unravel over the mesh's axis sizes (XLA's device assignment is the
    flattened mesh device list), and the label names every axis the
    groups vary over, joined ``+`` in mesh-axis order. ``"none"`` for
    degenerate single-device groups, ``"unknown"`` when the ids don't
    fit the mesh (foreign device assignment)."""
    if mesh is None:
        return "unknown"
    names = tuple(mesh.axis_names)
    dims = tuple(int(mesh.shape[a]) for a in names)
    total = int(np.prod(dims))
    varying = set()
    for g in groups:
        if len(g) < 2:
            continue
        if any(d >= total or d < 0 for d in g):
            return "unknown"
        coords = [np.unravel_index(d, dims) for d in g]
        for i in range(len(dims)):
            if len({c[i] for c in coords}) > 1:
                varying.add(i)
    if not varying:
        return "none"
    return "+".join(names[i] for i in sorted(varying))


def parse_collectives(hlo_text, mesh=None):
    """Scan optimized-HLO text for collective instructions. Returns one
    dict per instance::

        {"kind", "axis", "group_size", "n_groups", "payload_bytes",
         "wire_bytes", "op_name"}

    ``-done`` halves of async pairs are skipped (the ``-start`` carries
    the shape); explicit user collectives keep their own op_name in
    ``metadata`` while GSPMD-inserted reshards carry the op they were
    inserted FOR — the sharding audit keys off that distinction."""
    out = []
    for line in hlo_text.splitlines():
        m = _KIND_RE.search(line)
        if m is None:
            continue
        kind, variant = m.group(1), m.group(2)
        if variant == "-done":
            continue                       # counted at the -start half
        eq = line.find(" = ")
        rtype = line[eq + 3:m.start()] if eq >= 0 else ""
        close = _matching_paren(line, m.end() - 1)
        operands = line[m.end():close if close >= 0 else len(line)]
        attrs = line[close + 1:] if close >= 0 else line
        if kind == "collective-permute":
            pm = _PAIRS_RE.search(attrs)
            groups = parse_replica_groups("{" + pm.group(1) + "}") \
                if pm else []
        else:
            gm = _GROUPS_RE.search(attrs)
            groups = parse_replica_groups(gm.group(1)) if gm else []
        unknown_global = False
        if not groups and kind != "collective-permute":
            # replica_groups={} (or absent) is HLO for "ALL devices in
            # one group" — an empty parse must not let the largest
            # collective vanish with group_size 1 / wire 0
            if mesh is not None:
                names = tuple(mesh.axis_names)
                total = int(np.prod([int(mesh.shape[a])
                                     for a in names]))
                if total > 1:
                    groups = [tuple(range(total))]
            else:
                # no mesh to size the group: count it at the S=2 wire
                # LOWER bound under an "unknown" axis rather than 0
                unknown_global = True
        size = 2 if unknown_global \
            else max((len(g) for g in groups), default=1)
        if kind == "collective-permute" and groups:
            # pairs, not groups: the payload crosses one link per pair
            size = 2
        if kind == "reduce-scatter":
            payload = _shapes_bytes(operands)  # the larger, pre-scatter
        elif variant == "-start":
            # async halves type their result as a tuple carrying the
            # operand(s) alongside the output (+ backend contexts) —
            # summing the tuple would overcount, so derive from the
            # operand list instead: the gathered result is operand x S
            payload = _shapes_bytes(operands)
            if kind == "all-gather":
                payload *= size
        else:
            payload = _shapes_bytes(rtype)
            if kind == "all-reduce" and payload == 0:
                payload = _shapes_bytes(operands)
        md = _METADATA_RE.search(attrs)
        out.append({
            "kind": kind,
            "axis": "unknown" if unknown_global
            else axes_label(groups, mesh),
            "group_size": int(size),
            "n_groups": len(groups),
            "payload_bytes": int(payload),
            "wire_bytes": int(payload * _WIRE_FACTOR[kind](size)),
            "op_name": md.group(1) if md else "",
        })
    return out


def _rides_dcn(axis, dcn_axes):
    """A multi-axis group label (``"dp+sp+tp"``) rides DCN when ANY of
    its component axes is cross-slice — the slowest fabric in the path
    prices the collective."""
    return any(part in dcn_axes for part in axis.split("+"))


class CommLedger:
    """Per-(collective, axis) aggregation of one executable's parsed
    collectives, with the roofline prediction attached."""

    def __init__(self, collectives, mesh=None):
        self.collectives = list(collectives)
        self.mesh = mesh
        self.rows = {}
        for c in self.collectives:
            key = (c["kind"], c["axis"])
            row = self.rows.setdefault(
                key, {"count": 0, "payload_bytes": 0, "wire_bytes": 0,
                      "group_size": c["group_size"]})
            row["count"] += 1
            row["payload_bytes"] += c["payload_bytes"]
            row["wire_bytes"] += c["wire_bytes"]
            row["group_size"] = max(row["group_size"], c["group_size"])

    @classmethod
    def from_hlo(cls, hlo_text, mesh=None):
        return cls(parse_collectives(hlo_text, mesh), mesh=mesh)

    @classmethod
    def from_compiled(cls, compiled, mesh=None):
        return cls.from_hlo(compiled.as_text(), mesh=mesh)

    def __bool__(self):
        return bool(self.rows)

    def totals(self):
        by_axis = {}
        count = payload = wire = 0
        for (kind, axis), row in self.rows.items():
            count += row["count"]
            payload += row["payload_bytes"]
            wire += row["wire_bytes"]
            by_axis[axis] = by_axis.get(axis, 0) + row["wire_bytes"]
        return {"count": count, "payload_bytes": payload,
                "wire_bytes": wire, "by_axis": by_axis}

    def predicted_comm_s(self, dcn_axes=()):
        """Predicted per-step seconds in collectives: each axis's wire
        bytes over its fabric bandwidth (DCN for axes in ``dcn_axes``,
        ICI otherwise; reference peaks on unlisted hardware), summed —
        a serial upper bound. Returns ``(seconds, used_ref_peaks)``;
        the flag is True iff any axis ACTUALLY divided by a reference
        peak (a fabric whose table/override has a real value never
        taints the flag)."""
        ici = ici_peak()
        dcn = dcn_peak()
        total = 0.0
        ref = False
        for axis, wire in self.totals()["by_axis"].items():
            if _rides_dcn(axis, dcn_axes):
                bw = dcn if dcn is not None else REF_DCN_PEAK
                ref = ref or dcn is None
            else:
                bw = ici if ici is not None else REF_ICI_PEAK
                ref = ref or ici is None
            total += wire / bw
        return total, ref

    def comm_bound_ratio(self, cost, dcn_axes=()):
        """Predicted fraction of step time spent communicating:
        ``t_comm / (t_comm + t_step)`` with ``t_step`` the
        compute/bandwidth roofline of ``cost`` (an
        ``utilization.executable_cost`` dict). None when ``cost`` is
        missing/empty (incl. the ``cost_for`` False sentinel on
        backends without cost_analysis) — unknown compute must read as
        "no prediction", not as 100% comm-bound."""
        if not cost:
            return None
        t_comm, _ref = self.predicted_comm_s(dcn_axes=dcn_axes)
        from .profiling import REF_HBM_PEAK, REF_PEAK_FLOPS
        pf = peak_flops() or REF_PEAK_FLOPS
        pb = hbm_peak() or REF_HBM_PEAK
        t_step = max(cost.get("flops", 0.0) / pf,
                     cost.get("bytes", 0.0) / pb)
        if t_comm <= 0 and t_step <= 0:
            return None
        return t_comm / (t_comm + t_step)

    def to_dict(self):
        """JSON-safe nesting for the MULTICHIP dryrun records and the
        shard_report CLI: ``{"<kind>@<axis>": row, ..., "totals": {...}}``
        (no dots in keys — tools/bench_compare.py dotted paths reach
        every leaf)."""
        out = {f"{kind}@{axis}": dict(row)
               for (kind, axis), row in sorted(self.rows.items())}
        out["totals"] = self.totals()
        return out

    def format_table(self):
        lines = [f"{'collective':<20} {'axis':<8} {'count':>5} "
                 f"{'payload MiB':>12} {'wire MiB':>10}"]
        for (kind, axis), row in sorted(self.rows.items()):
            lines.append(
                f"{kind:<20} {axis:<8} {row['count']:>5} "
                f"{row['payload_bytes'] / 2**20:>12.3f} "
                f"{row['wire_bytes'] / 2**20:>10.3f}")
        t = self.totals()
        lines.append(f"{'TOTAL':<20} {'':<8} {t['count']:>5} "
                     f"{t['payload_bytes'] / 2**20:>12.3f} "
                     f"{t['wire_bytes'] / 2**20:>10.3f}")
        return "\n".join(lines)


def flat_allreduce_wire_bytes(ledger, mesh, dcn_axes=("dcn_dp",)):
    """What the NAIVE flat all-reduce would move over DCN per step: the
    full gradient volume (reconstructed as the hier path's cross-slice
    payload x the in-slice degree it was scattered by) all-reduced over
    the whole ``S = dcn x dp`` group at DCN pricing —
    ``2(S-1)/S x B_total`` per device. The yardstick
    :func:`assert_hier_decomposition` holds the observed DCN traffic
    against."""
    inner = 1
    total = 1
    for a in mesh.axis_names:
        total *= int(mesh.shape[a])
        if a not in dcn_axes:
            inner *= int(mesh.shape[a])
    dcn_payload = sum(row["payload_bytes"]
                      for (kind, axis), row in ledger.rows.items()
                      if _rides_dcn(axis, dcn_axes))
    return _WIRE_FACTOR["all-reduce"](total) * dcn_payload * inner


def assert_hier_decomposition(compiled_or_ledger, mesh, dcn_axes=None,
                              where="train"):
    """Pre-burn gate for the multi-slice hierarchical grad sync: parse
    the compiled executable's collectives and PROVE the decomposition
    before the first slab is dispatched. Three checks, all fatal
    (:class:`~paddle_tpu.resilience.HierarchicalCommsError`):

    1. every DCN-priced collective's group varies ONLY over declared
       cross-slice axes — a ``"dcn_dp+dp"`` label means a collective
       spans both fabrics and the whole payload crawls at DCN speed;
    2. the observed cross-slice wire bytes are STRICTLY below the flat
       all-reduce estimate (:func:`flat_allreduce_wire_bytes`) — the
       decomposition must actually pay off, not just exist;
    3. cross-slice collectives exist at all — zero DCN rows on a
       dcn_dp mesh means hier_grad_sync never ran and gradients are
       not synchronized across slices.

    Returns the ledger on success so callers can log it. ``dcn_axes``
    defaults to ``FLAGS_comms_dcn_axes``, falling back to
    ``("dcn_dp",)`` (the axis the mesh module declares cross-slice).
    """
    from ..resilience import HierarchicalCommsError
    if dcn_axes is None:
        from ..flags import flag as _flag
        dcn_axes = tuple(a.strip() for a in
                         _flag("comms_dcn_axes").split(",")
                         if a.strip()) or ("dcn_dp",)
    ledger = compiled_or_ledger \
        if isinstance(compiled_or_ledger, CommLedger) \
        else CommLedger.from_compiled(compiled_or_ledger, mesh)
    violations = []
    dcn_wire = 0
    dcn_rows = 0
    for (kind, axis), row in sorted(ledger.rows.items()):
        if not _rides_dcn(axis, dcn_axes):
            continue
        dcn_rows += row["count"]
        dcn_wire += row["wire_bytes"]
        stray = [p for p in axis.split("+") if p not in dcn_axes]
        if stray:
            violations.append(
                f"{kind}@{axis}: group varies over non-DCN axes "
                f"{stray} ({row['wire_bytes']} wire bytes would cross "
                f"slices carrying in-slice traffic)")
    if dcn_rows == 0:
        violations.append(
            "no cross-slice collectives found — the hier_grad_sync "
            "pass did not run on this program (compile it through "
            "CompiledProgram.with_data_parallel over the dcn_dp mesh) "
            "and per-slice gradients would silently diverge")
    else:
        flat = flat_allreduce_wire_bytes(ledger, mesh, dcn_axes)
        if flat and dcn_wire >= flat:
            violations.append(
                f"cross-slice wire bytes {dcn_wire} do not beat the "
                f"flat all-reduce estimate {flat:.0f} — the "
                f"decomposition exists but does not pay")
    if violations:
        raise HierarchicalCommsError(
            f"hierarchical-comms gate failed for {where!r} on mesh "
            f"{dict(mesh.shape)} (DCN axes {tuple(dcn_axes)}):\n  - "
            + "\n  - ".join(violations),
            violations=violations, ledger=ledger)
    return ledger


def observe_ledger(where, ledger, cost=None, dcn_axes=()):
    """Export one newly compiled executable's ledger: bump the
    per-(collective, axis) byte/op counters, set the predicted
    ``device_comm_bound_ratio{where}`` gauge, and — under an active
    profiler — lay down the ``comms/<axis>_bytes`` Perfetto counter
    track plus per-collective child spans (span length = the PREDICTED
    per-axis comm time, so the flame chart shows relative cost).
    Returns the comm-bound ratio (or None)."""
    for (kind, axis), row in ledger.rows.items():
        lab = (kind, axis)
        _BYTES_TOTAL.inc(row["wire_bytes"], labels=lab)
        _OPS_TOTAL.inc(row["count"], labels=lab)
    ratio = ledger.comm_bound_ratio(cost, dcn_axes=dcn_axes)
    # the gauge describes the MOST RECENTLY compiled executable: when
    # this one has no prediction (cost unavailable) it must not keep
    # exporting the previous executable's ratio — NaN is Prometheus's
    # "no value" (the PR-12 stale-gauge discipline)
    _COMM_BOUND.set(ratio if ratio is not None else float("nan"),
                    labels=(where,))
    if ledger.rows and (_prof.is_profiling()
                        or _tracing.current() is not None):
        _record_tracks(where, ledger, dcn_axes=dcn_axes)
    return ratio


def _record_tracks(where, ledger, dcn_axes=()):
    """One ``comms/ledger_<where>`` parent span with a child span per
    (collective, axis) — each child's duration is its predicted wire
    time — plus cumulative ``comms/<axis>_bytes`` counter samples."""
    ici = ici_peak() or REF_ICI_PEAK
    dcn = dcn_peak() or REF_DCN_PEAK
    parent = _tracing.current() or _tracing.new_trace()
    t0 = time.perf_counter()
    cursor = t0
    cum_by_axis = {}
    with _tracing.ambient(parent):
        with _tracing.span(f"comms/ledger_{where}") as span_ctx:
            for (kind, axis), row in sorted(ledger.rows.items()):
                bw = dcn if _rides_dcn(axis, dcn_axes) else ici
                dur = max(row["wire_bytes"] / bw, 1e-9)
                _tracing.record_child(f"comm/{kind}@{axis}", cursor,
                                      cursor + dur, span_ctx)
                cursor += dur
                cum_by_axis[axis] = cum_by_axis.get(axis, 0) \
                    + row["wire_bytes"]
                _prof.record_counter(f"comms/{axis}_bytes", cursor,
                                     cum_by_axis[axis])
