"""Sharding audit: what GSPMD actually decided vs what you declared.

GSPMD (arXiv 2105.04663) propagates shardings from sparse user
annotations — and its decisions routinely diverge from what the
annotations imply: a parameter nobody annotated silently replicates
across the whole mesh, a batch dim misses the dp axis because its size
doesn't divide, an intermediate round-trips through an inserted
all-gather every step. None of that is visible today: a
``mesh(dp,tp,pp,ep)`` dryrun reports a loss and nothing else.

This module extracts the per-tensor ACTUAL shardings of a compiled
executable (``compiled.input_shardings`` + the collectives in its
optimized HLO), diffs them against the program's declared
``dist_attr``/PartitionSpecs, and emits typed findings in the PR-8
verifier style:

- ``replicated-large-param`` — a persistable input at/above
  ``FLAGS_shard_audit_replicated_mb`` fully replicated while the mesh
  has a >1 axis (every chip holds — and the optimizer updates — the
  whole tensor).
- ``unsharded-batch`` — a fed batch dim unsharded under a >1 dp axis
  (every chip computes the whole batch: dp is silently off for this
  input).
- ``sharding-mismatch`` — the actual input sharding differs from the
  sanitized declared ``dist_attr`` spec (an annotation that didn't
  take — wrong axis name, non-dividing dim, annotated after
  ``minimize``).
- ``reshard-inserted`` — a GSPMD-inserted all-gather / all-to-all in
  the compiled HLO (distinguished from EXPLICIT user collectives by
  the instruction metadata: an inserted reshard carries the op it was
  inserted for, e.g. ``dot``; an explicit one carries its own
  collective primitive name).

Findings carry var name, bytes, actual/declared axes, and the
producing op; they land as ``shard_audit_finding`` flight events and
``shard_audit_findings_total{code}``. The audit only READS the
compiled artifact — numerics are bitwise-unchanged with the flag on or
off.
"""
import threading

import numpy as np

from ..flags import flag as _flag
from .metrics import default_registry
from .recorder import flight_recorder as _flightrec

FINDING_CODES = ("replicated-large-param", "unsharded-batch",
                 "sharding-mismatch", "reshard-inserted")

_FINDINGS_TOTAL = default_registry().counter(
    "shard_audit_findings_total",
    "sharding-audit findings emitted for newly compiled mesh "
    "executables, by finding code",
    labels=("code",), max_series=16)

# findings recorded into the flight ring per audit (a pathological
# program must not churn the whole postmortem window)
_MAX_FLIGHT_FINDINGS = 16


class ShardingFinding:
    """One audit finding (framework.analysis.Diagnostic shape, plus the
    byte count and axis specs the sharding domain needs)."""

    __slots__ = ("code", "message", "var", "nbytes", "actual",
                 "declared", "op_type")

    def __init__(self, code, message, var=None, nbytes=0, actual=None,
                 declared=None, op_type=None):
        self.code = code
        self.message = message
        self.var = var
        self.nbytes = int(nbytes)
        self.actual = actual
        self.declared = declared
        self.op_type = op_type

    def __str__(self):
        loc = f"{self.var}" if self.var else "?"
        if self.op_type:
            loc += f" ({self.op_type})"
        return f"[{self.code}] {loc}: {self.message}"

    def __repr__(self):
        return f"ShardingFinding({self!s})"

    def to_dict(self):
        return {"code": self.code, "var": self.var,
                "bytes": self.nbytes,
                "actual": list(self.actual) if self.actual else None,
                "declared": (list(self.declared) if self.declared
                             else None),
                "op_type": self.op_type, "message": self.message}


class ShardingAuditReport:
    """The findings of one executable's audit."""

    def __init__(self, findings, inputs=None):
        self.findings = list(findings)
        self.inputs = inputs or {}

    def counts(self):
        out = {}
        for f in self.findings:
            out[f.code] = out.get(f.code, 0) + 1
        return out

    def by_code(self, code):
        return [f for f in self.findings if f.code == code]

    def worst(self, code=None):
        """The largest-byte finding (optionally of one code), or
        None."""
        pool = self.by_code(code) if code else self.findings
        return max(pool, key=lambda f: f.nbytes) if pool else None

    def __bool__(self):
        return bool(self.findings)

    def format_table(self):
        if not self.findings:
            return "sharding audit clean: no findings"
        lines = [f"{'code':<24} {'var':<36} {'MiB':>9}  "
                 f"actual -> declared"]
        for f in sorted(self.findings, key=lambda f: -f.nbytes):
            lines.append(
                f"{f.code:<24} {str(f.var)[:36]:<36} "
                f"{f.nbytes / 2**20:>9.2f}  "
                f"{_spec_str(f.actual)} -> {_spec_str(f.declared)}")
        return "\n".join(lines)


def _spec_str(spec):
    if spec is None:
        return "-"
    return "(" + ",".join("·" if a is None else str(a)
                          for a in spec) + ")"


def _normalize_spec(spec, ndim):
    """A PartitionSpec/tuple as a plain ndim-length tuple of axis-name
    strings (multi-axis entries joined ``+``) and Nones."""
    entries = tuple(spec) if spec is not None else ()
    out = []
    for e in entries[:ndim]:
        if e is None:
            out.append(None)
        elif isinstance(e, (tuple, list)):
            out.append("+".join(str(a) for a in e) if e else None)
        else:
            out.append(str(e))
    out += [None] * (ndim - len(out))
    return tuple(out)


def named_input_shardings(compiled):
    """{name: {"spec", "shape", "dtype", "nbytes"}} for every
    dict-keyed input leaf of a compiled executable — the executor /
    engine / generator all pass their tensors in name-keyed dicts, so
    the pytree paths of ``input_shardings`` recover the program var
    names. Unnamed leaves (the RNG key positional) are skipped; so are
    sharding types that expose no ``spec`` and are not fully
    replicated."""
    from jax import tree_util as jtu
    in_sh = compiled.input_shardings[0]
    args_info = compiled.args_info[0]
    sh_leaves = jtu.tree_flatten_with_path(in_sh)[0]
    info_leaves = jtu.tree_flatten_with_path(args_info)[0]
    infos = {jtu.keystr(p): v for p, v in info_leaves}
    out = {}
    for path, sh in sh_leaves:
        name = None
        for entry in reversed(path):
            key = getattr(entry, "key", None)
            if isinstance(key, str):
                name = key
                break
        if name is None:
            continue
        info = infos.get(jtu.keystr(path))
        aval = getattr(info, "_aval", None) if info is not None else None
        shape = tuple(getattr(aval, "shape", ()) or ())
        dtype = getattr(aval, "dtype", None)
        spec = getattr(sh, "spec", None)
        if spec is None:
            if getattr(sh, "is_fully_replicated", False):
                spec = ()
            else:
                continue
        itemsize = np.dtype(dtype).itemsize if dtype is not None else 4
        out[name] = {
            "spec": _normalize_spec(spec, len(shape)),
            "shape": shape,
            "dtype": str(dtype) if dtype is not None else None,
            "nbytes": int(np.prod(shape, dtype=np.int64)) * itemsize
            if shape else itemsize,
            # which mesh (by axis names) the sharding actually lives
            # on — the audit refuses to judge an executable compiled
            # off-mesh against an unrelated ambient mesh
            "mesh_axes": tuple(getattr(getattr(sh, "mesh", None),
                                       "axis_names", ()) or ()),
        }
    return out


def _mesh_has_parallelism(mesh):
    return mesh is not None and any(
        int(mesh.shape[a]) > 1 for a in mesh.axis_names)


def audit_executable(compiled, mesh, program=None, feed_names=(),
                     batch_dim=0, threshold_mb=None, hlo_text=None,
                     collectives=None):
    """Audit one compiled executable against ``mesh`` (and, when given,
    ``program``'s declared ``dist_attr`` annotations). ``feed_names``
    marks the batch-carrying inputs (their dim ``batch_dim`` should be
    dp-sharded); ``batch_dim`` is 1 for ``run_steps`` slabs (the
    leading K axis replicates by design). ``collectives`` (a
    pre-parsed ``comms.parse_collectives`` list) skips re-parsing the
    HLO when the caller already has it. Returns a
    :class:`ShardingAuditReport`."""
    from ..parallel.mesh import partition_spec
    if threshold_mb is None:
        threshold_mb = float(_flag("shard_audit_replicated_mb"))
    threshold = threshold_mb * 2**20
    findings = []
    inputs = named_input_shardings(compiled)
    if not _mesh_has_parallelism(mesh):
        return ShardingAuditReport([], inputs=inputs)
    # only judge NAMED inputs of executables actually compiled ON this
    # mesh: a single-device executable (e.g. a meshless serving engine
    # while a training mesh is ambient) reports every input fully
    # replicated and would drown the audit in false findings. The
    # HLO-based reshard scan below is mesh-validated per collective
    # (foreign device ids label "unknown") and still runs.
    axes = tuple(mesh.axis_names)
    on_mesh = any(i.get("mesh_axes") == axes for i in inputs.values())
    gblock = program.global_block() if program is not None else None
    feed_set = set(feed_names)
    dp_size = int(mesh.shape["dp"]) if "dp" in mesh.axis_names else 1

    for name, info in (inputs.items() if on_mesh else ()):
        if name.startswith("@"):
            continue
        spec, shape, nbytes = info["spec"], info["shape"], info["nbytes"]
        var = gblock.vars.get(name) if gblock is not None else None
        is_feed = name in feed_set or (
            var is not None and getattr(var, "is_data", False))
        persistable = (var is not None
                       and getattr(var, "persistable", False)) or \
            (var is None and not is_feed)

        if persistable and nbytes >= threshold \
                and all(e is None for e in spec):
            findings.append(ShardingFinding(
                "replicated-large-param",
                f"{nbytes / 2**20:.1f} MiB persistable input is fully "
                f"replicated across mesh "
                f"{dict((a, int(mesh.shape[a])) for a in mesh.axis_names)}"
                f" — every chip holds (and updates) the whole tensor",
                var=name, nbytes=nbytes, actual=spec,
                declared=_declared_spec(var, mesh, shape),
                op_type="param"))
        if is_feed and dp_size > 1 and len(shape) > batch_dim \
                and spec[batch_dim] is None:
            why = ""
            if shape[batch_dim] % dp_size:
                why = (f" (dim {batch_dim} = {shape[batch_dim]} does "
                       f"not divide dp={dp_size})")
            findings.append(ShardingFinding(
                "unsharded-batch",
                f"batch dim {batch_dim} is unsharded under a dp={dp_size} "
                f"axis{why} — every chip computes the full batch",
                var=name, nbytes=nbytes, actual=spec,
                declared=("dp",), op_type="feed"))
        if var is not None and getattr(var, "dist_attr", None):
            declared = _normalize_spec(
                partition_spec(mesh, var.dist_attr, shape), len(shape))
            if declared != spec and any(e is not None for e in declared):
                findings.append(ShardingFinding(
                    "sharding-mismatch",
                    f"declared dist_attr {_spec_str(declared)} but the "
                    f"compiled executable placed it {_spec_str(spec)}",
                    var=name, nbytes=nbytes, actual=spec,
                    declared=declared, op_type="param"))

    if collectives is None:
        from .comms import parse_collectives
        try:
            text = hlo_text if hlo_text is not None \
                else compiled.as_text()
        except Exception:  # noqa: BLE001 — backend-dependent surface
            text = ""
        collectives = parse_collectives(text, mesh)
    findings.extend(_reshard_findings(collectives))
    return ShardingAuditReport(findings, inputs=inputs)


def _declared_spec(var, mesh, shape):
    from ..parallel.mesh import partition_spec
    if var is None or not getattr(var, "dist_attr", None):
        return None
    return _normalize_spec(
        partition_spec(mesh, var.dist_attr, shape), len(shape))


# explicit collective primitives: an instruction whose metadata op_name
# contains one of these was asked for by the program (shard_map
# ppermute / all_to_all in the pipeline+MoE ops), not inserted by the
# partitioner
_EXPLICIT_MARKERS = ("all_gather", "all_to_all", "ppermute",
                     "psum_scatter")


def _reshard_findings(collectives):
    """``reshard-inserted``: all-gather / all-to-all collectives the
    partitioner added on intermediates (metadata names the op the
    reshard was inserted FOR — an explicit collective names its own
    primitive)."""
    out = []
    for c in collectives:
        if c["kind"] not in ("all-gather", "all-to-all"):
            continue
        op_name = c["op_name"]
        base = op_name.rsplit("/", 1)[-1] if op_name else ""
        if any(m in base for m in _EXPLICIT_MARKERS):
            continue
        out.append(ShardingFinding(
            "reshard-inserted",
            f"GSPMD inserted a {c['kind']} over axis {c['axis']!r} "
            f"moving {c['payload_bytes']} bytes per step (inserted "
            f"for {base or 'an unnamed op'})",
            var=base or None, nbytes=c["payload_bytes"],
            actual=(c["axis"],), declared=None, op_type=c["kind"]))
    return out


# ---------------------------------------------------------------------------
# The flag-gated hook the executor / serving engine / generator call
# once per newly compiled executable.
# ---------------------------------------------------------------------------

_recent = {}
_recent_lock = threading.Lock()
_recent_seq = 0
_MAX_RECENT = 32


def observe_executable(where, compiled, mesh, program=None,
                       feed_names=(), batch_dim=0, cost=None,
                       dcn_axes=None, tag=None):
    """Run the FLAGS-selected subset of {sharding audit, collective
    ledger} over one newly compiled executable, export the results
    (metrics, flight events, Perfetto tracks), and retain the record in
    :func:`recent_observations`. Caller contract: invoke ONCE per
    executable (the compile-miss path — the memoization IS the call
    site), wrap in try/except (telemetry never kills a step), and skip
    when both flags are off. ``dcn_axes`` defaults to
    ``FLAGS_comms_dcn_axes`` (the multi-slice operator knob — hooks
    don't know which axes cross slices). Returns the record dict or
    None."""
    if mesh is None:
        return None
    audit = _flag("shard_audit")
    ledger_on = _flag("comms_ledger")
    if not (audit or ledger_on):
        return None
    if dcn_axes is None:
        dcn_axes = tuple(a.strip() for a in
                         _flag("comms_dcn_axes").split(",")
                         if a.strip())
    unknown = tuple(a for a in dcn_axes if a not in mesh.axis_names)
    if unknown:
        # a listed cross-slice axis the active mesh doesn't have prices
        # NOTHING at DCN — silently, which is exactly how a typo'd
        # FLAGS_comms_dcn_axes would fake an all-ICI traffic profile
        _flightrec().record(
            "comms_dcn_axis_unknown", axes=",".join(unknown),
            mesh_axes=",".join(mesh.axis_names), where=where,
            hint="FLAGS_comms_dcn_axes names axes absent from the "
                 "active mesh; their collectives are priced at ICI "
                 "bandwidth, not DCN")
    record = {"where": where, "tag": tag or where}
    # ONE HLO text read + ONE regex parse, shared by the audit's
    # reshard scan and the ledger (real mesh programs' optimized HLO
    # runs to megabytes)
    from .comms import parse_collectives
    try:
        hlo_text = compiled.as_text()
    except Exception:  # noqa: BLE001 — backend-dependent surface
        hlo_text = ""
    collectives = parse_collectives(hlo_text, mesh)
    if audit:
        report = audit_executable(compiled, mesh, program=program,
                                  feed_names=feed_names,
                                  batch_dim=batch_dim,
                                  collectives=collectives)
        record["audit"] = report
        record["findings"] = report.counts()
        for f in report.findings[:_MAX_FLIGHT_FINDINGS]:
            _flightrec().record(
                "shard_audit_finding", code=f.code, var=f.var,
                bytes=f.nbytes, where=where,
                axes=_spec_str(f.actual), tag=record["tag"])
        for code, n in report.counts().items():
            _FINDINGS_TOTAL.inc(n, labels=(code,))
    if ledger_on:
        from .comms import CommLedger, observe_ledger
        ledger = CommLedger(collectives, mesh=mesh)
        record["ledger"] = ledger
        record["comm_bound_ratio"] = observe_ledger(
            where, ledger, cost=cost, dcn_axes=dcn_axes)
    global _recent_seq
    with _recent_lock:
        if len(_recent) >= _MAX_RECENT:
            _recent.pop(next(iter(_recent)))
        # keys must stay unique per EXECUTABLE: the serving engine /
        # generator pass constant tags and the executor reuses one
        # program tag across feed-shape buckets — overwriting would
        # silently drop all but the last compile's record
        key = record["tag"]
        if key in _recent:
            _recent_seq += 1
            key = f"{key}#{_recent_seq}"
        _recent[key] = record
    return record


def maybe_observe(where, compiled, mesh, program=None, feed_names=(),
                  batch_dim=0, cost=None, tag=None):
    """The ONE flag-gated, exception-swallowing front door the
    executor / serving engine / generator hooks call on their
    compile-miss paths: both flags off (or no mesh) costs the flag
    reads and nothing else; an analysis failure never kills the step
    it describes."""
    if mesh is None or not (_flag("shard_audit")
                            or _flag("comms_ledger")):
        return None
    try:
        return observe_executable(where, compiled, mesh,
                                  program=program,
                                  feed_names=feed_names,
                                  batch_dim=batch_dim, cost=cost,
                                  tag=tag)
    except Exception:  # noqa: BLE001 — telemetry never kills a step
        return None


def recent_observations(clear=False):
    """{tag: record} of the most recent :func:`observe_executable`
    calls (bounded). Records hold the live ``ShardingAuditReport`` /
    ``CommLedger`` objects — the MULTICHIP dryruns and tests read them
    back here after a flag-on run."""
    with _recent_lock:
        out = dict(_recent)
        if clear:
            _recent.clear()
    return out


# ---------------------------------------------------------------------------
# Offline lowering: compile a Program under a mesh from avals alone
# (no data, no initialized scope) — the shard_report CLI / test path.
# ---------------------------------------------------------------------------

def lower_program(program, mesh, batch=8, fetch_names=None,
                  feed_names=None):
    """AOT-lower+compile ``program``'s global block under ``mesh`` from
    shape/dtype avals alone: feeds take the executor's batch-dim dp
    sharding, state takes each var's declared ``dist_attr`` placement
    (unannotated vars replicate — exactly what ``Executor.run`` does
    with a real scope). ``-1`` feed dims substitute ``batch``. Returns
    ``(compiled, feed_names)``."""
    import jax
    from ..framework.dtype import np_dtype
    from ..framework.executor import _batch_pspec_shape
    from ..framework.lowering import analyze_block_io, build_block_fn
    from ..parallel.mesh import sharding_for
    from jax.sharding import NamedSharding

    gblock = program.global_block()
    if feed_names is None:
        feed_names = [n for n, v in gblock.vars.items()
                      if getattr(v, "is_data", False)]
    feed_names = list(feed_names)
    if fetch_names is None:
        # default fetch root: the last op's last output (the loss in a
        # train program; enough to keep the whole block live)
        fetch_names = []
        ops = program.global_block().ops
        if ops:
            outs = list(ops[-1].output_arg_names)
            fetch_names = outs[-1:] if outs else []
    state_in, state_out = analyze_block_io(program, 0, feed_names)
    fn = build_block_fn(program, 0, feed_names, list(fetch_names),
                        state_in, state_out, mesh=mesh)

    def _aval(shape, dtype, sharding):
        return jax.ShapeDtypeStruct(tuple(shape), dtype,
                                    sharding=sharding)

    feeds = {}
    for n in feed_names:
        var = gblock.vars[n]
        shape = tuple(int(batch) if int(d) == -1 else int(d)
                      for d in var.shape)
        feeds[n] = _aval(shape, np_dtype(var.dtype),
                         NamedSharding(mesh,
                                       _batch_pspec_shape(mesh, shape)))
    state = {}
    for n in state_in:
        var = gblock.vars.get(n)
        if var is None or getattr(var, "shape", None) is None:
            raise ValueError(
                f"state var {n!r} has no declared shape — cannot "
                f"lower from avals")
        shape = tuple(int(d) for d in var.shape)
        if any(d < 0 for d in shape):
            raise ValueError(
                f"state var {n!r} has a dynamic shape {shape} — "
                f"cannot lower from avals")
        state[n] = _aval(shape, np_dtype(var.dtype),
                         sharding_for(mesh, var))
    key = jax.random.PRNGKey(program.random_seed or 0)
    jitted = jax.jit(fn)
    compiled = jitted.lower({}, state, feeds, key).compile()
    return compiled, feed_names
