#!/usr/bin/env python
"""Training goodput report: render the goodput ledger as a table and
gate CI on a goodput floor.

Sources (exactly one):

- ``--from FILE`` — a Prometheus text dump written by
  ``tools/export_metrics.py`` (``--out``) from a training process;
- ``--url URL`` — a live scrape of an ``export_metrics.serve()``
  endpoint (or any exposition URL);
- no source — THIS process's registry (the library path:
  ``import train_report; train_report.main([])`` after training
  in-process).

``--flight FILE`` (a ``FlightRecorder.dump`` JSON) adds the top
``data_stall`` windows to the table. ``--assert-goodput-floor X``
exits 1 when compute/wall < X, NAMING the worst non-compute category —
the CI gate that keeps an input-pipeline regression from landing as a
silent MFU drop.

Usage:
    python tools/export_metrics.py --out train.prom   # in the trainer
    python tools/train_report.py --from train.prom \\
        --assert-goodput-floor 0.5
"""
import argparse
import json
import re
import sys

_CAT_RE = re.compile(
    r'^train_time_seconds_total\{category="([^"]+)"\}\s+(\S+)\s*$')
_RATIO_RE = re.compile(r"^train_goodput_ratio\s+(\S+)\s*$")


def parse_exposition(text):
    """-> {"categories": {name: seconds}, "goodput_ratio": float|None}
    from Prometheus text format."""
    cats = {}
    ratio = None
    for line in text.splitlines():
        m = _CAT_RE.match(line)
        if m:
            cats[m.group(1)] = float(m.group(2))
            continue
        m = _RATIO_RE.match(line)
        if m:
            ratio = float(m.group(1))
    return {"categories": cats, "goodput_ratio": ratio}


def top_stalls(flight_doc, n=5):
    """The n largest data_stall windows from a flight-recorder dump."""
    events = [e for e in flight_doc.get("events", ())
              if e.get("kind") == "data_stall"]
    events.sort(key=lambda e: -float(e.get("wait_ms", 0.0)))
    return events[:n]


def cumulative_ratio(categories):
    """compute / total over the scraped counters — the ratio that is
    CONSISTENT with the table and with worst_category() (the
    train_goodput_ratio gauge covers only the most recent run, while
    the counters accumulate across the process lifetime)."""
    total = sum(categories.values())
    return (categories.get("compute", 0.0) / total) if total else 0.0


def render(categories, goodput_ratio=None, stalls=()):
    """The per-category table (share of the category sum — the dump has
    no wall clock, but a stopped ledger's categories sum to wall)."""
    total = sum(categories.values())
    lines = ["----------------  Training goodput ledger  "
             "----------------",
             f"{'category':<12} {'seconds':>12} {'share':>8}"]
    for cat in sorted(categories, key=lambda c: -categories[c]):
        share = (categories[cat] / total * 100.0) if total > 0 else 0.0
        lines.append(f"{cat:<12} {categories[cat]:>12.3f} "
                     f"{share:>7.1f}%")
    lines.append(f"{'total':<12} {total:>12.3f} {100.0:>7.1f}%")
    lines.append(f"goodput ratio (compute/wall, cumulative): "
                 f"{cumulative_ratio(categories):.4f}")
    if goodput_ratio is not None:
        lines.append(f"goodput ratio (last run, gauge): "
                     f"{goodput_ratio:.4f}")
    for ev in stalls:
        lines.append(
            f"stall: queue={ev.get('queue', '?')} waited "
            f"{float(ev.get('wait_ms', 0.0)):.1f}ms "
            f"({float(ev.get('fraction', 0.0)):.0%} of a "
            f"{float(ev.get('window_s', 0.0)):.2f}s window)")
    return "\n".join(lines)


def worst_category(categories):
    """The largest NON-compute category — what a goodput-floor
    violation names as the thing to fix."""
    non_compute = {c: s for c, s in categories.items() if c != "compute"}
    if not non_compute:
        return None, 0.0
    worst = max(non_compute, key=non_compute.get)
    return worst, non_compute[worst]


def _live_text():
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo)
    from paddle_tpu.observability import render_metrics
    return render_metrics()


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--from", dest="src", default=None,
                    help="Prometheus text dump (export_metrics.py "
                         "--out)")
    ap.add_argument("--url", default=None,
                    help="live exposition URL (export_metrics.serve)")
    ap.add_argument("--flight", default=None,
                    help="flight-recorder dump JSON: adds the top "
                         "data_stall windows")
    ap.add_argument("--assert-goodput-floor", type=float, default=None,
                    metavar="X",
                    help="exit 1 when compute/wall < X, naming the "
                         "worst non-compute category")
    args = ap.parse_args(argv)
    if args.src:
        with open(args.src, encoding="utf-8") as f:
            text = f.read()
    elif args.url:
        from urllib.request import urlopen
        with urlopen(args.url, timeout=10) as resp:
            text = resp.read().decode("utf-8")
    else:
        text = _live_text()
    parsed = parse_exposition(text)
    cats = parsed["categories"]
    if not cats:
        print("no train_time_seconds_total samples found — did a "
              "TrainingSupervisor run in the scraped process?",
              file=sys.stderr)
        return 2
    stalls = ()
    if args.flight:
        with open(args.flight, encoding="utf-8") as f:
            stalls = top_stalls(json.load(f))
    print(render(cats, parsed["goodput_ratio"], stalls))
    if args.assert_goodput_floor is not None:
        # the floor and the named worst category both come from the
        # SAME cumulative counters — judging the last-run gauge while
        # blaming a category accumulated across earlier runs would
        # point the operator at the wrong fix
        total = sum(cats.values())
        ratio = cumulative_ratio(cats)
        if ratio < args.assert_goodput_floor:
            worst, secs = worst_category(cats)
            print(f"GOODPUT-FLOOR VIOLATION: ratio {ratio:.4f} < floor "
                  f"{args.assert_goodput_floor}; worst non-compute "
                  f"category: {worst} ({secs:.3f}s of "
                  f"{total:.3f}s wall)", file=sys.stderr)
            return 1
        print(f"OK: goodput ratio {ratio:.4f} >= floor "
              f"{args.assert_goodput_floor}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
