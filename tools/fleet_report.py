#!/usr/bin/env python
"""Fleet overload report: render autoscaler decisions and per-class
admission outcomes from a Prometheus dump, and gate CI on the
interactive-class p99.

Sources (exactly one):

- ``--from FILE`` — a Prometheus text dump written by
  ``tools/export_metrics.py`` (``--out``) from a serving process (or a
  router's fleet-wide aggregation);
- ``--url URL`` — a live scrape of any exposition endpoint;
- no source — THIS process's registry (the library path after an
  in-process fleet run).

Rendered: the autoscaler trail (``fleet_replicas_count{state}``,
``fleet_scale_events_total{direction}``), the per-class ledger
(``serving_class_completed_total{class}`` vs
``serving_admission_shed_total{class}`` plus the class p99 from
``serving_class_latency_ms``), and the overload-control counters
(``serving_retry_budget_exhausted_total``,
``serving_expired_in_queue_total``).

``--assert-interactive-p99-ms X`` exits 1 when the interactive-class
p99 exceeds X — the CI gate that keeps an overload-control regression
(a retry storm reaching interactive traffic) from landing as a silent
tail blowup. Exit 2 when the dump has no interactive samples to judge.

Usage:
    python tools/export_metrics.py --out fleet.prom   # in the server
    python tools/fleet_report.py --from fleet.prom \\
        --assert-interactive-p99-ms 250
"""
import argparse
import re
import sys

_SAMPLE_RE = re.compile(
    r'^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'(\w+)="([^"]*)"')


def parse_exposition(text):
    """-> {metric: {frozen-label-tuple: value}} for every sample line
    (labels as a sorted tuple of (k, v) pairs)."""
    out = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        m = _SAMPLE_RE.match(line)
        if m is None:
            continue
        name, labelstr, val = m.group(1), m.group(2) or "", m.group(3)
        try:
            v = float(val)
        except ValueError:
            continue
        labels = tuple(sorted(_LABEL_RE.findall(labelstr)))
        out.setdefault(name, {})[labels] = \
            out.get(name, {}).get(labels, 0.0) + v
    return out


def _by_label(samples, key):
    """Fold a metric's samples onto one label axis (summing the rest —
    a router-aggregated dump carries an extra ``replica`` label)."""
    out = {}
    for labels, v in (samples or {}).items():
        d = dict(labels)
        if key in d:
            out[d[key]] = out.get(d[key], 0.0) + v
    return out


def _total(samples):
    return sum((samples or {}).values())


def class_p99_ms(metrics, cls="interactive"):
    """p99 (ms) of ``serving_class_latency_ms`` for one class, from the
    cumulative ``_bucket`` samples (linear interpolation inside the
    winning bucket, the Prometheus histogram_quantile convention).
    None when the class has no observations."""
    buckets = {}
    for labels, v in (metrics.get("serving_class_latency_ms_bucket")
                      or {}).items():
        d = dict(labels)
        if d.get("class") != cls or "le" not in d:
            continue
        le = float("inf") if d["le"] in ("+Inf", "inf") else float(d["le"])
        buckets[le] = buckets.get(le, 0.0) + v
    if not buckets:
        return None
    bounds = sorted(buckets)
    total = buckets[bounds[-1]]
    if total <= 0:
        return None
    target = total * 0.99
    prev_le, prev_cum = 0.0, 0.0
    for le in bounds:
        cum = buckets[le]
        if cum >= target:
            if le == float("inf"):
                return prev_le      # overflow bucket: clamp (Prom idiom)
            width = le - prev_le
            in_bucket = cum - prev_cum
            frac = ((target - prev_cum) / in_bucket) if in_bucket else 0
            return prev_le + width * frac
        prev_le, prev_cum = le, cum
    return bounds[-1]


def summarize(metrics):
    """Everything the report renders, as one dict (the --json payload
    and the test surface)."""
    completed = _by_label(
        metrics.get("serving_class_completed_total"), "class")
    shed = _by_label(metrics.get("serving_admission_shed_total"),
                     "class")
    classes = {}
    for cls in sorted(set(completed) | set(shed)):
        done = completed.get(cls, 0.0)
        lost = shed.get(cls, 0.0)
        offered = done + lost
        classes[cls] = {
            "completed": done, "shed": lost,
            "goodput": round(done / offered, 4) if offered else None,
            "p99_ms": class_p99_ms(metrics, cls),
        }
    return {
        "replicas": _by_label(metrics.get("fleet_replicas_count"),
                              "state"),
        "scale_events": _by_label(
            metrics.get("fleet_scale_events_total"), "direction"),
        "classes": classes,
        "retry_budget_exhausted": _total(
            metrics.get("serving_retry_budget_exhausted_total")),
        "expired_in_queue": _total(
            metrics.get("serving_expired_in_queue_total")),
    }


def render(doc):
    lines = ["----------------  Fleet overload report  ----------------"]
    reps = doc["replicas"]
    if reps:
        lines.append("replicas: " + ", ".join(
            f"{s}={int(n)}" for s, n in sorted(reps.items())))
    ev = doc["scale_events"]
    lines.append(f"autoscaler events: up={int(ev.get('up', 0))} "
                 f"down={int(ev.get('down', 0))}")
    lines.append(f"{'class':<14} {'completed':>10} {'shed':>8} "
                 f"{'goodput':>8} {'p99_ms':>10}")
    for cls, row in doc["classes"].items():
        gp = f"{row['goodput']:.3f}" if row["goodput"] is not None \
            else "-"
        p99 = f"{row['p99_ms']:.1f}" if row["p99_ms"] is not None \
            else "-"
        lines.append(f"{cls:<14} {int(row['completed']):>10} "
                     f"{int(row['shed']):>8} {gp:>8} {p99:>10}")
    lines.append(f"retry budget exhaustions: "
                 f"{int(doc['retry_budget_exhausted'])}")
    lines.append(f"expired while queued: "
                 f"{int(doc['expired_in_queue'])}")
    return "\n".join(lines)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Fleet overload/autoscaler report + interactive-p99 "
                    "CI gate")
    ap.add_argument("--from", dest="src", default=None,
                    help="Prometheus text dump file")
    ap.add_argument("--url", default=None,
                    help="live exposition URL to scrape")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as JSON instead of a table")
    ap.add_argument("--assert-interactive-p99-ms", type=float,
                    default=None, metavar="X",
                    help="exit 1 when the interactive-class p99 "
                         "exceeds X ms")
    args = ap.parse_args(argv)
    if args.src and args.url:
        ap.error("--from and --url are mutually exclusive")
    if args.src:
        with open(args.src, encoding="utf-8") as f:
            text = f.read()
    elif args.url:
        from urllib.request import urlopen
        with urlopen(args.url, timeout=10) as r:
            text = r.read().decode("utf-8", "replace")
    else:
        from paddle_tpu.observability.metrics import render_metrics
        text = render_metrics()
    doc = summarize(parse_exposition(text))
    if args.json:
        import json
        print(json.dumps(doc, indent=2, sort_keys=True))
    else:
        print(render(doc))
    floor = args.assert_interactive_p99_ms
    if floor is not None:
        p99 = doc["classes"].get("interactive", {}).get("p99_ms")
        if p99 is None:
            print("FLEET REPORT: no interactive-class latency samples "
                  "in the dump — nothing to gate", file=sys.stderr)
            return 2
        if p99 > floor:
            print(f"INTERACTIVE-P99 VIOLATION: p99 {p99:.1f}ms exceeds "
                  f"the {floor:.1f}ms gate "
                  f"(completed="
                  f"{int(doc['classes']['interactive']['completed'])}, "
                  f"budget exhaustions="
                  f"{int(doc['retry_budget_exhausted'])})",
                  file=sys.stderr)
            return 1
        print(f"OK: interactive p99 {p99:.1f}ms <= {floor:.1f}ms")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, __file__.rsplit("/", 2)[0])
    sys.exit(main())
