#!/usr/bin/env python
"""Standalone Program-IR lint: run the framework verifier
(paddle_tpu/framework/analysis.py) over a saved program and print every
diagnostic — the CLI front-end to the same checker suite
``FLAGS_verify_passes`` runs between optimization passes.

Usage:
    python tools/lint_program.py <path> [--shapes] [--fetch NAME ...]
    python tools/lint_program.py --list-checks

<path> is an inference-model directory (containing ``__model__``), a
``__model__``/``*.pdmodel`` JSON file, or any file written by
save_inference_model. Exit 1 when any diagnostic fires.

    --shapes        also run registry-driven shape/dtype inference
                    checking (re-derives every output shape through the
                    op's registered lowering; slower)
    --fetch NAME    extra fetch targets to check reachability for
                    (defaults to the model's recorded fetch_var_names)
    --list-checks   print the diagnostics catalog and exit
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_program(path):
    """(program, feed_names, fetch_names) from a model dir or file."""
    if os.path.isdir(path):
        path = os.path.join(path, "__model__")
    with open(path) as f:
        model = json.load(f)
    from paddle_tpu.framework.core import Program
    if "program" in model:          # save_inference_model layout
        return (Program.from_dict(model["program"]),
                model.get("feed_var_names", ()),
                model.get("fetch_var_names", ()))
    return Program.from_dict(model), (), ()   # bare .pdmodel program dump


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Verify a saved program's IR well-formedness")
    ap.add_argument("path", nargs="?",
                    help="model dir or __model__/.pdmodel file")
    ap.add_argument("--shapes", action="store_true",
                    help="also check declared shapes/dtypes against the "
                         "registry lowering's inference")
    ap.add_argument("--fetch", action="append", default=[],
                    help="extra fetch target to check (repeatable)")
    ap.add_argument("--pedantic", action="store_true",
                    help="also run pedantic-tier checkers "
                         "(dead-persistable-write)")
    ap.add_argument("--list-checks", action="store_true",
                    help="print the diagnostics catalog and exit")
    args = ap.parse_args(argv)

    from paddle_tpu.framework.analysis import CHECKS, collect_diagnostics
    if args.list_checks:
        for code in sorted(CHECKS):
            print(f"{code:26s} {CHECKS[code]}")
        return 0
    if not args.path:
        ap.error("a model path is required (or --list-checks)")

    program, feeds, fetches = load_program(args.path)
    fetches = list(fetches) + list(args.fetch)
    diags = collect_diagnostics(program, fetch_names=fetches,
                                feed_names=feeds,
                                check_shapes=args.shapes,
                                pedantic=args.pedantic)
    n_ops = sum(len(b.ops) for b in program.blocks)
    if not diags:
        print(f"OK: {n_ops} ops / {len(program.blocks)} block(s), "
              f"{len(fetches)} fetch target(s) verified"
              + (" (shapes checked)" if args.shapes else ""))
        return 0
    print(f"{len(diags)} diagnostic(s) in {args.path}:")
    for d in diags:
        print(" -", d)
    return 1


if __name__ == "__main__":
    sys.exit(main())
