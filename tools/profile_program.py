#!/usr/bin/env python
"""Standalone performance-attribution CLI: run the per-op cost profiler
and the HBM live-set memory profiler (paddle_tpu/observability/
profiling.py) over a saved program — the offline front-end to the same
machinery ``FLAGS_profile_ops`` samples at run time.

Usage:
    python tools/profile_program.py <path> [--ops] [--memory]
        [--topk N] [--batch B] [--json]
        [--assert-mfu-floor R [--peak-tflops T --peak-hbm-gbs G]]

<path> is an inference-model directory (containing ``__model__``), a
``__model__``/``*.pdmodel`` JSON file, or any file written by
save_inference_model (the ``tools/lint_program.py`` input contract).

    --ops              per-op cost table (flops/bytes/roofline est_ms,
                       ranked; the default when neither mode is given)
    --memory           HBM live-set report: peak bytes, op index at
                       peak, top-k tensors live at peak
    --topk N           rows/tensors to print (default 12)
    --batch B          value substituted for -1 (batch) dims
                       (default 32)
    --json             machine-readable output (one JSON object)
    --assert-mfu-floor R
                       exit 1 with a named finding when the program's
                       ROOFLINE-LIMITED MFU estimate (est flops /
                       (est time * peak flops)) is below R — the CI
                       guardrail against landing a bandwidth-starved
                       program shape
    --peak-tflops T / --peak-hbm-gbs G
                       override the peak tables (CPU CI boxes have no
                       TPU entry; same contract as
                       observability.set_peaks)
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def load_program(path):
    """(program, feed_names, fetch_names) — same loader contract as
    tools/lint_program.py."""
    if os.path.isdir(path):
        path = os.path.join(path, "__model__")
    with open(path) as f:
        model = json.load(f)
    from paddle_tpu.framework.core import Program
    if "program" in model:          # save_inference_model layout
        return (Program.from_dict(model["program"]),
                model.get("feed_var_names", ()),
                model.get("fetch_var_names", ()))
    return Program.from_dict(model), (), ()


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Per-op cost + HBM live-set profile of a saved "
                    "program")
    ap.add_argument("path", help="model dir or __model__/.pdmodel file")
    ap.add_argument("--ops", action="store_true",
                    help="per-op cost attribution table")
    ap.add_argument("--memory", action="store_true",
                    help="HBM live-set memory profile")
    ap.add_argument("--topk", type=int, default=12)
    ap.add_argument("--batch", type=int, default=32,
                    help="value substituted for -1 (batch) dims")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--assert-mfu-floor", type=float, default=None,
                    help="exit 1 when the roofline-limited MFU "
                         "estimate is below this ratio")
    ap.add_argument("--peak-tflops", type=float, default=None)
    ap.add_argument("--peak-hbm-gbs", type=float, default=None)
    args = ap.parse_args(argv)
    if not args.ops and not args.memory:
        args.ops = True

    from paddle_tpu.observability import profiling, set_peaks
    if args.peak_tflops or args.peak_hbm_gbs:
        set_peaks(
            flops_per_s=(args.peak_tflops * 1e12
                         if args.peak_tflops else None),
            hbm_bytes_per_s=(args.peak_hbm_gbs * 1e9
                             if args.peak_hbm_gbs else None))

    program, feeds, fetches = load_program(args.path)
    out = {"path": args.path, "n_ops":
           sum(len(b.ops) for b in program.blocks)}
    report = None
    if args.ops or args.assert_mfu_floor is not None:
        report = profiling.profile_program(
            program, fetch_list=list(fetches), batch=args.batch,
            topk=None, optimize=False, measured=False)
        out["ops"] = report["ops"][:args.topk]
        out["totals"] = report["totals"]
        out["named_share"] = report["named_share"]
    if args.memory:
        out["memory"] = profiling.memory_profile(
            program, fetch_names=tuple(fetches), batch=args.batch,
            topk=args.topk)
        out["memory"].pop("timeline", None)   # keep the output compact

    finding = None
    if args.assert_mfu_floor is not None:
        t = report["totals"]
        est_s = t["est_ms"] / 1e3
        mfu = (t["flops"] / (est_s * t["peak_flops"])) if est_s else 0.0
        out["est_mfu"] = round(mfu, 6)
        if mfu < args.assert_mfu_floor:
            top = report["ops"][0] if report["ops"] else None
            finding = (
                f"MFU-FLOOR VIOLATION: roofline-limited MFU estimate "
                f"{mfu:.4f} < floor {args.assert_mfu_floor:.4f}"
                + (f"; top cost op: #{top['index']} {top['type']!r} "
                   f"({top['bound']}-bound, "
                   f"{top['share'] * 100:.1f}% of est time)"
                   if top else ""))
            out["finding"] = finding

    if args.as_json:
        print(json.dumps(out, default=float))
    else:
        if args.ops:
            print(profiling.format_table(report, topk=args.topk))
        if args.memory:
            m = out["memory"]
            print(f"peak HBM live set: {m['peak_bytes'] / 2**20:.2f} "
                  f"MiB at op #{m['peak_op_index']} "
                  f"({m['peak_op_type']}); resident baseline "
                  f"{m['baseline_bytes'] / 2**20:.2f} MiB")
            for r in m["top"]:
                print(f"  {r['bytes'] / 2**20:>9.2f} MiB  "
                      f"{r['name']:<40} [{r['kind']}, "
                      f"producer {r['producer']}]")
        if args.assert_mfu_floor is not None and finding is None:
            print(f"OK: est MFU {out['est_mfu']:.4f} >= floor "
                  f"{args.assert_mfu_floor:.4f}")
    if finding:
        print(finding, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
