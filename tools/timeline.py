#!/usr/bin/env python
"""Convert a profiler span dump into a Chrome tracing JSON (reference
tools/timeline.py — its --profile_path proto becomes the spans JSON
that paddle_tpu.profiler.stop_profiler(profile_path=...) writes; load
the output in chrome://tracing or Perfetto).

Spans come in two shapes, unified in one span table:

    [name, start_s, end_s, tid]                          profiler event
    [name, start_s, end_s, tid, trace_id, span_id,
     parent_id]                                          traced request

Traced spans (observability.tracing, wire-propagated request tracing)
carry their ids in the event ``args`` and are linked parent -> child
with Chrome flow events, so ONE request renders as one connected trace
interleaved with the host-side profiler spans around it.

Usage:
    python tools/timeline.py --profile_path /tmp/profile \\
        --timeline_path /tmp/timeline.json
"""
import argparse
import json


def to_chrome_trace(spans, counters=()):
    """spans: [(name, start_s, end_s, tid[, trace_id, span_id,
    parent_id])] -> Chrome trace dict (complete events, microsecond
    timebase, normalized to t0; flow events link traced parent/child
    spans). ``counters`` ([(name, t_s, value)] — e.g. the memory
    profiler's hbm_live_bytes live-set track) render as Chrome counter
    ("C") events, so Perfetto shows the byte timeline under the op
    spans."""
    if not spans and not counters:
        return {"traceEvents": []}
    t0 = min([s[1] for s in spans] + [c[1] for c in counters])
    if not spans:
        return {"traceEvents": [
            {"name": c[0], "ph": "C", "ts": (c[1] - t0) * 1e6,
             "pid": 0, "args": {"value": c[2]}} for c in counters]}
    events = []
    tids = {}
    # span_id -> (end_ts, tid) of traced spans, for flow binding
    by_span_id = {}
    traced = []
    for s in spans:
        name, start, end, tid = s[0], s[1], s[2], s[3]
        tids.setdefault(tid, len(tids))
        ev = {
            "name": name,
            "ph": "X",                       # complete event
            "ts": (start - t0) * 1e6,
            "dur": max((end - start) * 1e6, 0.001),
            "pid": 0,
            "tid": tids[tid],
            "cat": "host",
        }
        if len(s) >= 7:
            trace_id, span_id, parent_id = s[4], s[5], s[6]
            ev["cat"] = "request"
            ev["args"] = {"trace_id": trace_id, "span_id": span_id,
                          "parent_span_id": parent_id}
            by_span_id[span_id] = (ev["ts"], ev["dur"], tids[tid])
            traced.append(ev)
        events.append(ev)
    # flow events: one arrow per traced child from its parent span
    flows = []
    for ev in traced:
        parent = ev["args"]["parent_span_id"]
        src = by_span_id.get(parent)
        if not src:
            continue
        fid = f"{ev['args']['trace_id']}/{ev['args']['span_id']}"
        src_ts, src_dur, src_tid = src
        flows.append({"name": "trace", "ph": "s", "cat": "request",
                      "id": fid, "pid": 0, "tid": src_tid,
                      "ts": src_ts})
        flows.append({"name": "trace", "ph": "f", "bp": "e",
                      "cat": "request", "id": fid, "pid": 0,
                      "tid": ev["tid"], "ts": ev["ts"]})
    counter_events = [
        {"name": c[0], "ph": "C", "ts": (c[1] - t0) * 1e6, "pid": 0,
         "args": {"value": c[2]}} for c in counters]
    meta = [{"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "paddle_tpu host"}}]
    meta += [{"name": "thread_name", "ph": "M", "pid": 0, "tid": i,
              "args": {"name": f"thread {i}"}} for i in tids.values()]
    return {"traceEvents": meta + events + flows + counter_events,
            "displayTimeUnit": "ms"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile_path", required=True,
                    help="spans JSON written by profiler.stop_profiler")
    ap.add_argument("--timeline_path", required=True,
                    help="output Chrome trace JSON")
    args = ap.parse_args()
    with open(args.profile_path) as f:
        doc = json.load(f)
    spans = doc["spans"]
    counters = doc.get("counters", [])
    with open(args.timeline_path, "w") as f:
        json.dump(to_chrome_trace(spans, counters=counters), f)
    dropped = doc.get("dropped", 0)
    drop_note = f"; {dropped} spans were dropped at record time" \
        if dropped else ""
    counter_note = f", {len(counters)} counter samples" if counters \
        else ""
    print(f"wrote {args.timeline_path} ({len(spans)} spans"
          f"{counter_note}{drop_note}) "
          f"— open in chrome://tracing or Perfetto")


if __name__ == "__main__":
    main()
