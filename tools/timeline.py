#!/usr/bin/env python
"""Convert a profiler span dump into a Chrome tracing JSON (reference
tools/timeline.py — its --profile_path proto becomes the spans JSON
that paddle_tpu.profiler.stop_profiler(profile_path=...) writes; load
the output in chrome://tracing or Perfetto).

Usage:
    python tools/timeline.py --profile_path /tmp/profile \\
        --timeline_path /tmp/timeline.json
"""
import argparse
import json


def to_chrome_trace(spans):
    """spans: [(name, start_s, end_s, tid)] -> Chrome trace dict
    (complete events, microsecond timebase, normalized to t0)."""
    if not spans:
        return {"traceEvents": []}
    t0 = min(s[1] for s in spans)
    events = []
    tids = {}
    for name, start, end, tid in spans:
        tids.setdefault(tid, len(tids))
        events.append({
            "name": name,
            "ph": "X",                       # complete event
            "ts": (start - t0) * 1e6,
            "dur": max((end - start) * 1e6, 0.001),
            "pid": 0,
            "tid": tids[tid],
            "cat": "host",
        })
    meta = [{"name": "process_name", "ph": "M", "pid": 0,
             "args": {"name": "paddle_tpu host"}}]
    meta += [{"name": "thread_name", "ph": "M", "pid": 0, "tid": i,
              "args": {"name": f"thread {i}"}} for i in tids.values()]
    return {"traceEvents": meta + events,
            "displayTimeUnit": "ms"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--profile_path", required=True,
                    help="spans JSON written by profiler.stop_profiler")
    ap.add_argument("--timeline_path", required=True,
                    help="output Chrome trace JSON")
    args = ap.parse_args()
    with open(args.profile_path) as f:
        spans = json.load(f)["spans"]
    with open(args.timeline_path, "w") as f:
        json.dump(to_chrome_trace(spans), f)
    print(f"wrote {args.timeline_path} ({len(spans)} spans) — open in "
          f"chrome://tracing or Perfetto")


if __name__ == "__main__":
    main()
