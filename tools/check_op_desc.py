#!/usr/bin/env python
"""Op-schema compatibility gate (reference tools/check_op_desc.py:
compares the registered op protos between versions — deleting an op or
its grad support breaks saved programs). Here the schema is the
registry: {op_type: {grad, needs_rng, custom_grad, infer_shape}}.

Usage:
    python tools/check_op_desc.py --dump > tools/op_schema_baseline.json
    python tools/check_op_desc.py tools/op_schema_baseline.json
Exit 1 when an op was deleted or lost capability vs the baseline.
"""
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def current_schema():
    from paddle_tpu.framework.registry import OPS
    import paddle_tpu  # noqa: F401  (registers every op module)
    out = {}
    for t, d in sorted(OPS.items()):
        out[t] = {
            "grad": d.grad is not False,
            "custom_grad": d.custom_grad_lower is not None,
            "needs_rng": bool(d.needs_rng),
            "custom_infer_shape": not (d.infer_shape is None
                                       or d.infer_shape is False),
        }
    return out


def check(baseline, now):
    """Errors: deleted ops, ops that LOST grad support, ops whose RNG
    contract changed (a saved program's ops carry __rng_seed__ attrs iff
    the op consumed the stream at save time — flipping needs_rng makes
    every such program fail the verifier's missing-rng-seed check, or
    silently share stream 0). Returns (errors, added)."""
    errors = []
    for t, spec in baseline.items():
        if t not in now:
            errors.append(f"op {t!r} was deleted")
            continue
        if spec.get("grad") and not now[t]["grad"]:
            errors.append(f"op {t!r} lost gradient support")
        if "needs_rng" in spec and spec["needs_rng"] != now[t]["needs_rng"]:
            errors.append(
                f"op {t!r} changed its RNG contract (needs_rng "
                f"{spec['needs_rng']} -> {now[t]['needs_rng']}): saved "
                f"programs' __rng_seed__ attrs no longer line up")
    added = sorted(set(now) - set(baseline))
    return errors, added


def main():
    if "--dump" in sys.argv:
        print(json.dumps(current_schema(), indent=1, sort_keys=True))
        return
    with open(sys.argv[1]) as f:
        baseline = json.load(f)
    errors, added = check(baseline, current_schema())
    if errors:
        print("OP SCHEMA COMPATIBILITY ERRORS:")
        for e in errors:
            print(" -", e)
        sys.exit(1)
    print(f"op schema compatible: {len(baseline)} baseline ops intact"
          + (f", {len(added)} added ({', '.join(added[:8])}"
             f"{'...' if len(added) > 8 else ''})" if added else ""))


if __name__ == "__main__":
    main()
