#!/usr/bin/env python
"""Standalone sharding-audit + collective-ledger CLI: compile a saved
program under a named mesh FROM AVALS ALONE (no data, no initialized
scope — ``observability.sharding.lower_program``) and report what GSPMD
decided: per-tensor actual shardings diffed against the program's
``dist_attr`` annotations, and the per-(collective, axis) traffic
ledger parsed from the compiled HLO. The offline front-end to the same
machinery ``FLAGS_shard_audit`` / ``FLAGS_comms_ledger`` run at compile
time.

Usage:
    python tools/shard_report.py <path> [--mesh dp=2,tp=2] [--batch B]
        [--audit] [--ledger] [--json] [--topk N]
        [--threshold-mb X] [--assert-no-replicated-params]
        [--ici-gbs G] [--dcn-gbs G] [--dcn-axes pp,...]

<path> is an inference-model directory (containing ``__model__``), a
``__model__``/``*.pdmodel`` JSON file, or any file written by
save_inference_model (the tools/profile_program.py input contract;
``dist_attr`` annotations survive serialization).

    --mesh dp=2,tp=2   mesh axis sizes (default: dp over every device);
                       the CLI self-provisions that many virtual CPU
                       devices when the platform has too few
    --batch B          value substituted for -1 (batch) dims (default 8)
    --audit            per-tensor sharding findings table (the default
                       when neither mode is given)
    --ledger           per-(collective, axis) bytes/count table + the
                       predicted comm-bound fraction
    --json             machine-readable output (one JSON object)
    --threshold-mb X   replicated-large-param threshold (default:
                       FLAGS_shard_audit_replicated_mb)
    --assert-no-replicated-params
                       exit 1 NAMING the largest replicated param when
                       any replicated-large-param finding fires — the
                       CI gate a mesh PR runs over its sharded program
    --ici-gbs / --dcn-gbs / --dcn-axes
                       override the comm peak tables / mark axes as
                       cross-slice (observability.set_peaks contract)
"""
import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def parse_mesh_arg(spec):
    """"dp=2,tp=2" -> {"dp": 2, "tp": 2} (validated axis names)."""
    out = {}
    if not spec:
        return out
    for part in spec.split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(f"bad --mesh entry {part!r} "
                             f"(want axis=N)")
        name, _, n = part.partition("=")
        name = name.strip()
        if name not in ("dp", "tp", "pp", "sp", "ep"):
            raise ValueError(f"unknown mesh axis {name!r} "
                             f"(dp/tp/pp/sp/ep)")
        try:
            size = int(n)
        except ValueError:
            raise ValueError(f"bad --mesh entry {part!r} "
                             f"(want axis=N)") from None
        if size < 1:
            raise ValueError(f"bad --mesh entry {part!r} "
                             f"(axis size must be >= 1)")
        out[name] = size
    return out


def _provision(n_devices):
    """Make sure jax sees >= n virtual CPU devices — ONE copy of the
    fragile XLA_FLAGS/re-init dance lives in
    ``__graft_entry__._provision_cpu_devices``; delegate to it (the
    repo root is already on sys.path)."""
    import __graft_entry__
    return __graft_entry__._provision_cpu_devices(n_devices)


def load_program(path):
    """(program, feed_names, fetch_names) — ONE loader implementation
    shared with tools/profile_program.py (same input contract)."""
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import profile_program
    return profile_program.load_program(path)


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Sharding audit + collective-traffic ledger of a "
                    "saved program under a mesh")
    ap.add_argument("path", help="model dir or __model__/.pdmodel file")
    ap.add_argument("--mesh", default="",
                    help="axis sizes, e.g. dp=2,tp=2 (default: dp over "
                         "every visible device)")
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--audit", action="store_true")
    ap.add_argument("--ledger", action="store_true")
    ap.add_argument("--json", action="store_true", dest="as_json")
    ap.add_argument("--topk", type=int, default=12)
    ap.add_argument("--threshold-mb", type=float, default=None)
    ap.add_argument("--assert-no-replicated-params",
                    action="store_true")
    ap.add_argument("--ici-gbs", type=float, default=None)
    ap.add_argument("--dcn-gbs", type=float, default=None)
    ap.add_argument("--dcn-axes", default="",
                    help="comma list of axes that ride DCN (default "
                         "none)")
    args = ap.parse_args(argv)
    if not args.audit and not args.ledger:
        args.audit = True

    axes = parse_mesh_arg(args.mesh)
    import math
    n_needed = max(math.prod(axes.values()) if axes else 1, 1)
    devices = _provision(n_needed)

    from paddle_tpu.observability import set_peaks, sharding
    from paddle_tpu.observability.comms import CommLedger
    from paddle_tpu.parallel.mesh import MeshConfig, make_mesh
    if args.ici_gbs or args.dcn_gbs:
        set_peaks(ici_bytes_per_s=(args.ici_gbs * 1e9
                                   if args.ici_gbs else None),
                  dcn_bytes_per_s=(args.dcn_gbs * 1e9
                                   if args.dcn_gbs else None))
    dcn_axes = tuple(a.strip() for a in args.dcn_axes.split(",")
                     if a.strip())

    program, feeds, fetches = load_program(args.path)
    if axes:
        mesh = make_mesh(MeshConfig(**axes),
                         devices=devices[:n_needed])
    else:
        mesh = make_mesh(MeshConfig(dp=len(devices)), devices=devices)
    compiled, feed_names = sharding.lower_program(
        program, mesh, batch=args.batch,
        fetch_names=list(fetches) or None,
        feed_names=list(feeds) or None)

    out = {"path": args.path,
           "mesh": {a: int(mesh.shape[a]) for a in mesh.axis_names},
           "batch": args.batch}
    # one HLO read + one parse, shared by audit and ledger (the
    # observe_executable discipline — optimized mesh HLO is megabytes)
    from paddle_tpu.observability.comms import parse_collectives
    try:
        hlo_text = compiled.as_text()
    except Exception:  # noqa: BLE001 — backend-dependent surface
        hlo_text = ""
    collectives = parse_collectives(hlo_text, mesh)
    report = None
    if args.audit or args.assert_no_replicated_params:
        report = sharding.audit_executable(
            program=program, compiled=compiled, mesh=mesh,
            feed_names=feed_names, threshold_mb=args.threshold_mb,
            collectives=collectives)
        out["audit"] = {"counts": report.counts(),
                        "findings": [f.to_dict() for f in
                                     report.findings[:args.topk]]}
    ledger = None
    if args.ledger:
        ledger = CommLedger(collectives, mesh=mesh)
        comm_s, ref = ledger.predicted_comm_s(dcn_axes=dcn_axes)
        from paddle_tpu.observability.utilization import \
            executable_cost
        ratio = ledger.comm_bound_ratio(executable_cost(compiled),
                                        dcn_axes=dcn_axes)
        out["ledger"] = ledger.to_dict()
        out["predicted_comm_s"] = comm_s
        out["comm_bound_ratio"] = ratio
        out["ref_peaks"] = ref

    finding = None
    if args.assert_no_replicated_params:
        worst = report.worst("replicated-large-param")
        if worst is not None:
            n = len(report.by_code("replicated-large-param"))
            finding = (
                f"REPLICATED-PARAM VIOLATION: {n} persistable "
                f"input(s) fully replicated across mesh {out['mesh']}; "
                f"worst offender {worst.var!r} "
                f"({worst.nbytes / 2**20:.2f} MiB on every chip) — "
                f"annotate dist_attr before optimizer.minimize() or "
                f"raise --threshold-mb")
            out["finding"] = finding

    if args.as_json:
        print(json.dumps(out, default=float))
    else:
        print(f"mesh {out['mesh']} batch {args.batch}")
        if args.audit:
            print(report.format_table())
        if args.ledger:
            print(ledger.format_table())
            rp = " (reference v5e peaks)" if out["ref_peaks"] else ""
            print(f"predicted comm time/step: "
                  f"{out['predicted_comm_s'] * 1e3:.4f} ms{rp}; "
                  f"comm-bound fraction: "
                  + (f"{out['comm_bound_ratio']:.3f}"
                     if out["comm_bound_ratio"] is not None else "n/a"))
        if args.assert_no_replicated_params and finding is None:
            print("OK: no replicated-large-param findings")
    if finding:
        print(finding, file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
