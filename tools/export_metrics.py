#!/usr/bin/env python
"""Dump Prometheus text-format metrics to a textfile (the node-exporter
textfile-collector idiom) or stdout.

Two sources:

- ``--endpoint host:port``: scrape a running ``InferenceServer`` over
  the wire (the ``"metrics"`` op — works across processes).
- no endpoint: render THIS process's registry (useful from a training
  driver: ``import tools.export_metrics as em; em.export(path)`` after
  importing paddle_tpu subsystems).

The output file is written atomically (tmp + rename) so a scraper never
reads a torn exposition.

Usage:
    python tools/export_metrics.py --endpoint 127.0.0.1:8500 \\
        --out /var/lib/node_exporter/textfile/paddle_tpu.prom
    python tools/export_metrics.py            # this process, stdout
"""
import argparse
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def scrape(endpoint=None, auth_key=None):
    """The exposition text, from a remote server or this process."""
    if endpoint:
        from paddle_tpu.serving import Client
        with Client(endpoint, auth_key=auth_key) as c:
            return c.metrics()
    from paddle_tpu.observability import render_metrics
    return render_metrics()


def export(path, text=None, endpoint=None):
    """Write the exposition atomically to ``path``; returns the byte
    count."""
    text = text if text is not None else scrape(endpoint)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(text)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--endpoint", default=None,
                    help="serving endpoint host:port (default: render "
                         "this process's registry)")
    ap.add_argument("--router", default=None,
                    help="fleet Router endpoint host:port — the reply "
                         "is the FLEET-WIDE exposition: every replica's "
                         "samples re-exposed with a replica label (one "
                         "scrape sees the fleet)")
    ap.add_argument("--out", default=None,
                    help="textfile path (default: stdout)")
    args = ap.parse_args()
    endpoint = args.router or args.endpoint
    if args.out:
        n = export(args.out, endpoint=endpoint)
        print(f"wrote {n} bytes to {args.out}")
    else:
        sys.stdout.write(scrape(endpoint))
    return 0


if __name__ == "__main__":
    sys.exit(main())
