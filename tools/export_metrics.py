#!/usr/bin/env python
"""Dump Prometheus text-format metrics to a textfile (the node-exporter
textfile-collector idiom) or stdout — or serve them over HTTP.

Two sources:

- ``--endpoint host:port``: scrape a running ``InferenceServer`` over
  the wire (the ``"metrics"`` op — works across processes).
- no endpoint: render THIS process's registry (useful from a training
  driver: ``import tools.export_metrics as em; em.export(path)`` after
  importing paddle_tpu subsystems).

The output file is written atomically (tmp + rename) so a scraper never
reads a torn exposition.

Standalone / training-process mode: a training job has no serving wire
to answer ``{"op": "metrics"}``, so two in-process paths make it
scrapable exactly like a replica:

- ``serve("127.0.0.1:9400")`` starts a daemon-thread HTTP exposition
  endpoint inside the trainer (Prometheus scrapes it directly; the
  goodput ledger, stall profiler, and health gauges all ride along);
- ``--interval N --out path`` loops an atomic textfile dump every N
  seconds (the textfile-collector cadence for jobs behind a
  node-exporter).

Usage:
    python tools/export_metrics.py --endpoint 127.0.0.1:8500 \\
        --out /var/lib/node_exporter/textfile/paddle_tpu.prom
    python tools/export_metrics.py            # this process, stdout
    # in the training driver:
    #   import tools.export_metrics as em
    #   em.serve("127.0.0.1:9400")            # scrape like a replica
"""
import argparse
import os
import sys
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def scrape(endpoint=None, auth_key=None):
    """The exposition text, from a remote server or this process."""
    if endpoint:
        from paddle_tpu.serving import Client
        with Client(endpoint, auth_key=auth_key) as c:
            return c.metrics()
    from paddle_tpu.observability import render_metrics
    return render_metrics()


def export(path, text=None, endpoint=None):
    """Write the exposition atomically to ``path``; returns the byte
    count."""
    text = text if text is not None else scrape(endpoint)
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return len(text)


def serve(addr="127.0.0.1:0", endpoint=None):
    """Start a daemon-thread HTTP exposition server (the in-process
    Prometheus endpoint for TRAINING jobs — no serving wire needed).
    ``addr`` is ``host:port`` (port 0 = ephemeral); returns the live
    ``http.server`` instance — read ``server.server_address`` for the
    bound port, call ``server.shutdown()`` to stop. Every GET renders
    a fresh scrape of this process's registry (or of ``endpoint`` when
    forwarding)."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    class _Handler(BaseHTTPRequestHandler):
        def do_GET(self):  # noqa: N802 — http.server API
            try:
                body = scrape(endpoint).encode("utf-8")
            except Exception as exc:  # noqa: BLE001 — scrape survives
                self.send_response(500)
                self.end_headers()
                self.wfile.write(str(exc).encode("utf-8"))
                return
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def log_message(self, *a):   # scrapes are not access-log news
            pass

    host, _, port = addr.partition(":")
    server = ThreadingHTTPServer((host or "127.0.0.1", int(port or 0)),
                                 _Handler)
    t = threading.Thread(target=server.serve_forever, daemon=True,
                         name="metrics-exposition")
    t.start()
    return server


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--endpoint", default=None,
                    help="serving endpoint host:port (default: render "
                         "this process's registry)")
    ap.add_argument("--router", default=None,
                    help="fleet Router endpoint host:port — the reply "
                         "is the FLEET-WIDE exposition: every replica's "
                         "samples re-exposed with a replica label (one "
                         "scrape sees the fleet)")
    ap.add_argument("--out", default=None,
                    help="textfile path (default: stdout)")
    ap.add_argument("--interval", type=float, default=0.0,
                    help="with --out: re-dump every N seconds (the "
                         "textfile-collector loop for training jobs; "
                         "0 = dump once)")
    args = ap.parse_args()
    endpoint = args.router or args.endpoint
    if args.interval > 0 and not args.out:
        ap.error("--interval needs --out")
    if args.out:
        first = True
        while True:
            try:
                n = export(args.out, endpoint=endpoint)
                if first:
                    print(f"wrote {n} bytes to {args.out}", flush=True)
                    first = False
            except Exception as exc:  # noqa: BLE001 — a replica
                # restart or one timed-out exchange (including on the
                # VERY FIRST scrape — the exporter may start before
                # the trainer) must not kill the long-lived scrape
                # loop: stale-forever metrics are the exact failure
                # mode this exporter exists to prevent
                if args.interval <= 0:
                    raise
                print(f"scrape failed ({type(exc).__name__}: {exc}); "
                      f"retrying in {args.interval}s", file=sys.stderr,
                      flush=True)
            if args.interval <= 0:
                break
            time.sleep(args.interval)
    else:
        sys.stdout.write(scrape(endpoint))
    return 0


if __name__ == "__main__":
    sys.exit(main())
