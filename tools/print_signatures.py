#!/usr/bin/env python
"""Print every public API signature of a module tree in alphabetical
order (the paddle_tpu analog of the reference's
tools/print_signatures.py — the API-freeze half of its CI gate; pair
with tools/diff_api.py).

Usage:
    python tools/print_signatures.py paddle_tpu > tools/api_signatures.txt
"""
import hashlib
import importlib
import inspect
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# namespaces that form the frozen public surface
_DEFAULT_SUBMODULES = [
    "paddle_tpu", "paddle_tpu.layers", "paddle_tpu.optimizer",
    "paddle_tpu.dygraph", "paddle_tpu.io", "paddle_tpu.nets",
    "paddle_tpu.clip", "paddle_tpu.regularizer", "paddle_tpu.metrics",
    "paddle_tpu.profiler", "paddle_tpu.transpiler", "paddle_tpu.nn",
    "paddle_tpu.nn.functional", "paddle_tpu.tensor",
    "paddle_tpu.complex", "paddle_tpu.inference",
    "paddle_tpu.contrib.mixed_precision", "paddle_tpu.incubate.fleet",
    "paddle_tpu.serving", "paddle_tpu.framework.passes",
    "paddle_tpu.train", "paddle_tpu.observability",
]


def _sig_of(obj):
    try:
        return str(inspect.signature(obj))
    except (ValueError, TypeError):
        return "(*args, **kwargs)"


def _doc_hash(obj):
    doc = inspect.getdoc(obj) or ""
    return hashlib.md5(doc.encode()).hexdigest()[:8]


def collect(module_names):
    """{qualified_name: "signature dochash"} over public callables and
    classes (plus public methods of public classes)."""
    out = {}
    for mn in module_names:
        try:
            mod = importlib.import_module(mn)
        except ImportError:
            continue
        for name in sorted(dir(mod)):
            if name.startswith("_"):
                continue
            obj = getattr(mod, name)
            qual = f"{mn}.{name}"
            if inspect.isfunction(obj) or inspect.isbuiltin(obj):
                out[qual] = f"{_sig_of(obj)} doc:{_doc_hash(obj)}"
            elif inspect.isclass(obj):
                out[qual] = (f"{_sig_of(obj.__init__)} "
                             f"doc:{_doc_hash(obj)}")
                for m in sorted(dir(obj)):
                    if m.startswith("_"):
                        continue
                    meth = inspect.getattr_static(obj, m)
                    if callable(meth):
                        out[f"{qual}.{m}"] = _sig_of(
                            getattr(obj, m, meth))
    return out


def main():
    roots = sys.argv[1:] or _DEFAULT_SUBMODULES
    if roots == ["paddle_tpu"]:
        roots = _DEFAULT_SUBMODULES
    for name, sig in sorted(collect(roots).items()):
        print(f"{name} {sig}")


if __name__ == "__main__":
    main()
