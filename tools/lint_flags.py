#!/usr/bin/env python
"""FLAGS cross-reference lint: every ``FLAGS_<name>`` referenced
anywhere in ``paddle_tpu/`` must be declared in ``paddle_tpu/flags.py``,
and every declared flag must be referenced somewhere (read via
``flag("<name>")``/``FLAGS_<name>`` or documented as an accepted-no-op
compat knob in ``flags._COMPAT_ONLY``). Catches the two rot modes the
typed registry can't: a flag renamed in flags.py while a doc/env
reference keeps the old name, and a flag added "for later" that nothing
ever reads.

Usage: python tools/lint_flags.py        (exit 1 on any finding)
Also runs as a tier-1 test (tests/test_tools_gates.py).
"""
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

PKG = os.path.join(REPO, "paddle_tpu")
FLAGS_PY = os.path.join(PKG, "flags.py")

# FLAGS_<name> in code, strings, and docstrings; <name> ending in "_"
# is a prefix wildcard (docstring idiom "FLAGS_serving_*")
_REF_FLAGS = re.compile(r"FLAGS_([A-Za-z0-9_]+)")
# flag("<name>") / _flag("<name>") hot-path getter calls (the lookbehind
# instead of \b: a word boundary never matches between '_' and 'f', so
# \bflag\( would silently miss the dominant aliased _flag(...) idiom)
_REF_CALL = re.compile(
    r"(?<![A-Za-z0-9])_?flag\(\s*['\"]([A-Za-z0-9_]+)['\"]\s*\)")


def scan_references(pkg_dir=PKG):
    """{flag name -> [files]} for every reference outside flags.py."""
    refs = {}
    for dirpath, _dirs, files in sorted(os.walk(pkg_dir)):
        for fn in sorted(files):
            if not fn.endswith(".py"):
                continue
            path = os.path.join(dirpath, fn)
            if os.path.abspath(path) == os.path.abspath(FLAGS_PY):
                continue
            src = open(path, encoding="utf-8", errors="replace").read()
            rel = os.path.relpath(path, REPO)
            for pat in (_REF_FLAGS, _REF_CALL):
                for m in pat.finditer(src):
                    refs.setdefault(m.group(1), []).append(rel)
    return refs


def check(declared, compat_only, refs):
    """-> list of error strings (empty = clean). Wildcard references
    (trailing "_") expand to every declared flag with that prefix."""
    errors = []
    referenced = set()
    for name, files in sorted(refs.items()):
        if name.endswith("_"):      # prefix wildcard (FLAGS_serving_*)
            hits = {d for d in declared if d.startswith(name)}
            if hits:
                referenced |= hits
            else:
                errors.append(
                    f"FLAGS_{name}* (in {files[0]}) matches no "
                    f"declared flag prefix")
            continue
        if name in declared:
            referenced.add(name)
        else:
            errors.append(
                f"FLAGS_{name} referenced in {sorted(set(files))} but "
                f"not declared in paddle_tpu/flags.py")
    for name in sorted(declared - referenced - compat_only):
        errors.append(
            f"flag {name!r} is declared in paddle_tpu/flags.py but "
            f"nothing in paddle_tpu/ references it (read it, or add it "
            f"to flags._COMPAT_ONLY with a reason)")
    for name in sorted(compat_only - declared):
        errors.append(
            f"_COMPAT_ONLY lists {name!r}, which is not declared")
    for name in sorted(compat_only & referenced):
        errors.append(
            f"flag {name!r} is in _COMPAT_ONLY but IS referenced — "
            f"drop it from the compat set")
    return errors


def main():
    from paddle_tpu import flags as F
    errors = check(set(F._DEFS), set(F._COMPAT_ONLY), scan_references())
    if errors:
        print("FLAG LINT ERRORS:")
        for e in errors:
            print(" -", e)
        return 1
    print(f"flags clean: {len(F._DEFS)} declared "
          f"({len(F._COMPAT_ONLY)} compat-only), every reference "
          f"declared and every non-compat flag referenced")
    return 0


if __name__ == "__main__":
    sys.exit(main())
