#!/usr/bin/env python
"""Metric-name lint (the FLAGS-lint idiom applied to telemetry): every
metric registered in ``observability.default_registry()`` — native
families AND collector-declared ones — must be

- snake_case (``[a-z][a-z0-9_]*``),
- unique (the registry enforces this at registration; the lint
  re-checks so a poisoned catalog list is caught in tests),
- unit-suffixed with one of ``observability.metrics.UNIT_SUFFIXES``
  (``_total``/``_ms``/``_bytes``/``_ratio``/``_state``/``_count``/
  ``_value``),
- present in the README "Observability" metric catalog table (a metric
  nobody documented is a metric nobody will find in a dashboard).

Usage: python tools/lint_metrics.py        (exit 1 on any finding)
Also runs as a tier-1 test (tests/test_tools_gates.py).
"""
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

README = os.path.join(REPO, "README.md")

_SNAKE = re.compile(r"^[a-z][a-z0-9_]*$")


def check(names, readme_text, suffixes=None):
    """-> list of error strings (empty = clean)."""
    if suffixes is None:
        from paddle_tpu.observability.metrics import UNIT_SUFFIXES
        suffixes = UNIT_SUFFIXES
    errors = []
    seen = set()
    for name in names:
        if name in seen:
            errors.append(f"metric {name!r} registered more than once")
        seen.add(name)
        if not _SNAKE.match(name):
            errors.append(f"metric {name!r} is not snake_case")
        if not name.endswith(tuple(suffixes)):
            errors.append(
                f"metric {name!r} lacks a unit suffix "
                f"({', '.join(suffixes)})")
        # catalog rows render the name in backticks: `name`
        if f"`{name}`" not in readme_text:
            errors.append(
                f"metric {name!r} is missing from the README "
                f"\"Observability\" metric catalog")
    return errors


def registered_names():
    """Import every metric-bearing subsystem, then read the registry's
    catalog (native + collector-declared families)."""
    import paddle_tpu  # noqa: F401 — executor/passes/resilience register
    import paddle_tpu.serving  # noqa: F401 — ServingStats bridge
    import paddle_tpu.train  # noqa: F401 — train supervisor families
    import paddle_tpu.models.generation  # noqa: F401 — decode stages
    from paddle_tpu.observability import default_registry
    return sorted(default_registry().catalog())


def main():
    names = registered_names()
    with open(README, encoding="utf-8") as f:
        readme = f.read()
    errors = check(names, readme)
    if errors:
        print("METRIC LINT ERRORS:")
        for e in errors:
            print(" -", e)
        return 1
    print(f"metrics clean: {len(names)} registered names, all "
          f"snake_case, unit-suffixed and documented in the README "
          f"catalog")
    return 0


if __name__ == "__main__":
    sys.exit(main())
