#!/usr/bin/env python
"""Diff two bench result files and gate on regressions: the bench
trajectory becomes a CHECKABLE artifact instead of a table a human
eyeballs.

Inputs are either raw ``bench.py`` output (JSON lines; the LAST line is
the summary) or a driver wrapper (``{"tail": "<json lines>"}``) — both
``BENCH_rNN.json`` and ``MULTICHIP_rNN.json`` parse, since the
multichip dryrun now ends with a structured ``{"meshes": {...}}``
summary line. Keys are dotted paths into the summary object, e.g.
``value``, ``configs.widedeep.value``, or for multichip records
``meshes.dp_tp_sp.comm_bound_ratio`` /
``meshes.ep_dp.ledger.totals.wire_bytes`` (ledger keys avoid dots by
construction: ``all-reduce@dp``).

By default a key is HIGHER-IS-BETTER (throughput); prefix it with ``-``
for lower-is-better (latency/ms):

    python tools/bench_compare.py BENCH_r05.json BENCH_r06.json \\
        --key value --key configs.widedeep.value \\
        --key=-configs.chaos.value --max-regress-pct 10

(lower-is-better keys need the ``--key=-...`` form — argparse treats a
bare leading ``-`` as an option.)

Exit 1 when any named key regressed by more than ``--max-regress-pct``
(missing/null keys are reported but only fail under ``--strict``).
"""
import argparse
import json
import sys


def load_summary(path):
    """The LAST parseable JSON object of a bench output file (or of the
    BENCH_rNN wrapper's "tail")."""
    with open(path) as f:
        text = f.read()
    try:
        doc = json.loads(text)
        if isinstance(doc, dict) and "tail" in doc \
                and isinstance(doc["tail"], str):
            text = doc["tail"]
        elif isinstance(doc, dict):
            return doc                       # already one summary object
    except ValueError:
        pass
    last = None
    for line in text.splitlines():
        line = line.strip()
        if not line.startswith("{"):
            continue
        try:
            last = json.loads(line)
        except ValueError:
            continue
    if last is None:
        raise ValueError(f"{path}: no JSON summary line found")
    return last


def lookup(doc, dotted):
    cur = doc
    for part in dotted.split("."):
        if not isinstance(cur, dict) or part not in cur:
            return None
        cur = cur[part]
    return cur


def compare(old, new, keys, max_regress_pct):
    """-> (regressions, notes): ``regressions`` are gate failures,
    ``notes`` informational lines (improvements, missing keys)."""
    regressions, notes = [], []
    for raw in keys:
        lower_better = raw.startswith("-")
        key = raw[1:] if lower_better else raw
        ov, nv = lookup(old, key), lookup(new, key)
        if not isinstance(ov, (int, float)) \
                or not isinstance(nv, (int, float)):
            notes.append(f"SKIP {key}: old={ov!r} new={nv!r} "
                         f"(non-numeric/missing)")
            continue
        if ov == 0:
            notes.append(f"SKIP {key}: old value is 0")
            continue
        delta_pct = (nv - ov) / abs(ov) * 100.0
        regressed = (-delta_pct if not lower_better else delta_pct) \
            > max_regress_pct
        line = (f"{key}: {ov:g} -> {nv:g} ({delta_pct:+.2f}%"
                f"{', lower is better' if lower_better else ''})")
        if regressed:
            regressions.append(f"REGRESSION {line} exceeds "
                               f"{max_regress_pct:g}%")
        else:
            notes.append(f"ok {line}")
    return regressions, notes


def main(argv=None):
    ap = argparse.ArgumentParser(
        description="Gate bench results against a prior run")
    ap.add_argument("old", help="baseline bench file (raw output or "
                                "BENCH_rNN.json wrapper)")
    ap.add_argument("new", help="candidate bench file")
    ap.add_argument("--key", action="append", default=[],
                    help="dotted path into the summary (repeatable); "
                         "prefix '-' for lower-is-better")
    ap.add_argument("--max-regress-pct", type=float, default=10.0)
    ap.add_argument("--strict", action="store_true",
                    help="missing/non-numeric keys also fail the gate")
    args = ap.parse_args(argv)
    keys = args.key or ["value"]
    old = load_summary(args.old)
    new = load_summary(args.new)
    regressions, notes = compare(old, new, keys, args.max_regress_pct)
    for n in notes:
        print(n)
    for r in regressions:
        print(r, file=sys.stderr)
    if args.strict and any(n.startswith("SKIP") for n in notes):
        print("STRICT: skipped keys fail the gate", file=sys.stderr)
        return 1
    return 1 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
