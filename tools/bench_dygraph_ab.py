"""Dygraph-vs-static A/B: BERT-base, fp32, batch 64, seq 128 — the only
variable is the execution path (Executor.run over the static program vs
dygraph.jit_step whole-step capture of models/bert_dygraph.py, the same
math). Measures steady-state step time (best of 3 windows) and XLA
cost_analysis of both executables; results table in BENCHMARKS.md
"Dygraph-vs-static A/B". Run on the TPU host: python tools/bench_dygraph_ab.py
"""
import os
import sys, time
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
import numpy as np
import jax
jax.config.update("jax_default_prng_impl", "rbg")
import paddle_tpu as fluid
from paddle_tpu import dygraph
from paddle_tpu.models import bert, bert_dygraph

cfg = bert.BertConfig.base()
batch, seq, preds = 64, 128, 20
rng = np.random.default_rng(0)
pool = [bert.random_batch(cfg, batch, seq, preds, rng=rng) for _ in range(2)]
N = 20

# ---------------- static path ----------------
main, startup = fluid.Program(), fluid.Program()
with fluid.program_guard(main, startup):
    out = bert.bert_pretrain(cfg, batch, seq, preds)
    fluid.optimizer.Adam(1e-4).minimize(out["loss"])
exe = fluid.Executor()
scope = fluid.Scope()
staged = [{k: jax.device_put(v) for k, v in b.items()} for b in pool]
with fluid.scope_guard(scope):
    exe.run(startup)
    for i in range(3):
        exe.run(main, feed=staged[i % 2], fetch_list=[out["loss"].name])
    best = 1e9
    for _r in range(3):
        t0 = time.perf_counter()
        for i in range(N):
            exe.run(main, feed=staged[i % 2], fetch_list=[])
        l, = exe.run(main, feed=staged[0], fetch_list=[out["loss"].name])
        float(np.asarray(l).reshape(()))
        best = min(best, (time.perf_counter() - t0) / (N + 1))
    import bench
    cost_s = bench._step_cost(exe, main)
print(f"static:  {best*1e3:8.2f} ms/step  {batch/best:8.1f} samples/s  "
      f"flops {cost_s['flops']/1e9:.1f}G bytes {cost_s['bytes']/1e9:.1f}G")
t_static = best

# ---------------- dygraph path ----------------
with dygraph.guard():
    model = bert_dygraph.BertPretrainDy(cfg)
    opt = dygraph_opt = fluid.optimizer.Adam(1e-4,
                                             parameter_list=model.parameters())
    @dygraph.jit_step
    def step(*args):
        loss = model(*args)
        loss.backward()
        opt.minimize(loss)
        model.clear_gradients()
        return loss

    keys = ("src_ids", "sent_ids", "pos_ids", "input_mask",
            "mask_pos", "mask_label", "labels")
    dstaged = [[jax.device_put(b[k]) for k in keys] for b in pool]
    # eager warmup small batch
    small = [v[:4] if getattr(v, "ndim", 0) else v
             for v in [pool[0][k] for k in keys]]
    small[4] = pool[0]["mask_pos"][:4 * preds]
    small[5] = pool[0]["mask_label"][:4 * preds]
    step(*[dygraph.to_variable(np.asarray(v)) for v in small])
    vb = [dygraph.to_variable(v) for v in dstaged[0]]
    vb2 = [dygraph.to_variable(v) for v in dstaged[1]]
    step(*vb)                       # capture at full batch
    float(step(*vb2).numpy().reshape(-1)[0])
    best = 1e9
    for _r in range(3):
        t0 = time.perf_counter()
        last = None
        for i in range(N):
            last = step(*(vb if i % 2 == 0 else vb2))
        float(last.numpy().reshape(-1)[0])
        best = min(best, (time.perf_counter() - t0) / N)
    import bench
    cost_d = bench._jit_step_cost(step, dstaged[0])
print(f"dygraph: {best*1e3:8.2f} ms/step  {batch/best:8.1f} samples/s  "
      + (f"flops {cost_d['flops']/1e9:.1f}G bytes {cost_d['bytes']/1e9:.1f}G"
         if cost_d else "no cost"))
print(f"ratio dygraph/static samples/s: {t_static/best:.3f}")
