#!/usr/bin/env python
"""API-freeze diff gate (reference tools/diff_api.py): deleting or
changing a public API line is an ERROR; additions are allowed (and
should be re-baselined deliberately).

Usage:
    python tools/print_signatures.py paddle_tpu > /tmp/new_api.txt
    python tools/diff_api.py tools/api_signatures.txt /tmp/new_api.txt
Exit code 1 on any deletion/change.
"""
import difflib
import sys


def diff(origin_lines, new_lines):
    """Return the list of forbidden (deleted/changed) diff lines."""
    result = difflib.Differ().compare(origin_lines, new_lines)
    return [d for d in result if d and d[0] in ("-", "?")]


def main():
    with open(sys.argv[1]) as f:
        origin = f.read().splitlines()
    with open(sys.argv[2]) as f:
        new = f.read().splitlines()
    bad = diff(origin, new)
    if bad:
        print("API CHANGE OR DELETION IS NOT ALLOWED:")
        for d in bad:
            print(d)
        print("(additions are fine — re-baseline with "
              "print_signatures.py if this change is deliberate)")
        sys.exit(1)
    print("API surface unchanged (additions only)")


if __name__ == "__main__":
    main()
