#!/usr/bin/env Rscript
# R inference client for paddle_tpu via reticulate (capability parity
# with the reference R example, /root/reference/r/example/mobilenet.r,
# which drives paddle.fluid.core the same way).
#
# Usage: Rscript linear.r <model_dir>
#   model_dir: a fluid.io.save_inference_model output directory.

library(reticulate)

np <- import("numpy")
inference <- import("paddle_tpu.inference")

args <- commandArgs(trailingOnly = TRUE)
model_dir <- ifelse(length(args) >= 1, args[1], "data/model")

config <- inference$AnalysisConfig(model_dir)
config$switch_use_feed_fetch_ops(FALSE)
config$switch_specify_input_names(TRUE)

predictor <- inference$create_paddle_predictor(config)

input_names <- predictor$get_input_names()
input_tensor <- predictor$get_input_handle(input_names[[1]])

x <- np$ones(c(4L, 16L), dtype = "float32")
input_tensor$copy_from_cpu(x)

predictor$run()

output_names <- predictor$get_output_names()
output_tensor <- predictor$get_output_handle(output_names[[1]])
result <- output_tensor$copy_to_cpu()

cat("output shape:", paste(dim(result), collapse = "x"), "\n")
cat("output[1,1]:", result[1, 1], "\n")
