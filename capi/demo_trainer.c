/* End-to-end training from C — no Python in the loop.
 *
 * The paddle_tpu analog of the reference's
 * /root/reference/paddle/fluid/train/demo/demo_trainer.cc: load the
 * (main, startup) program pair a Python build script saved with
 * paddle_tpu.capi_train.save_train_model, then feed synthetic linear
 * data and step the whole compiled train program (fwd + bwd + SGD),
 * printing the first and last loss.
 *
 * Usage: demo_trainer <model_dir> <steps>
 * Exit code 0 iff the final loss improved on the first by 10x.
 */
#include <stdio.h>
#include <stdlib.h>

#include "paddle_c_api.h"

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <model_dir> <steps>\n", argv[0]);
    return 2;
  }
  const int steps = atoi(argv[2]);

  PD_Trainer* t = PD_NewTrainer(argv[1]);
  if (t == NULL) {
    fprintf(stderr, "PD_NewTrainer: %s\n", PD_GetLastError());
    return 1;
  }

  /* y = x @ [2, -3.4] + 4.2 + noise-free target: 64 samples, 2 feats */
  enum { N = 64, F = 2 };
  static float x[N * F], y[N];
  unsigned rng = 12345;
  for (int i = 0; i < N; ++i) {
    for (int f = 0; f < F; ++f) {
      rng = rng * 1103515245u + 12345u;
      x[i * F + f] = ((rng >> 16) % 2000) / 1000.0f - 1.0f;
    }
    y[i] = 2.0f * x[i * F] - 3.4f * x[i * F + 1] + 4.2f;
  }
  const int xshape[2] = {N, F};
  const int yshape[2] = {N, 1};

  float first = 0.0f, loss = 0.0f;
  for (int s = 0; s < steps; ++s) {
    if (PD_TrainerFeedFloat(t, "x", x, xshape, 2) != 0 ||
        PD_TrainerFeedFloat(t, "y", y, yshape, 2) != 0) {
      fprintf(stderr, "feed: %s\n", PD_GetLastError());
      PD_DeleteTrainer(t);
      return 1;
    }
    if (PD_TrainerRunStep(t, "loss", &loss, 1) < 0) {
      fprintf(stderr, "step: %s\n", PD_GetLastError());
      PD_DeleteTrainer(t);
      return 1;
    }
    if (s == 0) first = loss;
  }
  printf("first_loss=%g last_loss=%g\n", first, loss);

  PD_DeleteTrainer(t);
  return loss < first / 10.0f ? 0 : 3;
}
