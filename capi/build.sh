#!/bin/sh
# Build libpaddle_tpu_capi.so (see paddle_c_api.h).
set -e
cd "$(dirname "$0")"
g++ -O2 -shared -fPIC -std=c++17 paddle_c_api.cc \
    $(python3-config --includes) \
    $(python3-config --ldflags --embed) \
    -o libpaddle_tpu_capi.so
echo "built $(pwd)/libpaddle_tpu_capi.so"
