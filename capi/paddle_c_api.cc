/* C API implementation: embeds the Python runtime and drives the
 * AnalysisPredictor (paddle_tpu/inference). See paddle_c_api.h.
 *
 * Mirrors the reference's C API layering (inference/capi/c_api.cc fronts
 * the C++ AnalysisPredictor): a thin native shim over the real predictor,
 * holding the GIL only around calls. Buffers cross the boundary through
 * numpy arrays built from memoryviews — no serialization.
 */
#include "paddle_c_api.h"

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

std::string g_last_error;
bool g_inited = false;

void set_error_from_python() {
  PyObject *type = nullptr, *value = nullptr, *tb = nullptr;
  PyErr_Fetch(&type, &value, &tb);
  PyErr_NormalizeException(&type, &value, &tb);
  if (value != nullptr) {
    PyObject* s = PyObject_Str(value);
    if (s != nullptr) {
      g_last_error = PyUnicode_AsUTF8(s);
      Py_DECREF(s);
    }
  } else {
    g_last_error = "unknown python error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

struct GIL {
  PyGILState_STATE state;
  GIL() : state(PyGILState_Ensure()) {}
  ~GIL() { PyGILState_Release(this->state); }
};

}  // namespace

struct PD_Predictor {
  PyObject* predictor;                  // paddle_tpu AnalysisPredictor
  std::vector<std::string> input_names;
  std::vector<std::string> output_names;
};

extern "C" {

int PD_Init(void) {
  if (g_inited) return 0;
  bool we_initialized = false;
  if (!Py_IsInitialized()) {
    Py_InitializeEx(0);
    we_initialized = true;
  }
  bool import_ok;
  {
    GIL gil;
    PyObject* mod = PyImport_ImportModule("paddle_tpu");
    import_ok = mod != nullptr;
    if (!import_ok) set_error_from_python();
    Py_XDECREF(mod);
  }
  if (we_initialized) {
    // Py_InitializeEx leaves this thread holding the GIL; release it so
    // other host threads' PyGILState_Ensure calls can acquire it —
    // including after a failed import (the error must stay reportable,
    // not turn into a cross-thread hang)
    PyEval_SaveThread();
  }
  if (!import_ok) return 1;
  g_inited = true;
  return 0;
}

const char* PD_GetLastError(void) { return g_last_error.c_str(); }

PD_Predictor* PD_NewPredictor(const char* model_dir) {
  if (PD_Init() != 0) return nullptr;
  GIL gil;
  PyObject* inf = PyImport_ImportModule("paddle_tpu.inference");
  if (inf == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PyObject* cfg = PyObject_CallMethod(inf, "AnalysisConfig", "s", model_dir);
  PyObject* pred = cfg != nullptr
      ? PyObject_CallMethod(inf, "create_paddle_predictor", "O", cfg)
      : nullptr;
  Py_XDECREF(cfg);
  Py_DECREF(inf);
  if (pred == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PD_Predictor* out = new PD_Predictor();
  out->predictor = pred;
  for (const char* which : {"get_input_names", "get_output_names"}) {
    PyObject* names = PyObject_CallMethod(pred, which, nullptr);
    if (names == nullptr) {
      set_error_from_python();
      Py_DECREF(pred);
      delete out;
      return nullptr;
    }
    auto& dst = std::strcmp(which, "get_input_names") == 0
        ? out->input_names : out->output_names;
    for (Py_ssize_t i = 0; i < PyList_Size(names); ++i) {
      dst.emplace_back(PyUnicode_AsUTF8(PyList_GetItem(names, i)));
    }
    Py_DECREF(names);
  }
  return out;
}

int PD_GetInputNum(PD_Predictor* p) {
  return static_cast<int>(p->input_names.size());
}
int PD_GetOutputNum(PD_Predictor* p) {
  return static_cast<int>(p->output_names.size());
}
const char* PD_GetInputName(PD_Predictor* p, int i) {
  return p->input_names[i].c_str();
}
const char* PD_GetOutputName(PD_Predictor* p, int i) {
  return p->output_names[i].c_str();
}

namespace {

int set_input(PD_Predictor* p, int i, const void* data, size_t itemsize,
              const char* np_dtype, const int* shape, int ndim) {
  GIL gil;
  long long numel = 1;
  for (int d = 0; d < ndim; ++d) numel *= shape[d];
  PyObject* np = PyImport_ImportModule("numpy");
  if (np == nullptr) { set_error_from_python(); return 1; }
  PyObject* mem = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)),
      numel * static_cast<long long>(itemsize), PyBUF_READ);
  PyObject* flat = mem != nullptr
      ? PyObject_CallMethod(np, "frombuffer", "Os", mem, np_dtype)
      : nullptr;
  PyObject* shp = PyTuple_New(ndim);
  for (int d = 0; d < ndim; ++d) {
    PyTuple_SetItem(shp, d, PyLong_FromLong(shape[d]));
  }
  PyObject* view_arr = flat != nullptr
      ? PyObject_CallMethod(flat, "reshape", "O", shp) : nullptr;
  // own the data: the memoryview aliases the CALLER's buffer, which may
  // be freed or reused before PD_PredictorRun
  PyObject* arr = view_arr != nullptr
      ? PyObject_CallMethod(view_arr, "copy", nullptr) : nullptr;
  Py_XDECREF(view_arr);
  Py_XDECREF(shp);
  Py_XDECREF(flat);
  Py_XDECREF(mem);
  Py_DECREF(np);
  if (arr == nullptr) { set_error_from_python(); return 1; }
  PyObject* handle = PyObject_CallMethod(
      p->predictor, "get_input_handle", "s", p->input_names[i].c_str());
  PyObject* ok = handle != nullptr
      ? PyObject_CallMethod(handle, "copy_from_cpu", "O", arr) : nullptr;
  Py_XDECREF(ok);
  Py_XDECREF(handle);
  Py_DECREF(arr);
  if (ok == nullptr) { set_error_from_python(); return 1; }
  return 0;
}

}  // namespace

int PD_SetInputFloat(PD_Predictor* p, int i, const float* data,
                     const int* shape, int ndim) {
  return set_input(p, i, data, sizeof(float), "float32", shape, ndim);
}

int PD_SetInputInt64(PD_Predictor* p, int i, const long long* data,
                     const int* shape, int ndim) {
  return set_input(p, i, data, sizeof(long long), "int64", shape, ndim);
}

int PD_PredictorRun(PD_Predictor* p) {
  GIL gil;
  PyObject* r = PyObject_CallMethod(p->predictor, "run", nullptr);
  if (r == nullptr) {
    set_error_from_python();
    return 1;
  }
  Py_DECREF(r);
  return 0;
}

long long PD_GetOutputFloat(PD_Predictor* p, int i, float* buf,
                            long long buf_len, int* shape, int* ndim_out) {
  GIL gil;
  PyObject* handle = PyObject_CallMethod(
      p->predictor, "get_output_handle", "s", p->output_names[i].c_str());
  PyObject* arr = handle != nullptr
      ? PyObject_CallMethod(handle, "copy_to_cpu", nullptr) : nullptr;
  Py_XDECREF(handle);
  if (arr == nullptr) { set_error_from_python(); return -1; }
  PyObject* np = PyImport_ImportModule("numpy");
  PyObject* f32 = PyObject_CallMethod(np, "ascontiguousarray", "Os", arr,
                                      "float32");
  Py_DECREF(np);
  Py_DECREF(arr);
  if (f32 == nullptr) { set_error_from_python(); return -1; }
  PyObject* shp = PyObject_GetAttrString(f32, "shape");
  int nd = static_cast<int>(PyTuple_Size(shp));
  long long numel = 1;
  for (int d = 0; d < nd; ++d) {
    long dim = PyLong_AsLong(PyTuple_GetItem(shp, d));
    if (d < 8) shape[d] = static_cast<int>(dim);
    numel *= dim;
  }
  *ndim_out = nd;
  Py_DECREF(shp);
  Py_buffer view;
  if (PyObject_GetBuffer(f32, &view, PyBUF_CONTIG_RO) != 0) {
    set_error_from_python();
    Py_DECREF(f32);
    return -1;
  }
  long long ncopy = numel < buf_len ? numel : buf_len;
  if (ncopy > 0 && buf != nullptr) {
    // size-only probes pass buf=NULL/buf_len=0 (the Go client sizes
    // the slice first) — memcpy with a null dest is UB even at n=0
    std::memcpy(buf, view.buf, ncopy * sizeof(float));
  }
  PyBuffer_Release(&view);
  Py_DECREF(f32);
  return numel;
}

void PD_DeletePredictor(PD_Predictor* p) {
  if (p == nullptr) return;
  {
    GIL gil;
    Py_XDECREF(p->predictor);
  }
  delete p;
}

}  // extern "C"

/* ---- C-native training (see paddle_c_api.h): fronts
 * paddle_tpu.capi_train.CTrainerSession the same way PD_Predictor fronts
 * the AnalysisPredictor. ---- */

struct PD_Trainer {
  PyObject* session;  // paddle_tpu.capi_train.CTrainerSession
};

namespace {

/* Build an owned numpy array from a raw buffer (same contract as
 * set_input: the caller's buffer is copied, not aliased). */
PyObject* np_array_copy(const void* data, size_t itemsize,
                        const char* np_dtype, const int* shape, int ndim) {
  long long numel = 1;
  for (int d = 0; d < ndim; ++d) numel *= shape[d];
  PyObject* np = PyImport_ImportModule("numpy");
  if (np == nullptr) return nullptr;
  PyObject* mem = PyMemoryView_FromMemory(
      const_cast<char*>(static_cast<const char*>(data)),
      numel * static_cast<long long>(itemsize), PyBUF_READ);
  PyObject* flat = mem != nullptr
      ? PyObject_CallMethod(np, "frombuffer", "Os", mem, np_dtype)
      : nullptr;
  PyObject* shp = PyTuple_New(ndim);
  for (int d = 0; d < ndim; ++d) {
    PyTuple_SetItem(shp, d, PyLong_FromLong(shape[d]));
  }
  PyObject* view_arr = flat != nullptr
      ? PyObject_CallMethod(flat, "reshape", "O", shp) : nullptr;
  PyObject* arr = view_arr != nullptr
      ? PyObject_CallMethod(view_arr, "copy", nullptr) : nullptr;
  Py_XDECREF(view_arr);
  Py_XDECREF(shp);
  Py_XDECREF(flat);
  Py_XDECREF(mem);
  Py_DECREF(np);
  return arr;
}

int trainer_feed(PD_Trainer* t, const char* name, const void* data,
                 size_t itemsize, const char* np_dtype, const int* shape,
                 int ndim) {
  GIL gil;
  PyObject* arr = np_array_copy(data, itemsize, np_dtype, shape, ndim);
  if (arr == nullptr) { set_error_from_python(); return 1; }
  PyObject* ok = PyObject_CallMethod(t->session, "feed", "sO", name, arr);
  Py_DECREF(arr);
  if (ok == nullptr) { set_error_from_python(); return 1; }
  Py_DECREF(ok);
  return 0;
}

}  // namespace

extern "C" {

PD_Trainer* PD_NewTrainer(const char* model_dir) {
  if (PD_Init() != 0) return nullptr;
  GIL gil;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.capi_train");
  if (mod == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PyObject* sess =
      PyObject_CallMethod(mod, "CTrainerSession", "s", model_dir);
  Py_DECREF(mod);
  if (sess == nullptr) {
    set_error_from_python();
    return nullptr;
  }
  PD_Trainer* t = new PD_Trainer();
  t->session = sess;
  return t;
}

int PD_TrainerFeedFloat(PD_Trainer* t, const char* name, const float* data,
                        const int* shape, int ndim) {
  return trainer_feed(t, name, data, sizeof(float), "float32", shape, ndim);
}

int PD_TrainerFeedInt64(PD_Trainer* t, const char* name,
                        const long long* data, const int* shape, int ndim) {
  return trainer_feed(t, name, data, sizeof(long long), "int64", shape,
                      ndim);
}

long long PD_TrainerRunStep(PD_Trainer* t, const char* fetch_name,
                            float* buf, long long buf_len) {
  GIL gil;
  PyObject* arr =
      PyObject_CallMethod(t->session, "run_step", "s", fetch_name);
  if (arr == nullptr) { set_error_from_python(); return -1; }
  Py_buffer view;
  if (PyObject_GetBuffer(arr, &view, PyBUF_CONTIG_RO) != 0) {
    set_error_from_python();
    Py_DECREF(arr);
    return -1;
  }
  long long numel = static_cast<long long>(view.len / sizeof(float));
  long long ncopy = numel < buf_len ? numel : buf_len;
  if (ncopy > 0 && buf != nullptr) {
    std::memcpy(buf, view.buf, ncopy * sizeof(float));
  }
  PyBuffer_Release(&view);
  Py_DECREF(arr);
  return numel;
}

int PD_TrainerSaveParams(PD_Trainer* t, const char* model_path) {
  GIL gil;
  PyObject* ok =
      PyObject_CallMethod(t->session, "save_params", "s", model_path);
  if (ok == nullptr) { set_error_from_python(); return 1; }
  Py_DECREF(ok);
  return 0;
}

int PD_TrainerLoadParams(PD_Trainer* t, const char* model_path) {
  GIL gil;
  PyObject* ok =
      PyObject_CallMethod(t->session, "load_params", "s", model_path);
  if (ok == nullptr) { set_error_from_python(); return 1; }
  Py_DECREF(ok);
  return 0;
}

void PD_DeleteTrainer(PD_Trainer* t) {
  if (t == nullptr) return;
  {
    GIL gil;
    Py_XDECREF(t->session);
  }
  delete t;
}

}  // extern "C"
