/* C inference/train API for paddle_tpu.
 *
 * Capability parity with the reference's C API
 * (/root/reference/paddle/fluid/inference/capi/ — c_api.cc, pd_config.cc,
 * pd_predictor.cc) and the C++ train entry
 * (/root/reference/paddle/fluid/framework/c/c_api.cc, train/demo/).
 *
 * The reference's C API fronts its C++ AnalysisPredictor; this one fronts
 * the XLA-compiled predictor by embedding the Python runtime (the compute
 * path itself is native XLA code either way). Link with:
 *   g++ -shared -fPIC paddle_c_api.cc $(python3-config --includes) \
 *       $(python3-config --ldflags --embed) -o libpaddle_tpu_capi.so
 */
#ifndef PADDLE_TPU_C_API_H_
#define PADDLE_TPU_C_API_H_

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef struct PD_Predictor PD_Predictor;

/* Initialize the runtime (idempotent). Returns 0 on success. */
int PD_Init(void);

/* Load a saved inference model directory (save_inference_model output).
 * Returns NULL on failure; PD_GetLastError() describes it. */
PD_Predictor* PD_NewPredictor(const char* model_dir);

/* Number / names of feed inputs and fetch outputs. */
int PD_GetInputNum(PD_Predictor* pred);
int PD_GetOutputNum(PD_Predictor* pred);
const char* PD_GetInputName(PD_Predictor* pred, int i);
const char* PD_GetOutputName(PD_Predictor* pred, int i);

/* Set input i from a dense float32 buffer with `ndim` dims in `shape`. */
int PD_SetInputFloat(PD_Predictor* pred, int i, const float* data,
                     const int* shape, int ndim);
/* Same for int64 feeds (ids/labels). */
int PD_SetInputInt64(PD_Predictor* pred, int i, const long long* data,
                     const int* shape, int ndim);

/* Run the compiled model over the staged inputs. Returns 0 on success. */
int PD_PredictorRun(PD_Predictor* pred);

/* Read back output i as float32. `shape`/`ndim_out` receive the result
 * dims (shape must have room for 8 dims); returns the element count, and
 * copies min(element_count, buf_len) values into buf. */
long long PD_GetOutputFloat(PD_Predictor* pred, int i, float* buf,
                            long long buf_len, int* shape, int* ndim_out);

void PD_DeletePredictor(PD_Predictor* pred);

/* ---- C-native training (reference train/demo/demo_trainer.cc +
 * framework/c/c_api.cc): load a (main, startup) program pair saved by
 * paddle_tpu.capi_train.save_train_model, run startup, then drive the
 * train loop entirely from C. ---- */
typedef struct PD_Trainer PD_Trainer;

/* Load the saved train model dir and run its startup program.
 * Returns NULL on failure (PD_GetLastError). */
PD_Trainer* PD_NewTrainer(const char* model_dir);

/* Stage a feed tensor by variable name (copied; reusable buffer). */
int PD_TrainerFeedFloat(PD_Trainer* t, const char* name, const float* data,
                        const int* shape, int ndim);
int PD_TrainerFeedInt64(PD_Trainer* t, const char* name,
                        const long long* data, const int* shape, int ndim);

/* Run ONE training step (forward + backward + optimizer — the whole
 * compiled step) over the staged feeds and fetch `fetch_name` as
 * float32. Returns the element count (copies min(count, buf_len) into
 * buf), or -1 on failure. */
long long PD_TrainerRunStep(PD_Trainer* t, const char* fetch_name,
                            float* buf, long long buf_len);

/* Persist / restore the trained parameters (io.save/io.load layout). */
int PD_TrainerSaveParams(PD_Trainer* t, const char* model_path);
int PD_TrainerLoadParams(PD_Trainer* t, const char* model_path);

void PD_DeleteTrainer(PD_Trainer* t);

/* Last error message (thread-unsafe, valid until the next API call). */
const char* PD_GetLastError(void);

#ifdef __cplusplus
}
#endif
#endif /* PADDLE_TPU_C_API_H_ */
