/* C driver for the paddle_tpu C API (reference pattern:
 * paddle/fluid/train/demo/demo_trainer.cc and inference/capi usage):
 * load a saved inference model, feed a float32 batch, run, print stats.
 *
 *   ./demo <model_dir> <rows>
 * prints: "ok rows=<n> out_numel=<m> mean=<v>"
 */
#include <stdio.h>
#include <stdlib.h>

#include "paddle_c_api.h"

int main(int argc, char** argv) {
  if (argc < 3) {
    fprintf(stderr, "usage: %s <model_dir> <rows>\n", argv[0]);
    return 2;
  }
  const char* model_dir = argv[1];
  int rows = atoi(argv[2]);

  PD_Predictor* pred = PD_NewPredictor(model_dir);
  if (!pred) {
    fprintf(stderr, "PD_NewPredictor failed: %s\n", PD_GetLastError());
    return 1;
  }
  if (PD_GetInputNum(pred) != 1) {
    fprintf(stderr, "expected 1 input, got %d\n", PD_GetInputNum(pred));
    return 1;
  }
  int in_shape[2] = {rows, 8};
  float* x = (float*)malloc(sizeof(float) * rows * 8);
  for (int i = 0; i < rows * 8; ++i) {
    x[i] = (float)(i % 17) * 0.1f - 0.8f;
  }
  if (PD_SetInputFloat(pred, 0, x, in_shape, 2) != 0) {
    fprintf(stderr, "SetInput failed: %s\n", PD_GetLastError());
    return 1;
  }
  if (PD_PredictorRun(pred) != 0) {
    fprintf(stderr, "Run failed: %s\n", PD_GetLastError());
    return 1;
  }
  float out[4096];
  int shape[8];
  int ndim = 0;
  long long numel =
      PD_GetOutputFloat(pred, 0, out, 4096, shape, &ndim);
  if (numel < 0) {
    fprintf(stderr, "GetOutput failed: %s\n", PD_GetLastError());
    return 1;
  }
  long long counted = numel < 4096 ? numel : 4096;
  double mean = 0.0;
  for (long long i = 0; i < counted; ++i) mean += out[i];
  mean = counted > 0 ? mean / (double)counted : 0.0;
  printf("ok rows=%d out_numel=%lld ndim=%d mean=%.6f\n", rows, numel,
         ndim, mean);
  free(x);
  PD_DeletePredictor(pred);
  return 0;
}
